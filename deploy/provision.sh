#!/usr/bin/env bash
# Image provisioning — the Packer-analogue of the reference's conda bake
# (origin_repo/deploy/packer/ape_x_cpu.sh / ape_x_gpu.sh, invoked from the
# per-role packer JSONs).  Bakes a PINNED Python env at /opt/apex-env so
# fleet nodes boot into a known-good interpreter instead of resolving
# dependencies at startup (the reference's AMIs exist for the same reason:
# a 192-actor fleet cold-resolving pip deps is slow and version-skewed).
#
# One script, parametrized by accelerator (the reference keeps two copies):
#   provision.sh cpu   # actor / evaluator nodes (jax CPU wheel)
#   provision.sh tpu   # learner TPU VM (jax[tpu] + libtpu)
#
# Idempotent: a marker short-circuits re-runs, so the same script serves
# BOTH paths — baked into an image by deploy/packer/apex_images.pkr.hcl
# (CPU fleet), or run at first boot by the role bootstraps (TPU VM:
# GCP TPU VMs boot vendor runtime images selected via runtime_version and
# cannot boot custom Packer images, so the learner provisions on first
# startup and respawns hit the marker).
set -euo pipefail

ACCEL="${1:-cpu}"
ENV_DIR=/opt/apex-env
MARKER="$ENV_DIR/.provisioned-$ACCEL"

if [ -f "$MARKER" ]; then
  echo "provision: $MARKER present, env already baked"
  exit 0
fi

export DEBIAN_FRONTEND=noninteractive
apt-get update
# build-essential: the native shm ring (apex_tpu/native/shm_ring.cpp)
# compiles on demand at first import
apt-get install -y python3-venv python3-dev build-essential git tmux htop

python3 -m venv "$ENV_DIR"
"$ENV_DIR/bin/pip" install --upgrade pip

# Core numerics are PINNED — these decide numerical behavior and the
# learner/actor wire compatibility; env/comms extras float with floors
# (they only wrap IO).  Versions match the tested image.
if [ "$ACCEL" = "tpu" ]; then
  "$ENV_DIR/bin/pip" install "jax[tpu]==0.9.0" \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
else
  "$ENV_DIR/bin/pip" install "jax==0.9.0"
fi
"$ENV_DIR/bin/pip" install \
  "flax==0.12.3" "optax==0.2.6" "numpy==2.0.2" "pyzmq==27.1.0" \
  "orbax-checkpoint" "chex" "einops" "msgpack" "tensorboardX" \
  "tensorboard" "gymnasium>=1.0" "ale-py" "opencv-python-headless"

touch "$MARKER"
echo "provision: $ACCEL env baked at $ENV_DIR"
