#!/usr/bin/env bash
# Learner bootstrap (reference origin_repo/deploy/learner.sh): clone, install,
# launch the learner role in tmux.  Runs on the TPU VM; jax[tpu] drives the
# local slice as an n-chip dp mesh.
set -euo pipefail
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
pip install -e . 'jax[tpu]' pyzmq tensorboardX gymnasium "ale-py" opencv-python-headless

# --mesh-dp defaults to 0 = all local chips; the runtime counts them itself
tmux new -s learner -d "APEX_LOGDIR=/opt/apex-tpu/runs python -m apex_tpu.runtime \
  --role learner --env-id ${env_id} --n-actors ${n_actors} \
  --batch-size 512 --train-ratio 16 --min-train-ratio 2 \
  --checkpoint-dir /opt/apex-tpu/ckpts --barrier-timeout 1800 --verbose; read"
tmux new -s tensorboard -d "tensorboard --logdir /opt/apex-tpu/runs --host 0.0.0.0; read"
