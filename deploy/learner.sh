#!/usr/bin/env bash
# Learner bootstrap (reference origin_repo/deploy/learner.sh): clone, install,
# launch the learner role in tmux.  Runs on the TPU VM; jax[tpu] drives the
# local slice as an n-chip dp mesh.
set -euo pipefail
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# TPU VMs boot vendor runtime images (no custom Packer image possible),
# so the learner provisions the pinned env at FIRST boot; the idempotence
# marker (deploy/provision.sh) makes later respawns free.
[ -f /opt/apex-env/.provisioned-tpu ] || bash deploy/provision.sh tpu
/opt/apex-env/bin/pip install -e . --no-deps

# --mesh-dp defaults to 0 = all local chips; the runtime counts them
# itself — in EVERY mode since PR 17 (service batches shard over the
# mesh through the shard_map'd update; the fused plane shards lanes +
# pool partitions).  The one constraint is divisibility: batch 512
# divides any pow2 slice, checked loud at startup.
MESH_DP=0
tmux new -s learner -d "APEX_LOGDIR=/opt/apex-tpu/runs \
  APEX_TENANT=$${APEX_TENANT:-} \
  APEX_REPLAY_SHARDS=${replay_shards} REPLAY_IP=${replay_ip} \
  APEX_MESH_DP=$MESH_DP /opt/apex-env/bin/python -m apex_tpu.runtime \
  --role learner --env-id ${env_id} --n-actors ${n_actors} \
  --batch-size 512 --train-ratio 16 --min-train-ratio 2 \
  --checkpoint-dir /opt/apex-tpu/ckpts --barrier-timeout 1800 --verbose; read"
tmux new -s tensorboard -d "/opt/apex-env/bin/tensorboard --logdir /opt/apex-tpu/runs --host 0.0.0.0; read"
