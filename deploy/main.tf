# GCP topology for apex-tpu (re-design of origin_repo/deploy/deploy.tf):
# TPU-VM learner (replay dissolved into its HBM) + CPU actor fleet +
# evaluator.  Per-role startup scripts mirror the reference's tmux
# bootstraps (deploy/actor.sh etc.).

terraform {
  required_providers {
    google = { source = "hashicorp/google" }
  }
}

provider "google" {
  project = var.project
  region  = var.region
  zone    = var.zone
}

output "learner_ip" {
  value = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
}

# -- network ---------------------------------------------------------------
# The reference opens 51001-51003 (replay) and 52001-52002 (learner)
# (deploy.tf:64-126); without the replay server only the learner ports
# remain: 51001 chunk ingest, 52001 param PUB, 52002 barrier, 52003
# fleet status (`--role status` queries from any fleet node).

resource "google_compute_firewall" "apex_ports" {
  name    = "apex-tpu-ports"
  network = "default"

  allow {
    protocol = "tcp"
    ports    = ["51001", "52001", "52002", "52003", "6006"] # 6006: tensorboard
  }

  source_tags = ["apex-actor", "apex-evaluator"]
  target_tags = ["apex-learner"]
}

# -- learner (TPU VM) ------------------------------------------------------

resource "google_tpu_v2_vm" "learner" {
  name                = "apex-learner"
  zone                = var.zone
  runtime_version     = var.tpu_runtime_version
  accelerator_type    = var.tpu_accelerator_type

  metadata = {
    startup-script = templatefile("${path.module}/learner.sh", {
      repo_url = var.repo_url
      env_id   = var.env_id
      n_actors = var.actor_node_count * var.actors_per_node
    })
  }

  tags = ["apex-learner"]
}

# -- actor fleet -----------------------------------------------------------

resource "google_compute_instance" "actor" {
  count        = var.actor_node_count
  name         = "apex-actor-${count.index}"
  machine_type = var.actor_machine_type
  tags         = ["apex-actor"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/actor.sh", {
    repo_url        = var.repo_url
    env_id          = var.env_id
    node_id         = count.index
    actors_per_node = var.actors_per_node
    envs_per_actor  = var.envs_per_actor
    n_actors        = var.actor_node_count * var.actors_per_node
    learner_ip      = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
  })
}

# -- evaluator -------------------------------------------------------------

resource "google_compute_instance" "evaluator" {
  name         = "apex-evaluator"
  machine_type = var.evaluator_machine_type
  tags         = ["apex-evaluator"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/evaluator.sh", {
    repo_url   = var.repo_url
    env_id     = var.env_id
    learner_ip = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
  })
}
