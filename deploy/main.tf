# GCP topology for apex-tpu (re-design of origin_repo/deploy/deploy.tf):
# TPU-VM learner (replay dissolved into its HBM) + CPU actor fleet +
# evaluator.  Per-role startup scripts mirror the reference's tmux
# bootstraps (deploy/actor.sh etc.).

terraform {
  required_providers {
    google = { source = "hashicorp/google" }
  }
}

provider "google" {
  project = var.project
  region  = var.region
  zone    = var.zone
}

output "learner_ip" {
  value = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
}

# -- network ---------------------------------------------------------------
# The reference opens 51001-51003 (replay) and 52001-52002 (learner)
# (deploy.tf:64-126).  Learner ports: 51001 chunk ingest (also stats,
# heartbeats, and the actors' direct-ingest fallback when a replay shard
# dies), 52001 param PUB, 52002 barrier, 52003 fleet status (`--role
# status` queries from any fleet node).  Replay shards (replay_shards >
# 0) additionally bind 53001 + shard_id on the replay host — one ROUTER
# per shard carrying both the actors' hashed chunk streams and the
# learner's pull/priority traffic.

resource "google_compute_firewall" "apex_ports" {
  name    = "apex-tpu-ports"
  network = "default"

  allow {
    protocol = "tcp"
    ports    = ["51001", "52001", "52002", "52003", "6006"] # 6006: tensorboard
  }

  # apex-replay sources: shard heartbeats ride the learner's chunk port;
  # apex-infer additionally subscribes the param PUB (52001) and beats
  # on the chunk port like every role
  source_tags = ["apex-actor", "apex-evaluator", "apex-replay",
                 "apex-infer"]
  target_tags = ["apex-learner"]
}

resource "google_compute_firewall" "apex_infer_port" {
  name    = "apex-tpu-infer-port"
  network = "default"

  allow {
    protocol = "tcp"
    # infer_port .. +15: serving shard s binds 54001 + s (CommsConfig
    # .infer_port + APEX_INFER_SHARDS, apex_tpu/serving/shard.py; 16
    # shards per host is the supported ceiling, like replay).
    # Remote-policy actors connect their per-worker DEALERs to their
    # identity-hashed home shard; the serve-ctl controller's gate
    # commands ride the same ROUTERs.
    ports = ["54001-54016"]
  }

  source_tags = ["apex-actor", "apex-serve-ctl"]
  target_tags = ["apex-infer"]
}

resource "google_compute_firewall" "apex_replay_ports" {
  name    = "apex-tpu-replay-ports"
  network = "default"

  allow {
    protocol = "tcp"
    # replay_port_base .. +15: shard s binds 53001 + s (CommsConfig
    # .replay_port_base; 16 shards per host is the supported ceiling)
    ports    = ["53001-53016"]
  }

  # actors push hashed chunks; the learner pulls batches + pushes
  # priority write-backs
  source_tags = ["apex-actor", "apex-learner"]
  target_tags = ["apex-replay"]
}

# -- learner (TPU VM) ------------------------------------------------------

resource "google_tpu_v2_vm" "learner" {
  name                = "apex-learner"
  zone                = var.zone
  runtime_version     = var.tpu_runtime_version
  accelerator_type    = var.tpu_accelerator_type

  metadata = {
    startup-script = templatefile("${path.module}/learner.sh", {
      repo_url      = var.repo_url
      env_id        = var.env_id
      n_actors      = var.actor_node_count * var.actors_per_node
      replay_shards = var.replay_shards
      # the instance NAME, not a resource reference: the replay host's
      # startup script needs the learner's IP, so an IP reference here
      # would be a terraform cycle — GCP's internal DNS resolves the
      # name inside the VPC instead
      replay_ip = var.replay_shards > 0 ? "apex-replay" : "127.0.0.1"
    })
  }

  tags = ["apex-learner"]
}

# -- actor fleet -----------------------------------------------------------

resource "google_compute_instance" "actor" {
  count        = var.actor_node_count
  name         = "apex-actor-${count.index}"
  machine_type = var.actor_machine_type
  tags         = ["apex-actor"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/actor.sh", {
    repo_url        = var.repo_url
    env_id          = var.env_id
    node_id         = count.index
    actors_per_node = var.actors_per_node
    envs_per_actor  = var.envs_per_actor
    n_actors        = var.actor_node_count * var.actors_per_node
    learner_ip      = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
    replay_shards   = var.replay_shards
    replay_ip       = var.replay_shards > 0 ? "apex-replay" : "127.0.0.1"
    remote_policy   = var.remote_policy ? 1 : 0
    # instance NAME like replay_ip above: GCP internal DNS resolves it
    # inside the VPC, avoiding a terraform IP-reference cycle
    infer_ip        = var.remote_policy ? "apex-infer" : "127.0.0.1"
  })
}

# -- replay host (optional: replay_shards > 0) -----------------------------
# The reference's standalone replay server restored, sharded
# (apex_tpu/replay_service): one memory-heavy host runs N shard
# processes, each owning one FramePoolReplay segment tree.  Actors hash
# chunks to shards; the learner pulls pre-sampled batches round-robin.

resource "google_compute_instance" "replay" {
  count        = var.replay_shards > 0 ? 1 : 0
  name         = "apex-replay"
  machine_type = var.replay_machine_type
  tags         = ["apex-replay"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/replay.sh", {
    repo_url      = var.repo_url
    env_id        = var.env_id
    replay_shards = var.replay_shards
    learner_ip    = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
  })
}

# -- infer host (optional: remote_policy) ----------------------------------
# The centralized batched-inference plane (apex_tpu/infer_service): one
# host owns a policy copy and batches the whole actor fleet's half-group
# requests into scan-stacked device dispatches.  Point it at an
# accelerator machine type (or co-locate with the learner and set
# APEX_INFER_DEVICE_PARAMS=1) for the real batching win; actors always
# keep bit-identical local fallbacks, so losing this host degrades
# throughput, never correctness.

resource "google_compute_instance" "infer" {
  count        = var.remote_policy ? 1 : 0
  name         = "apex-infer"
  machine_type = var.infer_machine_type
  tags         = ["apex-infer"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/infer.sh", {
    repo_url   = var.repo_url
    env_id     = var.env_id
    learner_ip = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
  })
}

# -- evaluator -------------------------------------------------------------

resource "google_compute_instance" "evaluator" {
  name         = "apex-evaluator"
  machine_type = var.evaluator_machine_type
  tags         = ["apex-evaluator"]

  boot_disk {
    initialize_params {
      image = var.fleet_image
      size  = 50
    }
  }

  network_interface {
    network = "default"
    access_config {}
  }

  metadata_startup_script = templatefile("${path.module}/evaluator.sh", {
    repo_url   = var.repo_url
    env_id     = var.env_id
    learner_ip = google_tpu_v2_vm.learner.network_endpoints[0].ip_address
  })
}
