# Baked fleet images — the reference's Packer AMI flow
# (origin_repo/deploy/packer/ape_x_actor.json + ape_x_cpu.sh) re-designed
# for GCP: one googlecompute build bakes the pinned /opt/apex-env
# (deploy/provision.sh) into an image family the Terraform fleet boots
# from (variables.tf: fleet_image).
#
#   packer init  deploy/packer
#   packer build -var project=$PROJECT deploy/packer
#
# Only the CPU fleet (actors + evaluator) is baked: GCP TPU VMs boot
# vendor runtime images selected by runtime_version and cannot use custom
# images, so the learner runs provision.sh tpu at first boot instead
# (learner.sh; the idempotence marker makes respawns free).

packer {
  required_plugins {
    googlecompute = {
      version = ">= 1.1"
      source  = "github.com/hashicorp/googlecompute"
    }
  }
}

variable "project" {
  type = string
}

variable "zone" {
  type    = string
  default = "us-central2-b"
}

source "googlecompute" "apex_cpu" {
  project_id          = var.project
  zone                = var.zone
  source_image_family = "ubuntu-2204-lts"
  image_name          = "apex-tpu-cpu-{{timestamp}}"
  image_family        = "apex-tpu-cpu"
  machine_type        = "n2-standard-4"
  disk_size           = 50
  ssh_username        = "ubuntu"
}

build {
  sources = ["source.googlecompute.apex_cpu"]

  provisioner "file" {
    source      = "${path.root}/../provision.sh"
    destination = "/tmp/provision.sh"
  }

  provisioner "shell" {
    inline = ["sudo bash /tmp/provision.sh cpu"]
  }
}
