# Deployment knobs (reference: origin_repo/deploy/variables.tf +
# terraform.tfvars: region, instance types, 48 nodes x 4 actors).

variable "project" {
  type        = string
  description = "GCP project id"
}

variable "region" {
  type    = string
  default = "us-central2"
}

variable "zone" {
  type    = string
  default = "us-central2-b"
}

variable "tpu_accelerator_type" {
  type        = string
  default     = "v4-8"
  description = "Learner TPU slice (BASELINE.md north star: v4-8)"
}

variable "tpu_runtime_version" {
  type    = string
  default = "tpu-ubuntu2204-base"
}

variable "actor_node_count" {
  type        = number
  default     = 32
  description = "CPU actor nodes (reference: 48)"
}

variable "actors_per_node" {
  type        = number
  default     = 8
  description = "Actor processes per node (reference: 4; north star 32x8=256)"
}

variable "envs_per_actor" {
  type        = number
  default     = 1
  description = "Env slots per actor process behind one batched policy call; raise to multiply fleet frames/s without more processes (ladder spans n_actors * envs_per_actor)"
}

variable "fleet_image" {
  type        = string
  default     = "ubuntu-os-cloud/ubuntu-2204-lts"
  description = "Boot image for the CPU fleet (actors + evaluator). Point at the packer-baked family (deploy/packer: projects/<project>/global/images/family/apex-tpu-cpu) so nodes boot with /opt/apex-env pre-provisioned; the default stock Ubuntu provisions on first boot instead."
}

variable "actor_machine_type" {
  type    = string
  default = "n2-standard-8"
}

variable "replay_shards" {
  type        = number
  default     = 0
  description = "Sharded replay service (apex_tpu/replay_service): N > 0 runs prioritized replay as N standalone shard processes on a dedicated replay host (reference topology: the r5.4xlarge replay node); 0 keeps replay in the learner's HBM. Shard s binds replay_port_base + s (53001 + s)."
}

variable "replay_machine_type" {
  type        = string
  default     = "n2-highmem-8"
  description = "Replay host (reference: r5.4xlarge — replay is memory-bound: N shards x capacity frames resident)"
}

variable "remote_policy" {
  type        = bool
  default     = false
  description = "Centralized batched inference (apex_tpu/infer_service): true launches one infer host binding infer_port (54001) and makes every actor ship half-group observations to it instead of running the policy on its own CPU; actors keep bit-identical local fallbacks, so the host is a throughput upgrade, never a single point of failure."
}

variable "infer_machine_type" {
  type        = string
  default     = "n2-standard-16"
  description = "Infer host (compute-bound: the whole fleet's policy forwards batch here — use an accelerator machine type for the real win; the CPU default serves small fleets)"
}

variable "evaluator_machine_type" {
  type    = string
  default = "n2-standard-4"
}

variable "env_id" {
  type    = string
  default = "SeaquestNoFrameskip-v4"
}

variable "repo_url" {
  type        = string
  description = "Git URL of this framework, cloned by the bootstrap scripts"
}
