#!/usr/bin/env bash
# Evaluator bootstrap (reference origin_repo/deploy/evaluator.sh): greedy
# unclipped scoring streamed from the learner's param PUB.
set -euo pipefail
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
pip install -e . pyzmq tensorboardX gymnasium "ale-py" opencv-python-headless

tmux new -s evaluator -d \
  "JAX_PLATFORMS=cpu APEX_LOGDIR=/opt/apex-tpu/runs python -m apex_tpu.runtime \
   --role evaluator --env-id ${env_id} --learner-ip ${learner_ip} \
   --barrier-timeout 1800 --verbose; read"
tmux new -s tensorboard -d "tensorboard --logdir /opt/apex-tpu/runs --host 0.0.0.0; read"
