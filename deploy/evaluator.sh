#!/usr/bin/env bash
# Evaluator bootstrap (reference origin_repo/deploy/evaluator.sh): greedy
# unclipped scoring streamed from the learner's param PUB.
set -euo pipefail
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer) or first-boot provisioning — see actor.sh.
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# Supervisor loop mirrors deploy/actor.sh: crashed evaluators respawn
# (rejoining via the param stream once the startup barrier is gone);
# 10 consecutive short-lived (<60s) runs halt the respawns.
tmux new -s evaluator -d \
  "fails=0; \
   while true; do \
     start=\$(date +%s); \
     JAX_PLATFORMS=cpu APEX_LOGDIR=/opt/apex-tpu/runs /opt/apex-env/bin/python -m apex_tpu.runtime \
     --role evaluator --env-id ${env_id} --learner-ip ${learner_ip} \
     --barrier-timeout 1800 --verbose; \
     rc=\$?; \
     if [ \$(( \$(date +%s) - start )) -gt 60 ]; then fails=0; fi; \
     fails=\$(( fails + 1 )); \
     if [ \$fails -gt 10 ]; then echo 'crash loop; halting respawns'; break; fi; \
     echo \"evaluator exited rc=\$rc; respawn \$fails in 5s\"; sleep 5; \
   done; read"
tmux new -s tensorboard -d "/opt/apex-env/bin/tensorboard --logdir /opt/apex-tpu/runs --host 0.0.0.0; read"
