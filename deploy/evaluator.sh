#!/usr/bin/env bash
# Evaluator bootstrap (reference origin_repo/deploy/evaluator.sh): greedy
# unclipped scoring streamed from the learner's param PUB.
set -euo pipefail
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer) or first-boot provisioning — see actor.sh.
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# Host supervisor mirrors deploy/actor.sh (apex_tpu.fleet.supervise):
# rate-limited respawns with jittered backoff; the respawned evaluator
# rejoins via the park path's barrier-vs-param-stream race once the
# startup barrier is gone.
tmux new -s evaluator -d \
  "JAX_PLATFORMS=cpu APEX_LOGDIR=/opt/apex-tpu/runs \
   APEX_TENANT=$${APEX_TENANT:-} \
   /opt/apex-env/bin/python -m apex_tpu.fleet.supervise \
     --max-respawns 10 --window 600 --min-uptime 60 --backoff 5 -- \
     /opt/apex-env/bin/python -m apex_tpu.runtime \
     --role evaluator --env-id ${env_id} --learner-ip ${learner_ip} \
     --barrier-timeout 1800 --verbose; read"
tmux new -s tensorboard -d "/opt/apex-env/bin/tensorboard --logdir /opt/apex-tpu/runs --host 0.0.0.0; read"
