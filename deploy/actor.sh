#!/usr/bin/env bash
# Actor-node bootstrap (reference origin_repo/deploy/actor.sh:4-9): one tmux
# session per actor process, global ACTOR_ID = node_id * per_node + idx.
set -euo pipefail
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
pip install -e . pyzmq tensorboardX gymnasium "ale-py" opencv-python-headless

idx=0
while [ $idx -lt ${actors_per_node} ]; do
  ACTOR_ID=$(( ${node_id} * ${actors_per_node} + idx ))
  tmux new -s "actor-$ACTOR_ID" -d \
    "JAX_PLATFORMS=cpu APEX_ROLE=actor ACTOR_ID=$ACTOR_ID N_ACTORS=${n_actors} \
     N_ENVS_PER_ACTOR=${envs_per_actor} \
     LEARNER_IP=${learner_ip} python -m apex_tpu.runtime \
     --env-id ${env_id} --barrier-timeout 1800; read"
  idx=$(( idx + 1 ))
done
