#!/usr/bin/env bash
# Actor-node bootstrap (reference origin_repo/deploy/actor.sh:4-9): one tmux
# session per actor process, global ACTOR_ID = node_id * per_node + idx.
set -euo pipefail
# stock Ubuntu ships without git — the clone below needs it before the
# in-repo provision script (which installs everything else) is reachable
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer): /opt/apex-env already provisioned; a fresh
# VM provisions on first boot (idempotence marker makes respawns free).
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# Supervisor loop: a crashed actor is relaunched after a short backoff —
# the role's join path (runtime/roles.py:_join_fleet, transport.barrier_wait
# rejoin contract) lets the respawn pass the long-gone startup barrier by
# observing the param stream, and the learner's silent_peers report clears
# on its first chunk.  A child that keeps dying young (<60s uptime) stops
# being respawned after 10 consecutive short-lived runs.
idx=0
while [ $idx -lt ${actors_per_node} ]; do
  ACTOR_ID=$(( ${node_id} * ${actors_per_node} + idx ))
  tmux new -s "actor-$ACTOR_ID" -d \
    "fails=0; \
     while true; do \
       start=\$(date +%s); \
       JAX_PLATFORMS=cpu APEX_ROLE=actor ACTOR_ID=$ACTOR_ID N_ACTORS=${n_actors} \
       N_ENVS_PER_ACTOR=${envs_per_actor} \
       LEARNER_IP=${learner_ip} /opt/apex-env/bin/python -m apex_tpu.runtime \
       --env-id ${env_id} --barrier-timeout 1800; \
       rc=\$?; \
       if [ \$(( \$(date +%s) - start )) -gt 60 ]; then fails=0; fi; \
       fails=\$(( fails + 1 )); \
       if [ \$fails -gt 10 ]; then echo 'crash loop; halting respawns'; break; fi; \
       echo \"actor-$ACTOR_ID exited rc=\$rc; respawn \$fails in 5s\"; sleep 5; \
     done; read"
  idx=$(( idx + 1 ))
done
