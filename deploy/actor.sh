#!/usr/bin/env bash
# Actor-node bootstrap (reference origin_repo/deploy/actor.sh:4-9): one tmux
# session per actor process, global ACTOR_ID = node_id * per_node + idx.
set -euo pipefail
# stock Ubuntu ships without git — the clone below needs it before the
# in-repo provision script (which installs everything else) is reachable
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer): /opt/apex-env already provisioned; a fresh
# VM provisions on first boot (idempotence marker makes respawns free).
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# Host supervisor (apex_tpu.fleet.supervise): rate-limited, respawn-
# budgeted relaunch with jittered exponential backoff — the ActorPool
# respawn semantics applied to whole processes.  A crashed actor's
# respawn rejoins the running fleet through the role's own park path
# (runtime/roles.py adapters + fleet/park.py: the barrier-vs-param-stream
# race), and the learner's FleetRegistry reports the DEAD -> ALIVE
# transition; a child that keeps dying young exhausts the budget and the
# supervisor halts loudly instead of crash-looping.
idx=0
while [ $idx -lt ${actors_per_node} ]; do
  ACTOR_ID=$(( ${node_id} * ${actors_per_node} + idx ))
  tmux new -s "actor-$ACTOR_ID" -d \
    "JAX_PLATFORMS=cpu APEX_ROLE=actor ACTOR_ID=$ACTOR_ID N_ACTORS=${n_actors} \
     APEX_TENANT=$${APEX_TENANT:-} \
     N_ENVS_PER_ACTOR=${envs_per_actor} LEARNER_IP=${learner_ip} \
     APEX_REPLAY_SHARDS=${replay_shards} REPLAY_IP=${replay_ip} \
     APEX_REMOTE_POLICY=${remote_policy} APEX_INFER_IP=${infer_ip} \
     /opt/apex-env/bin/python -m apex_tpu.fleet.supervise \
       --max-respawns 10 --window 600 --min-uptime 60 --backoff 5 -- \
       /opt/apex-env/bin/python -m apex_tpu.runtime \
       --env-id ${env_id} --barrier-timeout 1800; read"
  idx=$(( idx + 1 ))
done
