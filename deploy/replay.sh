#!/usr/bin/env bash
# Replay-host bootstrap (apex_tpu/replay_service — the reference's
# standalone replay server restored, sharded): one tmux session per
# shard process.  Shard s binds replay_port_base + s (53001 + s) and
# heartbeats into the learner's chunk port, so the fleet registry runs
# its JOINING/ALIVE/SUSPECT/DEAD machine over shards for free.
set -euo pipefail
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer): /opt/apex-env already provisioned; a fresh
# VM provisions on first boot (idempotence marker makes respawns free).
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# Host supervisor (apex_tpu.fleet.supervise): a crashed shard respawns
# with its tree EMPTY — the actors that hash to it refill it (their
# chunks rerouted to the learner's direct ingest only while the port was
# dark), and the learner's registry reports the DEAD -> ALIVE
# transition.  A shard that keeps dying young exhausts the budget and
# the supervisor halts loudly instead of crash-looping.
s=0
while [ $s -lt ${replay_shards} ]; do
  tmux new -s "replay-$s" -d \
    "JAX_PLATFORMS=cpu APEX_ROLE=replay SHARD_ID=$s \
     APEX_TENANTS='$${APEX_TENANTS:-}' \
     APEX_REPLAY_SHARDS=${replay_shards} LEARNER_IP=${learner_ip} \
     /opt/apex-env/bin/python -m apex_tpu.fleet.supervise \
       --max-respawns 10 --window 600 --min-uptime 60 --backoff 5 -- \
       /opt/apex-env/bin/python -m apex_tpu.runtime \
       --env-id ${env_id} --shard-id $s; read"
  s=$(( s + 1 ))
done
