#!/usr/bin/env bash
# Infer-host bootstrap (apex_tpu/infer_service + apex_tpu/serving — the
# sharded batched policy tier for --remote-policy actors):
# APEX_INFER_SHARDS supervised processes, shard s binding 54001 + s,
# each serving its identity-hashed worker band.  Every server
# subscribes the learner's param PUB like any actor (no new publish
# cycle) and heartbeats into the learner's chunk port, so the fleet
# registry runs its state machine over each shard for free; a
# chaos-killed/crashed shard costs its band one APEX_INFER_WAIT each
# (local-policy fallback, bit-identical by the parity pin) and the
# supervised respawn gets its traffic back through the clients'
# re-probe.  Export APEX_SERVE_CTL=1 to co-locate the canary deployment
# controller (--role serve-ctl, apex_tpu/serving/deploy) on this host.
set -euo pipefail
command -v git >/dev/null || (apt-get update && apt-get install -y git)
cd /opt
git clone ${repo_url} apex-tpu || (cd apex-tpu && git pull)
cd apex-tpu
# Baked image (deploy/packer): /opt/apex-env already provisioned; a fresh
# VM provisions on first boot (idempotence marker makes respawns free).
[ -f /opt/apex-env/.provisioned-cpu ] || bash deploy/provision.sh cpu
/opt/apex-env/bin/pip install -e . --no-deps

# On a device-attached host drop JAX_PLATFORMS=cpu and export
# APEX_INFER_DEVICE_PARAMS=1 so subscribed params stay device-resident
# (the device-to-device copy path); the CPU default serves correctness
# and small fleets.
INFER_SHARDS="$${APEX_INFER_SHARDS:-1}"
for s in $(seq 0 $((INFER_SHARDS - 1))); do
  tmux new -s "infer-$s" -d \
    "JAX_PLATFORMS=cpu APEX_ROLE=infer LEARNER_IP=${learner_ip} \
     APEX_TENANTS='$${APEX_TENANTS:-}' \
     APEX_REMOTE_POLICY=1 APEX_INFER_SHARDS=$INFER_SHARDS \
     /opt/apex-env/bin/python -m apex_tpu.fleet.supervise \
       --max-respawns 10 --window 600 --min-uptime 60 --backoff 5 -- \
       /opt/apex-env/bin/python -m apex_tpu.runtime \
       --infer-shard-id $s --env-id ${env_id}; read"
done
if [ "$${APEX_SERVE_CTL:-0}" = "1" ]; then
  tmux new -s "serve-ctl" -d \
    "JAX_PLATFORMS=cpu APEX_ROLE=serve-ctl LEARNER_IP=${learner_ip} \
     APEX_REMOTE_POLICY=1 APEX_INFER_SHARDS=$INFER_SHARDS \
     /opt/apex-env/bin/python -m apex_tpu.fleet.supervise \
       --max-respawns 10 --window 600 --min-uptime 60 --backoff 5 -- \
       /opt/apex-env/bin/python -m apex_tpu.runtime \
       --env-id ${env_id}; read"
fi
