"""Host-side builder of self-contained frame chunks for the frame pool.

The actor-side counterpart of :class:`apex_tpu.replay.frame_pool.FramePoolReplay`:
consumes SINGLE frames straight from the un-stacked env (FrameStack moves to
device sample time), maintains the acting stack for the policy, runs the same
n-step window semantics as :class:`apex_tpu.replay.nstep.NStepAccumulator`
(full-window ``gamma**n`` bootstrap, ``discount=0`` terminated tails,
``gamma**k`` truncated tails bootstrapping from the final frame —
``memory.py:393-478`` with the truncation correction), and emits fixed-shape
chunks:

    frames   u8[Kf, D]   flattened frames, first ``n_frames`` rows real
    n_frames i32         rows the device frame cursor advances by
    n_trans  i32         rows the device transition cursor advances by
    action/reward/discount  [K]
    obs_ref/next_ref        i32[K, S]  chunk-relative, oldest frame first
    priorities              f32[K]

with initial priorities from the Q-values observed while acting
(``memory.py:451-464`` — no extra network pass).  Pad rows (beyond
``n_trans``/``n_frames``) repeat the last real row INCLUDING its priority:
the device redirects them onto the last real row's ring slot, where
identical duplicate writes are deterministic no-ops (see the frame_pool
module docstring).  Chunks always carry at least one transition — a flush
with frames but no transitions keeps only the carry frames and ships
nothing.

Episode stacks pad at the start by repeating the reset frame, exactly like
``FrameStack.reset`` (``wrappers.py:202-206``, reference ``wrapper.py:231-236``).
"""

from __future__ import annotations

from collections import deque

import numpy as np

# Extra frame rows per chunk beyond one per transition (episode carry +
# reset frames).  Shm slot sizing (training/apex.py) derives each chunk's
# Kf from this same constant — a chunk must fit one ring slot.
FRAME_MARGIN = 16


class FrameChunkBuilder:
    """One builder per env slot (like the per-actor BatchStorage)."""

    def __init__(self, n_steps: int, gamma: float, frame_stack: int,
                 frame_shape: tuple[int, ...],
                 chunk_transitions: int = 64,
                 frame_margin: int = FRAME_MARGIN,
                 frame_dtype=np.uint8,
                 extra_shapes: dict | None = None):
        self.n = n_steps
        self.gamma = gamma
        self.s = frame_stack
        self.frame_shape = tuple(frame_shape)
        self.frame_dtype = np.dtype(frame_dtype)
        self.frame_dim = int(np.prod(frame_shape))
        self.K = chunk_transitions
        self.Kf = chunk_transitions + frame_margin
        # per-transition float32 sidecars captured at the acting step and
        # emitted with the window HEAD (FramePoolReplay.extra_spec twin:
        # the AQL family ships its a_mu candidate set here)
        self.extra_shapes = dict(extra_shapes or {})

        # episode state
        self._window: deque = deque()   # (ep_idx, action, reward, q_values)
        self._ep_step = -1              # ep index of the newest frame
        # recent (ep_idx, frame) pairs, newest last — sized to cover the
        # widest span a flush carry can need: window head's stack start
        # (ep_step - window_len - (S-1)) through ep_step, window_len <= n+1.
        self._recent: deque = deque(maxlen=frame_stack + n_steps + 1)
        self._ep2chunk: dict[int, int] = {}

        # view-backed acting-stack mode (bind_acting_view): the stack the
        # policy reads is maintained in place inside a caller-owned buffer,
        # rebuilt each step from rotating refs to the last S frames
        self._acting_view: np.ndarray | None = None
        self._view_frames: list[np.ndarray] = []

        self._chunks: list[dict] = []
        self._reset_chunk()

    # -- view-backed acting stack ------------------------------------------

    def stacked_shape(self) -> tuple[int, ...]:
        """Shape of the policy's acting stack: S frames channel-concatenated
        on the last axis (matches :meth:`current_stack`)."""
        return self.frame_shape[:-1] + (self.s * self.frame_shape[-1],)

    def bind_acting_view(self, view: np.ndarray) -> None:
        """Maintain the acting stack IN PLACE inside ``view`` (typically one
        row of a vector family's preallocated ``[B, *stacked]`` buffer).
        After binding, :meth:`current_stack` returns ``view`` without
        copying: ``begin_episode`` fills all S positions with the reset
        frame and ``add_step`` rolls the channel window forward — no
        per-step concatenate, no per-step allocation.  Callers must treat
        the returned stack as read-only."""
        want = self.stacked_shape()
        if tuple(view.shape) != want or view.dtype != self.frame_dtype:
            raise ValueError(
                f"acting view must be {want} {self.frame_dtype}, got "
                f"{tuple(view.shape)} {view.dtype}")
        self._acting_view = view

    def _view_reset(self, frame: np.ndarray) -> None:
        f = np.asarray(frame, self.frame_dtype).reshape(self.frame_shape)
        self._view_frames = [f] * self.s
        self._view_write()

    def _view_push(self, frame: np.ndarray) -> None:
        f = np.asarray(frame, self.frame_dtype).reshape(self.frame_shape)
        self._view_frames = self._view_frames[1:] + [f]
        self._view_write()

    def _view_write(self) -> None:
        """Rewrite all S channel slots from the rotating frame refs.  S
        small strided writes beat the in-place channel shift ~6x: the
        overlapping ``v[..., :-c] = v[..., c:]`` move forces numpy through
        its overlap-safe buffered path."""
        v = self._acting_view
        c = self.frame_shape[-1]
        for j, f in enumerate(self._view_frames):
            v[..., j * c:(j + 1) * c] = f

    # -- chunk buffer ------------------------------------------------------

    def _reset_chunk(self) -> None:
        self._frames: list[np.ndarray] = []
        self._trans: dict[str, list] = {
            k: [] for k in ("action", "reward", "discount", "obs_ref",
                            "next_ref", "q0", "qn")}
        self._extra_rows: dict[str, list] = {
            name: [] for name in self.extra_shapes}

    def _register_frame(self, ep_idx: int, frame: np.ndarray) -> None:
        self._ep2chunk[ep_idx] = len(self._frames)
        self._frames.append(np.asarray(frame, self.frame_dtype).reshape(-1))

    def _maybe_flush_for_frames(self, incoming: int = 1) -> None:
        if len(self._frames) + incoming > self.Kf:
            self._flush()

    def _stack_refs(self, end: int) -> list[int]:
        """Chunk refs of the S-stack ending at episode frame ``end``,
        oldest first, clamped to the episode start (repeat frame 0)."""
        return [self._ep2chunk[max(end - i, 0)]
                for i in range(self.s - 1, -1, -1)]

    # -- episode protocol --------------------------------------------------

    def begin_episode(self, frame: np.ndarray) -> None:
        """Register the reset observation."""
        self._window.clear()
        self._ep_step = -1              # no active episode while flushing
        self._maybe_flush_for_frames()
        self._ep_step = 0
        self._recent.clear()
        self._recent.append((0, np.asarray(frame, self.frame_dtype)))
        self._ep2chunk = {}
        self._register_frame(0, frame)
        if self._acting_view is not None:
            self._view_reset(frame)

    def current_stack(self) -> np.ndarray:
        """The policy's input: last S frames (oldest first, channel concat),
        padded at episode start by repeating the reset frame."""
        assert self._ep_step >= 0, "begin_episode first"
        if self._acting_view is not None:
            return self._acting_view
        by_idx = dict(self._recent)
        frames = [by_idx[max(self._ep_step - i, 0)]
                  for i in range(self.s - 1, -1, -1)]
        return np.concatenate([f.reshape(self.frame_shape) for f in frames],
                              axis=-1)

    def add_step(self, action: int, reward: float, q_values: np.ndarray,
                 new_frame: np.ndarray, terminated: bool,
                 truncated: bool, extras: dict | None = None) -> None:
        """Record one env step: the policy acted on the stack ending at the
        current newest frame; ``new_frame`` is the observation the env
        returned (on truncation it IS the final observation to bootstrap
        from — no separate argument needed).  ``extras`` must carry one
        array per declared ``extra_shapes`` name; they ship with the
        transition whose acting step this is (the window head)."""
        assert self._ep_step >= 0, "begin_episode first"
        self._maybe_flush_for_frames()
        obs_idx = self._ep_step
        self._ep_step += 1
        self._recent.append((self._ep_step, np.asarray(new_frame, self.frame_dtype)))
        self._register_frame(self._ep_step, new_frame)
        if self._acting_view is not None:
            self._view_push(new_frame)
        ex = {name: np.asarray((extras or {})[name], np.float32)
              for name in self.extra_shapes}
        self._window.append((obs_idx, action, float(reward),
                             np.asarray(q_values, np.float32), ex))

        if len(self._window) == self.n + 1:
            self._emit_full()
            self._window.popleft()
        if terminated:
            while self._window:
                self._emit_tail(bootstrap=False)
                self._window.popleft()
        elif truncated:
            while self._window:
                self._emit_tail(bootstrap=True)
                self._window.popleft()
        if terminated or truncated:
            self._ep_step = -1

    # -- emission ----------------------------------------------------------

    def _emit_full(self) -> None:
        w = self._window
        i0 = w[0][0]
        ret = sum((self.gamma ** i) * w[i][2] for i in range(self.n))
        self._push(w[0], ret, next_end=i0 + self.n,
                   discount=self.gamma ** self.n, qn=w[self.n][3])

    def _emit_tail(self, bootstrap: bool) -> None:
        w = self._window
        i0, k = w[0][0], len(w)
        ret = sum((self.gamma ** i) * w[i][2] for i in range(k))
        # terminated: next stack is a masked placeholder (the obs stack);
        # truncated: stack ends at the final frame i0 + k.
        self._push(w[0], ret, next_end=(i0 + k) if bootstrap else i0,
                   discount=(self.gamma ** k) if bootstrap else 0.0,
                   qn=w[-1][3])

    def _push(self, head: tuple, ret: float, next_end: int, discount: float,
              qn: np.ndarray) -> None:
        obs_idx, action, _, q0, extras = head
        t = self._trans
        t["action"].append(action)
        t["reward"].append(np.float32(ret))
        t["discount"].append(np.float32(discount))
        t["obs_ref"].append(self._stack_refs(obs_idx))
        t["next_ref"].append(self._stack_refs(next_end))
        t["q0"].append(q0)
        t["qn"].append(qn)
        for name in self.extra_shapes:
            self._extra_rows[name].append(extras[name])
        if len(t["action"]) == self.K:
            self._flush()

    # -- flush / carry -----------------------------------------------------

    def _flush(self) -> None:
        """Materialize the chunk (if it has transitions — frame-only chunks
        are dropped, their useful frames survive via the carry), then carry
        the frames the live window and acting stack still need."""
        if self._trans["action"]:
            self._chunks.append(self._materialize())
        elif not self._frames:
            return
        self._reset_chunk()
        if self._ep_step >= 0:
            head = self._window[0][0] if self._window else self._ep_step
            oldest_needed = max(head - (self.s - 1), 0)
            by_idx = dict(self._recent)
            self._ep2chunk = {}
            for ep_idx in range(oldest_needed, self._ep_step + 1):
                self._register_frame(ep_idx, by_idx[ep_idx])

    def _materialize(self) -> dict:
        t = self._trans
        n_trans = len(t["action"])
        n_frames = len(self._frames)
        assert n_trans >= 1 and n_frames >= 1

        def pad_to(rows: list, count: int, dtype):
            arr = np.asarray(rows, dtype)
            if len(arr) < count:
                arr = np.concatenate(
                    [arr, np.repeat(arr[-1:], count - len(arr), axis=0)])
            return arr

        chunk = dict(
            frames=pad_to(self._frames, self.Kf, self.frame_dtype),
            n_frames=np.int32(n_frames),
            n_trans=np.int32(n_trans),
            action=pad_to(t["action"], self.K, np.int32),
            reward=pad_to(t["reward"], self.K, np.float32),
            discount=pad_to(t["discount"], self.K, np.float32),
            obs_ref=pad_to(t["obs_ref"], self.K, np.int32),
            next_ref=pad_to(t["next_ref"], self.K, np.int32),
        )
        if self.extra_shapes:
            chunk["extras"] = {
                name: pad_to(self._extra_rows[name], self.K, np.float32)
                for name in self.extra_shapes}
        q0 = np.stack(t["q0"])
        qn = np.stack(t["qn"])
        q_taken = q0[np.arange(n_trans), chunk["action"][:n_trans]]
        target = (chunk["reward"][:n_trans]
                  + chunk["discount"][:n_trans] * qn.max(1))
        real = np.abs(target - q_taken).astype(np.float32) + 1e-6
        chunk["priorities"] = pad_to(real, self.K, np.float32)
        return chunk

    # -- consumption -------------------------------------------------------

    def poll(self) -> list[dict]:
        """Completed chunks accumulated since the last poll."""
        out, self._chunks = self._chunks, []
        return out

    def force_flush(self) -> list[dict]:
        """Flush any partial chunk (padded) and return everything pending.
        The in-flight n-step window is NOT emitted — flush at episode end
        (or after a truncated step) for exact coverage."""
        self._flush()
        return self.poll()
