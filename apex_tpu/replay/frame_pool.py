"""Frame-pool prioritized replay: stacks reconstructed on device at sample time.

The memory problem this solves: storing stacked observations materializes
every 84x84 frame ``2 * frame_stack`` times (obs + next_obs of neighboring
transitions share stack-1 frames).  The reference dedups with host-side
LazyFrames (``wrapper.py:218-252``) and still needs a 128GB replay host for
2e6 transitions.  On TPU the replay lives in HBM (16GB/chip), so the dedup
must move into the storage layout itself:

* a frame ring ``u8[F, D]`` stores every frame ONCE, flattened to D bytes so
  XLA's (8,128) tiling pads <2% instead of padding 84 -> 128;
* transitions store ``int32`` frame indices (``obs_ids``/``next_ids`` of
  shape ``[C, S]``); sampling gathers ``B*S`` rows and reassembles the
  NHWC stack (oldest first, matching :class:`apex_tpu.envs.wrappers.FrameStack`)
  inside the same fused XLA step.

Net: ~8x more capacity per chip than stacked storage (one frame per step vs
2S frames per transition).

Ingest contract (chunks built by
:class:`apex_tpu.replay.frame_chunks.FrameChunkBuilder`): every chunk is
SELF-CONTAINED — it ships all frames its transitions reference, with
chunk-relative refs in ``[0, Kf)``.  Chunks from many actors can interleave
freely.  Fixed shapes with variable fill: a chunk carries ``n_frames <=
Kf`` real frames and ``n_trans <= K`` real transitions (``n_trans >= 1``,
``n_frames >= 1`` — the builder never ships empty chunks), and the ring
cursors advance by the REAL counts.  Pad rows (which the builder fills by
REPEATING the last real row, priorities included) are written to the SAME
ring slot as that last real row: a scatter with duplicate indices all
carrying identical values is deterministic, so padding writes nothing new
and can never clobber older live entries.

Liveness: a transition's frames can be overwritten before the transition
itself when frames arrive faster than ~(frame_capacity/capacity) per
transition — e.g. bursts of length-1 episodes plus chunk-boundary carry.
Rather than relying on a static sizing invariant, staleness is DETECTED at
sample time: each transition records the frame-cursor epoch of its chunk,
and sampled transitions whose epoch has fallen out of the frame ring's
horizon are redirected to the newest (always-valid) slot.  All redirected
rows share that slot's data, so their TD errors — and the duplicate
priority write-back — are identical, keeping the tree deterministic.  With
the default ``frame_capacity = 2 * capacity`` redirection is a measure-zero
event for normal workloads; it is a graceful degradation, never silent
corruption.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import struct

from apex_tpu.ops import tree as tree_ops
from apex_tpu.replay.base import PERMethods


@struct.dataclass
class FramePoolState:
    """Donated-buffer state of one frame-pool shard."""

    frames: jax.Array       # u8[F, D] — flattened frame ring
    extras: dict            # f32[C, ...] per-transition sidecars (extra_spec)
    action: jax.Array       # i32[C]
    reward: jax.Array       # f32[C] — pre-accumulated n-step return
    discount: jax.Array     # f32[C] — bootstrap coefficient (0 at terminal)
    obs_ids: jax.Array      # i32[C, S] — frame-ring rows, oldest first
    next_ids: jax.Array     # i32[C, S]
    frame_epoch: jax.Array  # i32[C] — frame-cursor epoch at ingest (for
                            #   staleness detection; i32 wraparound-safe
                            #   because only differences < 2^31 matter)
    sum_tree: jax.Array     # f32[2C]
    min_tree: jax.Array     # f32[2C]
    pos: jax.Array          # i32 — next transition write index
    f_epoch: jax.Array      # i32 — total frames ever written (frame cursor
                            #   is f_epoch % F)
    size: jax.Array         # i32 — live transition count
    max_priority: jax.Array  # f32


@dataclass(frozen=True)
class FramePoolReplay(PERMethods):
    """Static spec + pure methods (hashable; closes over jits).

    ``frame_shape`` is one frame's shape — (H, W, c) for pixels, (D,) for
    vector observations (``frame_stack=1`` stores plain vectors; >1
    concatenates on the last axis like pixel channel stacking).  Sampled
    observations are ``(B, *frame_shape[:-1], S * frame_shape[-1])`` in
    ``frame_dtype``, oldest frame first on the last axis.
    """

    capacity: int
    frame_shape: tuple[int, ...] = (84, 84, 1)
    frame_stack: int = 4
    frame_capacity: int | None = None
    frame_dtype: str = "uint8"
    alpha: float = 0.6
    eps: float = 1e-6
    # Per-transition float32 sidecar arrays: ((name, trailing_shape), ...).
    # Stored [C, *shape], written from chunk["extras"][name] [K, *shape],
    # returned as top-level batch keys at sample time.  The AQL family
    # stores its candidate set here (a_mu [T, a_dim]) so pixel AQL gets
    # frame dedup instead of 8x stacked storage (VERDICT r3 weak #4).
    extra_spec: tuple[tuple[str, tuple[int, ...]], ...] = ()
    # Frame-row gather backend.  "auto" = jnp.take everywhere, with the
    # pallas scalar-prefetch kernel reachable only via the
    # APEX_GATHER_MODE=pallas opt-in (eligibility-gated per operand);
    # "pallas" forces the kernel — see ops/gather.py:resolved_mode for
    # why the kernel is opt-in until it has a clean on-chip record.
    gather_mode: str = "auto"

    def __post_init__(self):
        tree_ops._check_capacity(self.capacity)
        tree_ops._check_capacity(self.f_capacity)
        if self.f_capacity < self.frame_stack:
            raise ValueError(
                f"frame_capacity={self.f_capacity} cannot hold one "
                f"{self.frame_stack}-frame stack")
        reserved = {"obs", "action", "reward", "next_obs", "discount"}
        for name, _ in self.extra_spec:
            if name in reserved:
                raise ValueError(f"extra_spec name {name!r} collides with "
                                 f"a builtin batch key")

    def hbm_bytes(self) -> int:
        """Estimated HBM footprint of one shard's :class:`FramePoolState` —
        drivers validate this against the chip budget BEFORE allocating so a
        mis-sized config fails with an actionable error instead of an opaque
        XLA OOM."""
        c, s = self.capacity, self.frame_stack
        frame_bytes = (self.f_capacity * self.row_dim
                       * jnp.dtype(self.frame_dtype).itemsize)
        # action/reward/discount/frame_epoch i32|f32 + 2 id tables + 2 trees
        per_trans = 4 * 4 + 2 * 4 * s
        per_trans += sum(4 * math.prod(shape)
                         for _, shape in self.extra_spec)
        tree_bytes = 2 * (2 * c) * 4
        return frame_bytes + c * per_trans + tree_bytes

    @property
    def f_capacity(self) -> int:
        return (self.frame_capacity if self.frame_capacity is not None
                else 2 * self.capacity)

    @property
    def frame_dim(self) -> int:
        return math.prod(self.frame_shape)

    @property
    def row_dim(self) -> int:
        """Stored row width: pixel rows pad up to whole (8, 128) tiles so
        the pallas gather kernel can DMA single rows (ops/gather.py module
        docstring); 84x84 pads 7056 -> 7168 (+1.6%).  Small vector rows
        stay unpadded — they take the XLA gather path."""
        from apex_tpu.ops.gather import ROW_UNIT, pallas_eligible
        d = self.frame_dim
        padded = -(-d // ROW_UNIT) * ROW_UNIT
        if d >= ROW_UNIT // 2 and pallas_eligible(padded, self.frame_dtype):
            return padded
        return d

    @property
    def ring_shape(self) -> tuple[int, ...]:
        """Kernel-eligible rings are STORED in the tiled 3-D view
        ``(F, 8, row_dim/8)``: handing the kernel a pre-shaped operand is
        what keeps the pallas call zero-copy (reshaping inside the fused
        jit step would materialize the whole ring per step).  Eligibility —
        not "was padding needed" — decides the view, so exact-fit rows
        (frame_dim already a ROW_UNIT multiple) take the kernel path too."""
        from apex_tpu.ops.gather import pallas_eligible
        if pallas_eligible(self.row_dim, self.frame_dtype):
            return (self.f_capacity, 8, self.row_dim // 8)
        return (self.f_capacity, self.row_dim)

    # -- construction ------------------------------------------------------

    def init(self, example_item=None) -> FramePoolState:
        """``example_item`` is accepted and ignored for interface parity
        with :meth:`DeviceReplay.init` (shapes come from the spec)."""
        c, s = self.capacity, self.frame_stack
        return FramePoolState(
            frames=jnp.zeros(self.ring_shape, jnp.dtype(self.frame_dtype)),
            extras={name: jnp.zeros((c,) + tuple(shape), jnp.float32)
                    for name, shape in self.extra_spec},
            action=jnp.zeros(c, jnp.int32),
            reward=jnp.zeros(c, jnp.float32),
            discount=jnp.zeros(c, jnp.float32),
            obs_ids=jnp.zeros((c, s), jnp.int32),
            next_ids=jnp.zeros((c, s), jnp.int32),
            frame_epoch=jnp.full(c, jnp.int32(-(2 ** 30))),  # born stale
            sum_tree=tree_ops.init_sum_tree(c),
            min_tree=tree_ops.init_min_tree(c),
            pos=jnp.int32(0),
            f_epoch=jnp.int32(0),
            size=jnp.int32(0),
            max_priority=jnp.float32(1.0),
        )

    # -- mutation (pure) ---------------------------------------------------

    def add(self, state: FramePoolState, chunk: dict,
            priorities: jax.Array, valid=None) -> FramePoolState:
        """Ingest one self-contained chunk (see module docstring).

        ``chunk`` keys: ``frames`` u8[Kf, D], ``n_frames`` i32, ``n_trans``
        i32, ``action``/``reward``/``discount`` [K], ``obs_ref``/``next_ref``
        i32[K, S] (chunk-relative).  ``priorities`` f32[K].

        Pad rows (>= n_frames / n_trans, repeats of the last real row) are
        redirected onto the last real row's slot — identical duplicate
        writes, so nothing old is clobbered.

        Optional ``epoch_off`` i32[K]: per-transition offset added to the
        recorded frame epoch.  Merged payloads
        (:func:`apex_tpu.training.ingest_pipeline.merge_chunk_messages`)
        carry the cumulative frame offset of each transition's source
        chunk here, so one merged ingest records the SAME per-transition
        epochs a sequential chunk-by-chunk ingest would — bit-identical
        staleness detection, pinned in tests/test_ingest_pipeline.py.

        ``valid`` (scalar bool, traced) masks the WHOLE ingest: False
        leaves every field of ``state`` bit-identical, True is
        bit-identical to the unmasked call (both pinned in
        tests/test_ondevice_replay.py).  The fused on-device loop
        (:mod:`apex_tpu.ondevice.fused`) scans over a fixed chunk-slot
        grid whose unsealed slots carry garbage — this is how they
        ingest as no-ops inside one compiled program.  ``None`` (the
        host path) compiles exactly the historical program: no selects,
        no redirects.
        """
        kf = chunk["frames"].shape[0]
        k = priorities.shape[0]
        f, c = self.f_capacity, self.capacity
        # Shape validation runs at trace time (shapes are static under jit).
        # Oversized chunks would make the duplicate-write padding invariant
        # silently clobber live ring entries — reject them loudly instead.
        if kf > f:
            raise ValueError(
                f"chunk carries {kf} frame rows > frame_capacity={f}")
        if k > c:
            raise ValueError(
                f"chunk carries {k} transition rows > capacity={c}")
        if chunk["frames"].shape[1] != self.frame_dim:
            raise ValueError(
                f"chunk frame_dim {chunk['frames'].shape[1]} != spec "
                f"frame_dim {self.frame_dim}")
        for ref in ("obs_ref", "next_ref"):
            if tuple(chunk[ref].shape) != (k, self.frame_stack):
                raise ValueError(
                    f"chunk {ref} shape {tuple(chunk[ref].shape)} != "
                    f"({k}, {self.frame_stack})")
        epoch_off = chunk.get("epoch_off")
        if epoch_off is not None and tuple(epoch_off.shape) != (k,):
            raise ValueError(
                f"chunk epoch_off shape {tuple(epoch_off.shape)} != ({k},)")
        for name, shape in self.extra_spec:
            got = tuple(chunk["extras"][name].shape)
            if got != (k,) + tuple(shape):
                raise ValueError(
                    f"chunk extras[{name!r}] shape {got} != "
                    f"{(k,) + tuple(shape)}")
        fpos = state.f_epoch % f

        frow = jnp.minimum(jnp.arange(kf, dtype=jnp.int32),
                           chunk["n_frames"] - 1)
        fidx = (fpos + frow) % f
        rows = chunk["frames"]
        if len(self.ring_shape) == 3:            # tile-align (see ring_shape)
            rows = jnp.pad(rows, ((0, 0), (0, self.row_dim - self.frame_dim)))
            rows = rows.reshape(kf, 8, self.row_dim // 8)

        trow = jnp.minimum(jnp.arange(k, dtype=jnp.int32),
                           chunk["n_trans"] - 1)
        tidx = (state.pos + trow) % c
        obs_ids = (fpos + chunk["obs_ref"]) % f
        next_ids = (fpos + chunk["next_ref"]) % f

        p_alpha = self._to_tree_priority(priorities)
        if valid is None:
            frames = state.frames.at[fidx].set(rows)

            def tset(arr, vals):
                return arr.at[tidx].set(vals)

            sum_tree, min_tree = tree_ops.update_both(
                state.sum_tree, state.min_tree, tidx, p_alpha)
        else:
            # masked ingest: scatters redirect to an out-of-range row and
            # DROP; the trees instead re-write their CURRENT leaf values
            # (propagation recomputes identical reductions — a bit-exact
            # no-op), because a dropped leaf write would still recompute
            # ancestors from an out-of-bounds child gather
            frames = state.frames.at[
                jnp.where(valid, fidx, f)].set(rows, mode="drop")
            tdrop = jnp.where(valid, tidx, c)

            def tset(arr, vals):
                return arr.at[tdrop].set(vals, mode="drop")

            sum_tree = tree_ops.update_sum(
                state.sum_tree, tidx,
                jnp.where(valid, p_alpha,
                          tree_ops.get_leaves(state.sum_tree, tidx)))
            min_tree = tree_ops.update_min(
                state.min_tree, tidx,
                jnp.where(valid, p_alpha,
                          tree_ops.get_leaves(state.min_tree, tidx)))

        epoch = state.f_epoch
        if epoch_off is not None:
            epoch = epoch + epoch_off.astype(jnp.int32)

        def scalar(new, old):
            return new if valid is None else jnp.where(valid, new, old)

        return state.replace(
            frames=frames,
            extras={name: tset(state.extras[name],
                               chunk["extras"][name].astype(jnp.float32))
                    for name, _ in self.extra_spec},
            action=tset(state.action, chunk["action"].astype(jnp.int32)),
            reward=tset(state.reward, chunk["reward"].astype(jnp.float32)),
            discount=tset(state.discount,
                          chunk["discount"].astype(jnp.float32)),
            obs_ids=tset(state.obs_ids, obs_ids),
            next_ids=tset(state.next_ids, next_ids),
            frame_epoch=tset(state.frame_epoch,
                             jnp.broadcast_to(epoch, (k,))),
            sum_tree=sum_tree, min_tree=min_tree,
            pos=scalar((state.pos + chunk["n_trans"]) % c, state.pos),
            f_epoch=scalar(state.f_epoch + chunk["n_frames"],
                           state.f_epoch),
            size=scalar(jnp.minimum(state.size + chunk["n_trans"], c),
                        state.size),
            max_priority=scalar(
                jnp.maximum(state.max_priority, priorities.max()),
                state.max_priority),
        )

    # update_priorities / is_weights / _to_tree_priority: PERMethods.

    # -- sampling ----------------------------------------------------------

    def sample(self, state: FramePoolState, key: jax.Array, batch_size: int,
               beta: float | jax.Array, axis_name: str | None = None):
        """Stratified PER sample; returns ``(batch, weights, idx)`` with
        stacks gathered from the frame ring.  ``axis_name``: globalize the
        IS-weight normalizers over a sharded mesh axis
        (:meth:`PERMethods.is_weights`).

        Staleness guard (module docstring): transitions whose chunk's frames
        have aged out of the ring are redirected to the newest slot.  i32
        wraparound in the epoch difference is safe for ages < 2^31.
        """
        idx = tree_ops.stratified_sample(state.sum_tree, key, batch_size,
                                         state.size)
        age = state.f_epoch - state.frame_epoch[idx]
        newest = (state.pos - 1) % self.capacity
        idx = jnp.where(age <= self.f_capacity, idx, newest)
        batch = dict(
            obs=self._gather_stacks(state, state.obs_ids[idx]),
            action=state.action[idx],
            reward=state.reward[idx],
            next_obs=self._gather_stacks(state, state.next_ids[idx]),
            discount=state.discount[idx],
            **{name: state.extras[name][idx] for name, _ in self.extra_spec},
        )
        weights = self.is_weights(state, idx, beta, axis_name=axis_name)
        return batch, weights, idx

    def _gather_stacks(self, state: FramePoolState,
                       ids: jax.Array) -> jax.Array:
        """(B, S) frame-ring rows -> (B, *shape[:-1], S*shape[-1]),
        oldest frame first on the last axis."""
        from apex_tpu.ops.gather import gather_rows
        b, s = ids.shape
        shape = self.frame_shape
        rows = gather_rows(state.frames, ids.reshape(-1),
                           mode=self.gather_mode)       # (B*S, row_dim)
        rows = rows[:, :self.frame_dim]                 # drop tile padding
        rows = rows.reshape(b, s, *shape)
        rows = jnp.moveaxis(rows, 1, -2)                # stack before channel
        return rows.reshape(b, *shape[:-1], s * shape[-1])

    # -- helpers -----------------------------------------------------------

    def _to_tree_priority(self, priorities: jax.Array) -> jax.Array:
        p = jnp.maximum(priorities.astype(jnp.float32), self.eps)
        return p ** self.alpha
