"""Frame-dedup prioritized SEQUENCE replay for the recurrent (R2D2) family.

The stacked sequence layout (:mod:`apex_tpu.training.r2d2` on
:class:`~apex_tpu.replay.device.DeviceReplay`) stores every sequence's
``[T, H, W, c]`` observation block verbatim.  With R2D2's overlapping
windows (stride = unroll/2) each env frame appears in ~``t_total/stride``
sequences (~3.4x at defaults, ~6x at Atari-scale unrolls) — the sequence
analogue of the stacked-observation blowup the transition family solves
with :class:`~apex_tpu.replay.frame_pool.FramePoolReplay`, and of the
reference's host-side LazyFrames dedup (``origin_repo/wrapper.py:218-252``).

This module applies the same cure to sequences:

* a frame ring ``u8[F, D]`` stores every env frame ONCE;
* sequences store a ``[T]``-windowed id table (``obs_ids``) into the ring
  alongside their scalar-per-step leaves (action/reward/discount/mask) and
  the stored recurrent state;
* sampling gathers ``B*T`` rows and reshapes to ``[B, T, *frame_shape]``
  inside the fused step — bit-identical batches to the stacked layout
  (pinned in ``tests/test_seq_pool.py``).

Ingest contract (messages built by
:func:`apex_tpu.actors.r2d2.pooled_sequence_message`): every message is
SELF-CONTAINED — it ships each referenced frame exactly once (message-
relative refs in ``[0, Kf)``), row 0 is an all-zero frame shared by every
padded sequence position, pad frame rows are all-zero and redirect onto
row 0's slot, and pad sequences repeat the last real sequence — in every
case the FramePool duplicate-write invariant applies unchanged: a scatter
whose duplicate indices carry identical values writes nothing new.

Staleness is handled exactly as in :class:`FramePoolReplay`: each sequence
records the frame-cursor epoch of its message, and sampled sequences whose
epoch has aged out of the ring redirect to the newest (always-valid) slot.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import struct

from apex_tpu.ops import tree as tree_ops
from apex_tpu.replay.base import PERMethods


@struct.dataclass
class SequenceFramePoolState:
    """Donated-buffer state of one pooled sequence-replay shard."""

    frames: jax.Array       # u8[F, D] (or tiled [F, 8, D/8]) — frame ring
    action: jax.Array       # i32[C, T]
    reward: jax.Array       # f32[C, T]
    discount: jax.Array     # f32[C, T]
    mask: jax.Array         # f32[C, T]
    state_c: jax.Array      # f32[C, H] — stored recurrent state (cell)
    state_h: jax.Array      # f32[C, H]
    obs_ids: jax.Array      # i32[C, T] — frame-ring rows, in step order
    frame_epoch: jax.Array  # i32[C] — frame cursor at ingest (staleness)
    sum_tree: jax.Array     # f32[2C]
    min_tree: jax.Array     # f32[2C]
    pos: jax.Array          # i32 — next sequence write index
    f_epoch: jax.Array      # i32 — total frames ever written
    size: jax.Array         # i32 — live sequence count
    max_priority: jax.Array  # f32


@dataclass(frozen=True)
class SequenceFramePoolReplay(PERMethods):
    """Static spec + pure methods (hashable; closes over jits).

    ``t_total`` is the stored sequence length (burn_in + unroll + n_steps),
    ``lstm_features`` the recurrent state width.  ``frame_shape`` is one
    frame — the recurrent family acts on single frames (the LSTM is the
    memory), so there is no frame-stack axis here.
    """

    capacity: int                                 # sequences
    t_total: int
    lstm_features: int
    frame_shape: tuple[int, ...] = (84, 84, 1)
    frame_capacity: int | None = None
    frame_dtype: str = "uint8"
    alpha: float = 0.6
    eps: float = 1e-6
    gather_mode: str = "auto"   # see FramePoolReplay.gather_mode

    def __post_init__(self):
        tree_ops._check_capacity(self.capacity)
        # f_capacity needs no power-of-2 shape: the ring uses plain
        # modular arithmetic, only the TREES (over `capacity`) require it
        if self.f_capacity <= 0:
            raise ValueError(f"frame_capacity must be positive, "
                             f"got {self.f_capacity}")
        if self.f_capacity < self.t_total:
            raise ValueError(
                f"frame_capacity={self.f_capacity} cannot hold one "
                f"{self.t_total}-step sequence window")

    # -- geometry (shared conventions with FramePoolReplay) ----------------

    @property
    def f_capacity(self) -> int:
        # sequences reference ~stride new frames each; 4*capacity covers
        # the default stride=8 at half occupancy — drivers size this
        # explicitly from the configured stride (build_r2d2)
        return (self.frame_capacity if self.frame_capacity is not None
                else 4 * self.capacity)

    @property
    def frame_dim(self) -> int:
        return math.prod(self.frame_shape)

    @property
    def row_dim(self) -> int:
        """Tile-padded row width — same rule as
        :meth:`FramePoolReplay.row_dim` so the pallas gather kernel can
        DMA single rows of pixel rings."""
        from apex_tpu.ops.gather import ROW_UNIT, pallas_eligible
        d = self.frame_dim
        padded = -(-d // ROW_UNIT) * ROW_UNIT
        if d >= ROW_UNIT // 2 and pallas_eligible(padded, self.frame_dtype):
            return padded
        return d

    @property
    def ring_shape(self) -> tuple[int, ...]:
        """Kernel-eligible rings store the tiled 3-D view (see
        :meth:`FramePoolReplay.ring_shape`)."""
        from apex_tpu.ops.gather import pallas_eligible
        if pallas_eligible(self.row_dim, self.frame_dtype):
            return (self.f_capacity, 8, self.row_dim // 8)
        return (self.f_capacity, self.row_dim)

    def hbm_bytes(self) -> int:
        """Estimated HBM footprint of one shard (drivers budget-check this
        BEFORE allocating)."""
        c, t, h = self.capacity, self.t_total, self.lstm_features
        frame_bytes = (self.f_capacity * self.row_dim
                       * jnp.dtype(self.frame_dtype).itemsize)
        per_seq = 4 * (5 * t + 2 * h + 1)   # 4 [T] f32/i32 + ids + state + epoch
        tree_bytes = 2 * (2 * c) * 4
        return frame_bytes + c * per_seq + tree_bytes

    # -- construction ------------------------------------------------------

    def init(self, example_item=None) -> SequenceFramePoolState:
        """``example_item`` accepted and ignored (interface parity with
        :meth:`DeviceReplay.init`; shapes come from the spec)."""
        c, t, h = self.capacity, self.t_total, self.lstm_features
        return SequenceFramePoolState(
            frames=jnp.zeros(self.ring_shape, jnp.dtype(self.frame_dtype)),
            action=jnp.zeros((c, t), jnp.int32),
            reward=jnp.zeros((c, t), jnp.float32),
            discount=jnp.zeros((c, t), jnp.float32),
            mask=jnp.zeros((c, t), jnp.float32),
            state_c=jnp.zeros((c, h), jnp.float32),
            state_h=jnp.zeros((c, h), jnp.float32),
            obs_ids=jnp.zeros((c, t), jnp.int32),
            frame_epoch=jnp.full(c, jnp.int32(-(2 ** 30))),  # born stale
            sum_tree=tree_ops.init_sum_tree(c),
            min_tree=tree_ops.init_min_tree(c),
            pos=jnp.int32(0),
            f_epoch=jnp.int32(0),
            size=jnp.int32(0),
            max_priority=jnp.float32(1.0),
        )

    # -- mutation (pure) ---------------------------------------------------

    def add(self, state: SequenceFramePoolState, chunk: dict,
            priorities: jax.Array) -> SequenceFramePoolState:
        """Ingest one self-contained pooled sequence message.

        ``chunk`` keys: ``frames`` u8[Kf, D], ``n_frames`` i32, ``n_seqs``
        i32, ``obs_ref`` i32[G, T] (message-relative), ``action`` i32[G, T],
        ``reward``/``discount``/``mask`` f32[G, T], ``state_c``/``state_h``
        f32[G, H].  ``priorities`` f32[G].  Pad frame rows are all-zero
        and redirect onto row 0 (the message's shared zero frame); pad
        sequences repeat the last real sequence — both duplicate-write
        safe (module docstring).
        """
        kf = chunk["frames"].shape[0]
        g = priorities.shape[0]
        f, c, t = self.f_capacity, self.capacity, self.t_total
        if kf > f:
            raise ValueError(
                f"message carries {kf} frame rows > frame_capacity={f}")
        if g > c:
            raise ValueError(
                f"message carries {g} sequences > capacity={c}")
        if chunk["frames"].shape[1] != self.frame_dim:
            raise ValueError(
                f"message frame_dim {chunk['frames'].shape[1]} != spec "
                f"frame_dim {self.frame_dim}")
        if tuple(chunk["obs_ref"].shape) != (g, t):
            raise ValueError(
                f"message obs_ref shape {tuple(chunk['obs_ref'].shape)} "
                f"!= ({g}, {t})")

        fpos = state.f_epoch % f
        # pad rows (>= n_frames) are ALL-ZERO by the message contract and
        # redirect onto row 0 — the message's shared zero frame — so the
        # duplicate writes carry identical (zero) values and clobber
        # nothing (cf. FramePoolReplay's repeat-last-row variant)
        ar = jnp.arange(kf, dtype=jnp.int32)
        frow = jnp.where(ar < chunk["n_frames"], ar, 0)
        fidx = (fpos + frow) % f
        rows = chunk["frames"]
        if len(self.ring_shape) == 3:            # tile-align (ring_shape)
            rows = jnp.pad(rows, ((0, 0), (0, self.row_dim - self.frame_dim)))
            rows = rows.reshape(kf, 8, self.row_dim // 8)
        frames = state.frames.at[fidx].set(rows)

        srow = jnp.minimum(jnp.arange(g, dtype=jnp.int32),
                           chunk["n_seqs"] - 1)
        tidx = (state.pos + srow) % c
        obs_ids = (fpos + chunk["obs_ref"]) % f

        p_alpha = self._to_tree_priority(priorities)
        sum_tree, min_tree = tree_ops.update_both(
            state.sum_tree, state.min_tree, tidx, p_alpha)

        return state.replace(
            frames=frames,
            action=state.action.at[tidx].set(
                chunk["action"].astype(jnp.int32)),
            reward=state.reward.at[tidx].set(
                chunk["reward"].astype(jnp.float32)),
            discount=state.discount.at[tidx].set(
                chunk["discount"].astype(jnp.float32)),
            mask=state.mask.at[tidx].set(chunk["mask"].astype(jnp.float32)),
            state_c=state.state_c.at[tidx].set(
                chunk["state_c"].astype(jnp.float32)),
            state_h=state.state_h.at[tidx].set(
                chunk["state_h"].astype(jnp.float32)),
            obs_ids=state.obs_ids.at[tidx].set(obs_ids),
            frame_epoch=state.frame_epoch.at[tidx].set(state.f_epoch),
            sum_tree=sum_tree, min_tree=min_tree,
            pos=(state.pos + chunk["n_seqs"]) % c,
            f_epoch=state.f_epoch + chunk["n_frames"],
            size=jnp.minimum(state.size + chunk["n_seqs"], c),
            max_priority=jnp.maximum(state.max_priority, priorities.max()),
        )

    # update_priorities / is_weights / _to_tree_priority: PERMethods.

    # -- sampling ----------------------------------------------------------

    def sample(self, state: SequenceFramePoolState, key: jax.Array,
               batch_size: int, beta: float | jax.Array,
               axis_name: str | None = None):
        """Stratified PER sample; returns ``(batch, weights, idx)`` with
        the SAME batch schema as the stacked sequence layout — ``obs``
        gathered ``[B, T, *frame_shape]`` from the ring."""
        idx = tree_ops.stratified_sample(state.sum_tree, key, batch_size,
                                         state.size)
        age = state.f_epoch - state.frame_epoch[idx]
        newest = (state.pos - 1) % self.capacity
        idx = jnp.where(age <= self.f_capacity, idx, newest)
        batch = dict(
            obs=self._gather_sequences(state, state.obs_ids[idx]),
            action=state.action[idx],
            reward=state.reward[idx],
            discount=state.discount[idx],
            mask=state.mask[idx],
            state_c=state.state_c[idx],
            state_h=state.state_h[idx],
        )
        weights = self.is_weights(state, idx, beta, axis_name=axis_name)
        return batch, weights, idx

    def _gather_sequences(self, state: SequenceFramePoolState,
                          ids: jax.Array) -> jax.Array:
        """(B, T) frame-ring rows -> (B, T, *frame_shape), step order
        preserved (no channel stacking — single frames, the LSTM is the
        memory)."""
        from apex_tpu.ops.gather import gather_rows
        b, t = ids.shape
        rows = gather_rows(state.frames, ids.reshape(-1),
                           mode=self.gather_mode)       # (B*T, row_dim)
        rows = rows[:, :self.frame_dim]                 # drop tile padding
        return rows.reshape(b, t, *self.frame_shape)
