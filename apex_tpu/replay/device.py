"""HBM-resident prioritized replay.

TPU re-design of the reference's replay stack (``memory.py:146-391``): instead
of a Python list of pickled tuples guarded by one asyncio lock — the
reference's acknowledged system-wide bottleneck (``origin_repo/README.md:11``,
``replay.py:92-93,141-143``) — the buffer is a struct-of-arrays pytree of
preallocated device arrays plus flat sum/min trees (:mod:`apex_tpu.ops.tree`).
Every operation (add-with-priority, stratified sample + IS weights, priority
update) is a pure function of ``ReplayState`` and traces into the learner's
single fused XLA step; concurrency is resolved by program order inside the
compiled step rather than locks.

Semantic parity:

* ``add`` takes caller-computed priorities, merging add+update exactly like
  ``CustomPrioritizedReplayBuffer.add`` (``memory.py:334-346``); ring-buffer
  positioning matches ``ReplayBuffer.add`` (``memory.py:162-169``).
* ``sample`` reproduces proportional stratified sampling with importance
  weights normalized by the max weight derived from the min-priority leaf
  (``memory.py:252-298``).
* ``update_priorities`` stores ``priority ** alpha`` and tracks the running
  max priority (``memory.py:300-320``).

Observations should be stored ``uint8`` and scaled inside the model — HBM
bandwidth is the bottleneck resource, and uint8 keeps both the ring and the
sampled batch 4x smaller than f32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from apex_tpu.ops import tree as tree_ops
from apex_tpu.replay.base import PERMethods


@struct.dataclass
class ReplayState:
    """Donated-buffer state of one replay shard."""

    storage: Any                # pytree of (capacity, ...) arrays
    sum_tree: jax.Array         # (2*capacity,) f32
    min_tree: jax.Array         # (2*capacity,) f32
    pos: jax.Array              # i32 scalar — next write index
    size: jax.Array             # i32 scalar — current element count
    max_priority: jax.Array     # f32 scalar — reference memory.py:233


@dataclass(frozen=True)
class DeviceReplay(PERMethods):
    """Static spec + pure methods.  Hashable, so it can close over jits."""

    capacity: int
    alpha: float = 0.6
    eps: float = 1e-6

    def __post_init__(self):
        tree_ops._check_capacity(self.capacity)

    # -- construction ------------------------------------------------------

    def hbm_bytes(self, example_item: Any) -> int:
        """Estimated HBM footprint of one shard's :class:`ReplayState` for
        this item pytree (drivers check vs the chip budget pre-alloc)."""
        import numpy as np
        per_item = sum(
            int(np.prod(jnp.shape(x))) * np.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(example_item))
        tree_bytes = 2 * (2 * self.capacity) * 4
        return self.capacity * per_item + tree_bytes

    def init(self, example_item: Any) -> ReplayState:
        """Allocate zeroed storage shaped like one transition pytree."""
        storage = jax.tree.map(
            lambda x: jnp.zeros((self.capacity,) + jnp.shape(x),
                                dtype=jnp.asarray(x).dtype),
            example_item)
        return ReplayState(
            storage=storage,
            sum_tree=tree_ops.init_sum_tree(self.capacity),
            min_tree=tree_ops.init_min_tree(self.capacity),
            pos=jnp.int32(0),
            size=jnp.int32(0),
            max_priority=jnp.float32(1.0),
        )

    # -- mutation (pure) ---------------------------------------------------

    def add(self, state: ReplayState, batch: Any,
            priorities: jax.Array) -> ReplayState:
        """Fused ring-write + priority set for K transitions."""
        k = priorities.shape[0]
        idx = (state.pos + jnp.arange(k, dtype=jnp.int32)) % self.capacity
        storage = jax.tree.map(lambda s, b: s.at[idx].set(b.astype(s.dtype)),
                               state.storage, batch)
        p_alpha = self._to_tree_priority(priorities)
        sum_tree, min_tree = tree_ops.update_both(
            state.sum_tree, state.min_tree, idx, p_alpha)
        return state.replace(
            storage=storage, sum_tree=sum_tree, min_tree=min_tree,
            pos=(state.pos + k) % self.capacity,
            size=jnp.minimum(state.size + k, self.capacity),
            max_priority=jnp.maximum(state.max_priority, priorities.max()),
        )

    def add_max_priority(self, state: ReplayState, batch: Any) -> ReplayState:
        """Insert at the running max priority (``memory.py:235-240``)."""
        k = jax.tree.leaves(batch)[0].shape[0]
        prios = jnp.full((k,), state.max_priority, dtype=jnp.float32)
        return self.add(state, batch, prios)

    # update_priorities / is_weights / _to_tree_priority: PERMethods.

    # -- sampling ----------------------------------------------------------

    def sample(self, state: ReplayState, key: jax.Array, batch_size: int,
               beta: float | jax.Array, axis_name: str | None = None):
        """Returns ``(batch, weights, idx)``; weights normalized by max
        weight (globally, via collectives, when ``axis_name`` names a
        sharded mesh axis — see :meth:`PERMethods.is_weights`)."""
        idx = tree_ops.stratified_sample(state.sum_tree, key, batch_size,
                                         state.size)
        batch = jax.tree.map(lambda s: s[idx], state.storage)
        weights = self.is_weights(state, idx, beta, axis_name=axis_name)
        return batch, weights, idx
