"""Actor-side n-step transition accumulation.

Capability parity with the reference ``BatchStorage`` (``memory.py:393-478``):
a per-actor sliding window emits ``(s_t, a_t, R_t^(n), s_{t+n}, discount)``
with the Q-values observed while acting stored alongside, so initial TD
priorities are computed WITHOUT re-running the network
(``memory.py:396-397,451-464``) — the key Ape-X trick that keeps priority
computation on the actor.

Two deliberate corrections over the reference (not drift):

* The reference's flush accumulates n+1 rewards (``memory.py:418`` passes the
  deque's n rewards plus the current one to ``multi_step_reward``) while the
  learner bootstraps with ``gamma ** n`` (``utils.py:74``), double-counting
  the boundary reward.  Here the emitted return is the textbook n-step sum of
  exactly k rewards, ``R = sum_{i<k} gamma^i r_{t+i}``.
* Instead of a ``done`` flag and a fixed ``gamma ** n`` in the loss, each
  transition carries its own bootstrap ``discount``:

    - full window:            ``discount = gamma ** n``
    - episode TERMINATED:     tail flushes with ``discount = 0`` (no
      bootstrap — the env reached a true terminal state)
    - episode TRUNCATED:      tail flushes with ``discount = gamma ** k``
      bootstrapping from the final observation — a time-limit cut is NOT a
      terminal state, and masking it (as ``done = terminated or truncated``
      would) biases Q-values near the limit low.  This is the
      gymnasium-API-correct handling the reference predates.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import numpy as np


class NStepAccumulator:
    """Single-environment accumulator. For fleets, keep one per env slot."""

    def __init__(self, n_steps: int, gamma: float = 0.99):
        self.n_steps = n_steps
        self.gamma = gamma
        self._window: deque = deque()
        self._out: dict[str, list] = self._empty_out()

    @staticmethod
    def _empty_out() -> dict[str, list]:
        return {k: [] for k in ("obs", "action", "reward", "next_obs",
                                "discount", "q0", "qn")}

    def add(self, obs: Any, action: int, reward: float,
            q_values: np.ndarray, terminated: bool,
            truncated: bool = False, final_obs: Any = None) -> None:
        """Record one env step.

        ``obs`` is the state acted on, ``reward``/``terminated``/``truncated``
        the step outcome, ``q_values`` the network output at ``obs``.  On a
        truncated (but not terminated) step, ``final_obs`` must be the
        observation AFTER the step — the tail bootstraps from it.
        """
        if truncated and not terminated and final_obs is None:
            raise ValueError(
                "truncated step requires final_obs to bootstrap from")
        self._window.append((obs, action, reward, q_values))
        if len(self._window) == self.n_steps + 1:
            self._emit_full()
            self._window.popleft()
        if terminated:
            # True terminal: flush tail with no bootstrap.  next_obs is a
            # placeholder (the last acted state) — discount=0 masks it.
            placeholder = self._window[-1][0]
            while self._window:
                self._emit_tail(next_obs=placeholder, bootstrap=False)
                self._window.popleft()
        elif truncated:
            while self._window:
                self._emit_tail(next_obs=final_obs, bootstrap=True)
                self._window.popleft()

    def _emit_full(self) -> None:
        """Emit the oldest transition with a full n-step window."""
        w = self._window
        n = self.n_steps
        ret = sum((self.gamma ** i) * w[i][2] for i in range(n))
        self._push(w[0], ret, next_obs=w[n][0], discount=self.gamma ** n,
                   qn=w[n][3])

    def _emit_tail(self, next_obs: Any, bootstrap: bool) -> None:
        """Emit the oldest windowed transition at episode end (k < n rewards).

        For truncation the bootstrap Q estimate ``qn`` is the Q at the LAST
        acted state (one step before ``final_obs``) — the closest estimate
        available without re-running the network; it only seeds the initial
        priority, which the learner corrects on first sample.
        """
        w = self._window
        k = len(w)
        ret = sum((self.gamma ** i) * w[i][2] for i in range(k))
        discount = (self.gamma ** k) if bootstrap else 0.0
        self._push(w[0], ret, next_obs=next_obs, discount=discount,
                   qn=w[-1][3])

    def _push(self, head: tuple, ret: float, next_obs: Any, discount: float,
              qn: np.ndarray) -> None:
        obs0, action0, _, q0 = head
        o = self._out
        o["obs"].append(obs0)
        o["action"].append(action0)
        o["reward"].append(np.float32(ret))
        o["next_obs"].append(next_obs)
        o["discount"].append(np.float32(discount))
        o["q0"].append(q0)
        o["qn"].append(qn)

    def __len__(self) -> int:
        return len(self._out["obs"])

    def compute_priorities(self) -> np.ndarray:
        """Initial TD priorities from stored Q-values (``memory.py:451-464``)."""
        o = self._out
        actions = np.asarray(o["action"])
        rewards = np.asarray(o["reward"], np.float32)
        discounts = np.asarray(o["discount"], np.float32)
        q0 = np.stack(o["q0"])
        qn = np.stack(o["qn"])
        q_taken = q0[np.arange(len(q0)), actions]
        target = rewards + discounts * qn.max(1)
        return np.abs(target - q_taken).astype(np.float32) + 1e-6

    def make_batch(self) -> tuple[dict[str, np.ndarray], np.ndarray]:
        """Materialize accumulated transitions + priorities, then reset
        (``memory.py:466-469``).  LazyFrames force-materialize here — the one
        host-side copy before the wire/device."""
        prios = self.compute_priorities()
        o = self._out
        batch = dict(
            obs=np.stack([np.asarray(x) for x in o["obs"]]),
            action=np.asarray(o["action"], np.int32),
            reward=np.asarray(o["reward"], np.float32),
            next_obs=np.stack([np.asarray(x) for x in o["next_obs"]]),
            discount=np.asarray(o["discount"], np.float32),
        )
        self._out = self._empty_out()
        return batch, prios

    def reset(self) -> None:
        """Drop window and pending output (new episode hard reset)."""
        self._window.clear()
        self._out = self._empty_out()
