"""Actor-side n-step transition accumulation.

Capability parity with the reference ``BatchStorage`` (``memory.py:393-478``):
a per-actor sliding window emits ``(s_t, a_t, R_t^(n), s_{t+n}, done)`` with
the Q-values observed while acting stored alongside, so initial TD priorities
are computed WITHOUT re-running the network (``memory.py:396-397,451-464``) —
the key Ape-X trick that keeps priority computation on the actor.

Semantics delta (deliberate correction, not drift): the reference's flush
accumulates n+1 rewards (``memory.py:418`` passes the deque's n rewards plus
the current one to ``multi_step_reward``) while the learner bootstraps with
``gamma ** n`` (``utils.py:74``), double-counting the boundary reward.  Here
the emitted return is the textbook n-step sum of exactly n rewards,
``R = sum_{i<n} gamma^i r_{t+i}``, bootstrapped by ``gamma^n max_a Q(s_{t+n})``
— consistent with the loss in :mod:`apex_tpu.ops.losses`.  On episode end the
tail of the window flushes with shorter reward sums and ``done=1`` (bootstrap
masked), matching the reference's flush-on-done (``memory.py:416,432-435``).
"""

from __future__ import annotations

from collections import deque
from typing import Any

import numpy as np


class NStepAccumulator:
    """Single-environment accumulator. For fleets, keep one per env slot."""

    def __init__(self, n_steps: int, gamma: float = 0.99):
        self.n_steps = n_steps
        self.gamma = gamma
        self._window: deque = deque()
        self._out: dict[str, list] = self._empty_out()

    @staticmethod
    def _empty_out() -> dict[str, list]:
        return {k: [] for k in ("obs", "action", "reward", "next_obs", "done",
                                "q0", "qn")}

    def add(self, obs: Any, action: int, reward: float,
            q_values: np.ndarray, done: bool) -> None:
        """Record one env step: ``obs`` is the state acted on, ``reward``/
        ``done`` the step outcome, ``q_values`` the network output at ``obs``."""
        self._window.append((obs, action, reward, q_values))
        if len(self._window) == self.n_steps + 1:
            self._emit(bootstrap=True)
            self._window.popleft()
        if done:
            terminal_obs = self._window[-1][0]
            while self._window:
                self._emit(bootstrap=False, terminal_obs=terminal_obs)
                self._window.popleft()

    def _emit(self, bootstrap: bool, terminal_obs: Any = None) -> None:
        """Emit the oldest windowed transition."""
        w = self._window
        obs0, action0, _, q0 = w[0]
        ret = 0.0
        for i in range(len(w) if not bootstrap else self.n_steps):
            ret += (self.gamma ** i) * w[i][2]
        if bootstrap:
            next_obs, qn = w[self.n_steps][0], w[self.n_steps][3]
            done = 0.0
        else:
            next_obs, qn = terminal_obs, w[-1][3]
            done = 1.0
        o = self._out
        o["obs"].append(obs0)
        o["action"].append(action0)
        o["reward"].append(np.float32(ret))
        o["next_obs"].append(next_obs)
        o["done"].append(np.float32(done))
        o["q0"].append(q0)
        o["qn"].append(qn)

    def __len__(self) -> int:
        return len(self._out["obs"])

    def compute_priorities(self) -> np.ndarray:
        """Initial TD priorities from stored Q-values (``memory.py:451-464``)."""
        o = self._out
        actions = np.asarray(o["action"])
        rewards = np.asarray(o["reward"], np.float32)
        dones = np.asarray(o["done"], np.float32)
        q0 = np.stack(o["q0"])
        qn = np.stack(o["qn"])
        q_taken = q0[np.arange(len(q0)), actions]
        target = rewards + (self.gamma ** self.n_steps) * qn.max(1) * (1 - dones)
        return np.abs(target - q_taken).astype(np.float32) + 1e-6

    def make_batch(self) -> tuple[dict[str, np.ndarray], np.ndarray]:
        """Materialize accumulated transitions + priorities, then reset
        (``memory.py:466-469``).  LazyFrames force-materialize here — the one
        host-side copy before the wire/device."""
        prios = self.compute_priorities()
        o = self._out
        batch = dict(
            obs=np.stack([np.asarray(x) for x in o["obs"]]),
            action=np.asarray(o["action"], np.int32),
            reward=np.asarray(o["reward"], np.float32),
            next_obs=np.stack([np.asarray(x) for x in o["next_obs"]]),
            done=np.asarray(o["done"], np.float32),
        )
        self._out = self._empty_out()
        return batch, prios

    def reset(self) -> None:
        """Drop window and pending output (new episode hard reset)."""
        self._window.clear()
        self._out = self._empty_out()
