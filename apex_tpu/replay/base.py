"""PER math shared by every replay layout.

Both :class:`apex_tpu.replay.device.DeviceReplay` (stacked storage) and
:class:`apex_tpu.replay.frame_pool.FramePoolReplay` (frame-pool storage)
keep identical ``sum_tree``/``min_tree``/``size``/``max_priority`` fields in
their state; the priority-update and importance-weight math over those
fields lives here once so the two layouts cannot diverge semantically
(reference: ``memory.py:252-320``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import tree as tree_ops


class PERMethods:
    """Mixin over frozen replay specs with ``alpha``/``eps`` fields and
    states carrying ``sum_tree``/``min_tree``/``size``/``max_priority``."""

    def update_priorities(self, state, idx: jax.Array,
                          priorities: jax.Array):
        """Store ``priority ** alpha`` and track the running max
        (``memory.py:300-320``).  Duplicate ``idx`` entries must carry equal
        values (they do on every call path: duplicates share batch rows)."""
        p_alpha = self._to_tree_priority(priorities)
        sum_tree, min_tree = tree_ops.update_both(
            state.sum_tree, state.min_tree, idx, p_alpha)
        return state.replace(
            sum_tree=sum_tree, min_tree=min_tree,
            max_priority=jnp.maximum(state.max_priority, priorities.max()))

    def is_weights(self, state, idx: jax.Array,
                   beta: float | jax.Array,
                   axis_name: str | None = None) -> jax.Array:
        """IS weights normalized by the max weight from the min-priority
        leaf (``memory.py:252-298``).

        ``axis_name``: inside a ``shard_map`` over a dp-sharded replay.
        Each shard samples from its OWN tree, so a transition's true
        inclusion probability is ``leaf / (n_shards * shard_total)`` — the
        LOCAL total and LOCAL size reproduce exactly that
        (``local_p * local_size == global_p_eff * global_size``), making
        the bias correction unbiased for the sampler actually used even
        when priority mass concentrates unevenly across shards (a pure
        psum'd-total formula would assume a global sampler that doesn't
        exist).  Only the max-weight NORMALIZER is collectived (one scalar
        ``pmax`` over ICI) so every shard scales its loss terms
        identically; with balanced shards this reduces bit-for-bit to the
        reference's single-buffer formula (``tests/test_parallel.py``)."""
        total = tree_ops.tree_total(state.sum_tree)
        size = state.size.astype(jnp.float32)
        p_min = tree_ops.tree_min(state.min_tree) / total
        max_weight = (p_min * size) ** (-beta)
        if axis_name is not None:
            max_weight = jax.lax.pmax(max_weight, axis_name)
        p_sample = tree_ops.get_leaves(state.sum_tree, idx) / total
        return ((p_sample * size) ** (-beta) / max_weight).astype(jnp.float32)

    def _to_tree_priority(self, priorities: jax.Array) -> jax.Array:
        p = jnp.maximum(priorities.astype(jnp.float32), self.eps)
        return p ** self.alpha


def check_hbm_budget(estimated_bytes: int, budget_gb: float,
                     what: str, capacity: int) -> None:
    """Refuse to allocate a replay shard over the chip budget — an
    actionable error instead of an opaque XLA OOM mid-run.  Every driver
    construction path calls this before ``init``."""
    budget = int(budget_gb * 2 ** 30)
    if estimated_bytes > budget:
        raise ValueError(
            f"{what} would need ~{estimated_bytes / 2**30:.1f} GiB HBM, "
            f"over the {budget_gb:.1f} GiB budget (replay.hbm_budget_gb). "
            f"Shrink replay.capacity (currently {capacity}) or raise the "
            f"budget; multi-chip slices scale total capacity by the dp "
            f"degree, so per-chip capacity stays modest.")
