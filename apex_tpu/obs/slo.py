"""Fleet SLO engine: declarative objectives judged by burn-rate windows.

PR 6 gave the fleet raw signals (lineage histograms, heartbeat gauges,
Prometheus exposition) and PRs 7-10 added the roles that emit them;
nothing JUDGED those signals.  This module is the objective layer: a
declarative registry of SLOs (each = one signal path into the
fleet-summary/heartbeat-gauge space + a threshold + an error budget),
evaluated continuously by :class:`SloEngine` on the learner's health
tick with the classic SRE multi-window burn-rate scheme, and surfaced as
flap-damped alert state machines in ``fleet_summary.json``, the
``--role status`` table, and ``apex_slo_*`` Prometheus rows.

Burn-rate semantics (Google SRE workbook, scaled to our tick):

* every health tick the engine resolves each objective's signal and
  records one GOOD/BAD verdict against the threshold;
* burn rate over a window = (bad fraction over the window) / budget —
  1.0 means the error budget is being spent exactly at the sustainable
  rate, 14.4 means a 30-day budget would be gone in 2 days;
* PAGE-grade firing needs BOTH fast windows (default 1m/5m) above
  ``page_burn`` — the short window gives speed, the long one keeps a
  single bad tick from paging;
* WARN-grade firing needs both slow windows (default 30m/6h) above
  ``warn_burn`` — slow leaks that never trip the page pair.

Windows are SCALED TO RUN LENGTH for free: verdicts only exist after
engine start, so a 6h window over a 3-minute run is simply "the whole
run" (``min_samples`` keeps one lonely verdict from judging anything).
The engine takes injectable clocks, so every transition below is
deterministic under the fake-clock tests.

Alert machine, flap-damped (per objective)::

    OK --page burn--> BURNING --sustained breach_after_s--> BREACHED
    BURNING --burn clears--> OK            (transient spike: no page)
    BREACHED --quiet resolve_after_s--> RESOLVED --quiet ok_after_s--> OK
    RESOLVED --page burn--> BREACHED       (re-breach, counted)

BREACHED is the page: entering it needs SUSTAINED burn, leaving it needs
SUSTAINED quiet — a flapping signal parks in BURNING/BREACHED instead of
strobing alerts.  Severity maps OK/RESOLVED -> 0, BURNING/warn -> 1,
BREACHED -> 2; :func:`apex_tpu.fleet.supervise.scale_decision_slo` sizes
the fleet from exactly that number (``--scale-signal slo``).

The module doubles as the perf-regression gate the bench trajectory has
owed::

    python -m apex_tpu.obs.slo --check BASE.json CAND.json [--tol 0.15]

compares two bench/soak JSONs lane-by-lane (numeric leaves under common
dotted paths, direction classified from the leaf name: percentiles/ages/
lags are lower-better, rates/throughputs higher-better) and exits
nonzero on a regression beyond the tolerance band.

Pure stdlib: the engine runs on the learner's health tick (J006 hot-loop
discipline — host clocks and dict walks only) and the CLI runs on a
stock interpreter.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

OK, BURNING, BREACHED, RESOLVED = "OK", "BURNING", "BREACHED", "RESOLVED"

#: state -> severity (the autoscaler's input; warn-grade firing lifts an
#: otherwise-OK objective to 1)
SEVERITY = {OK: 0, RESOLVED: 0, BURNING: 1, BREACHED: 2}


# -- objectives --------------------------------------------------------------


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective.

    ``signal`` addresses the fleet-summary signal space:

    * ``"metrics.dead_actor_frac"`` — dotted walk into the summary dict
      (``metrics`` / ``latency`` / ``rates`` sections);
    * ``"gauge:<role>:<key>:<agg>"`` — aggregate one heartbeat-gauge key
      over the non-DEAD peers of a role (agg: max/min/sum/mean);
    * ``"derived.dead_frac.<role|all>"`` — DEAD fraction of a role's
      peers (None while no such peer ever registered);
    * ``"derived.role_fps.<role>"`` — summed fps of a role's live peers.

    ``threshold`` is the objective's bound under ``op`` ("<=" or ">=");
    ``None`` makes the objective OBSERVE-ONLY (value tracked and
    exported, never judged — how the eval-score objective ships until an
    operator sets a bar).  ``budget`` is the allowed bad-verdict
    fraction (the error budget burn rates divide by).  ``grace_s``
    suppresses verdicts that soon after engine start (rates are honestly
    zero during warmup — alerting on them would page every cold start).

    ``knobs`` (PR 11 carried follow-up) optionally overrides the
    engine-global burn windows/damping for THIS objective — a
    :class:`SloKnobOverrides` whose non-None fields win over the engine
    knobs.  The serving tier's canary gate is the motivating consumer:
    it wants a much tighter window on ``eval_score`` than on
    ``frame_age``.  Env twins: ``APEX_SLO_<NAME>_{FAST,SLOW,PAGE_BURN,
    WARN_BURN,BREACH_AFTER,RESOLVE_AFTER,OK_AFTER,MIN_SAMPLES}`` (name
    uppercased), parsed by :func:`objective_knobs_from_env`.
    """

    name: str
    signal: str
    threshold: float | None
    op: str = "<="
    budget: float = 0.01
    grace_s: float = 0.0
    description: str = ""
    knobs: "SloKnobOverrides | None" = None

    def judge(self, value) -> bool | None:
        """GOOD (True) / BAD (False) / no verdict (None: observe-only
        objective or unresolvable signal)."""
        if self.threshold is None or value is None:
            return None
        if self.op == "<=":
            return float(value) <= self.threshold
        return float(value) >= self.threshold


def _thr(environ, name: str, default: float | None) -> float | None:
    """Per-objective threshold env twin: unset/empty keeps the shipped
    default, ``off``/``none`` disables (observe-only), else a float."""
    v = environ.get(name, "")
    if not v:
        return default
    if v.lower() in ("off", "none"):
        return None
    return float(v)


def default_slos(actor_dead_thresh: float | None = None,
                 environ=None) -> list[SloObjective]:
    """The shipped objective set (every threshold has an env twin,
    ``APEX_SLO_<NAME>``; ``off`` disables an objective).

    ``actor_dead_thresh`` lets the trainer hand its
    ``comms.relax_floor_dead_frac`` in, so the actor-capacity SLO and
    the replay-ratio-floor reaction judge the SAME bar by construction —
    the two can disagree on timing (the SLO is flap-damped), never on
    the threshold.

    Every objective also reads its per-objective knob env twins
    (:func:`objective_knobs_from_env`) — unset twins leave the
    engine-global knobs in charge.
    """
    e = environ if environ is not None else os.environ

    def _obj(name, signal, threshold, op="<=", **kw):
        return SloObjective(name, signal, threshold, op,
                            knobs=objective_knobs_from_env(name, e), **kw)

    return [
        _obj(
            "infer_rt_p99_ms", "gauge:actor:infer_rt_ms_p99:max",
            _thr(e, "APEX_SLO_INFER_RT_MS", 250.0), "<=",
            description="worst actor-reported infer round-trip p99 "
                        "(timed-out requests counted at the fallback "
                        "wait — the ROADMAP serving-tier SLO)"),
        _obj(
            "frame_age_p99_s", "latency.frame_age_at_train_s.p99_s",
            _thr(e, "APEX_SLO_FRAME_AGE_S", 120.0), "<=",
            description="sealed-to-train frame age p99 (PR 6 lineage "
                        "histogram)"),
        _obj(
            "param_lag_p99_s", "latency.param_propagation_lag_s.p99_s",
            _thr(e, "APEX_SLO_PARAM_LAG_S", 60.0), "<=",
            description="publish-to-trained-experience staleness loop "
                        "p99"),
        _obj(
            "learner_steps_rate", "rates.steps_per_s",
            _thr(e, "APEX_SLO_STEPS_RATE", 0.01), ">=", grace_s=90.0,
            description="learner update rate floor (a stalled learner "
                        "is an outage, not a quiet one)"),
        _obj(
            "fleet_frames_rate", "rates.frames_per_s",
            _thr(e, "APEX_SLO_FRAMES_RATE", 0.1), ">=", grace_s=90.0,
            description="fleet-wide ingested-transition rate floor"),
        _obj(
            "actor_fps", "derived.role_fps.actor",
            _thr(e, "APEX_SLO_ACTOR_FPS", None), ">=", grace_s=90.0,
            description="summed live-actor env fps (observe-only until "
                        "an operator sets the bar for the deployment)"),
        _obj(
            "dead_peer_frac", "derived.dead_frac.all",
            _thr(e, "APEX_SLO_DEAD_FRAC", 0.5), "<=",
            description="DEAD fraction of the whole registered fleet"),
        _obj(
            "actor_dead_frac", "metrics.dead_actor_frac",
            (actor_dead_thresh if actor_dead_thresh is not None
             else _thr(e, "APEX_SLO_ACTOR_DEAD_FRAC", 0.5)), "<=",
            description="DEAD fraction of actor capacity — shares its "
                        "threshold with the replay-ratio-floor "
                        "reaction (relax_floor_dead_frac)"),
        _obj(
            "infer_up", "derived.dead_frac.infer",
            _thr(e, "APEX_SLO_INFER_DEAD", 0.0), "<=",
            description="any DEAD infer server breaches (the serving "
                        "tier has no spare by default)"),
        _obj(
            "eval_score", "gauge:evaluator:eval_score_mean:min",
            _thr(e, "APEX_SLO_EVAL_SCORE", None), ">=",
            description="worst evaluator-band mean episode score — the "
                        "model-quality objective the serving tier's "
                        "canary gate keys off (observe-only until an "
                        "operator sets the bar)"),
        _obj(
            "serving_rollbacks", "serving.rollbacks",
            _thr(e, "APEX_SLO_SERVING_ROLLBACKS", None), "<=",
            description="cumulative serving-tier canary rollbacks "
                        "(apex_tpu/serving/deploy) — observe-only by "
                        "default; set 0 to page on ANY rollback"),
    ]


def roster_slos(roster: dict, environ=None) -> list[SloObjective]:
    """Per-tenant objective SETS declared from the ``APEX_TENANTS`` /
    ``APEX_POPULATION`` roster (the PR 13 follow-up): for every roster
    tenant/lineage, a progress-floor objective and an eval-score
    objective — judged by the CONTROLLER (tenant-ctl/pbt-ctl) off its
    per-tenant status probes, instead of only the default tenant's
    engine judging its own fleet.

    Signals walk the controller's probe-derived summary
    (``{"tenants": {<name>: {"steps_rate": ..., "eval_score": ...}}}``)
    via the ordinary dotted resolution, so the same
    :class:`SloEngine` machinery — burn windows, flap damping,
    timelines — judges them unchanged.  Objective names carry the
    ``@tenant`` suffix grammar (``steps_floor@rally``) so operators
    read them next to the existing per-tenant signal paths.

    Env twins: ``APEX_SLO_TENANT_STEPS_RATE`` (default 0.01; ``off``
    disables) and ``APEX_SLO_TENANT_EVAL_SCORE`` (default observe-only)
    set the bars for EVERY roster tenant at once.
    """
    e = environ if environ is not None else os.environ
    steps_thr = _thr(e, "APEX_SLO_TENANT_STEPS_RATE", 0.01)
    score_thr = _thr(e, "APEX_SLO_TENANT_EVAL_SCORE", None)
    out: list[SloObjective] = []
    for name in sorted(roster):
        out.append(SloObjective(
            f"steps_floor@{name}", f"tenants.{name}.steps_rate",
            steps_thr, ">=", grace_s=90.0,
            description=f"tenant {name}: learner progress floor off the "
                        f"controller's status probes (a stalled lineage "
                        f"is an outage, not a quiet one)"))
        out.append(SloObjective(
            f"eval_score@{name}", f"tenants.{name}.eval_score",
            score_thr, ">=",
            description=f"tenant {name}: eval-ladder recent-window mean "
                        f"(observe-only until an operator sets the "
                        f"bar)"))
    return out


# -- signal resolution -------------------------------------------------------


def _tenant_split(path: str) -> tuple[str, str | None]:
    """Peel an optional ``@tenant`` suffix off a peer-walking signal
    path (PR 13): ``derived.dead_frac.actor@rally`` judges ONLY the
    rally tenant's actors — the per-tenant SLO dimension on a shared
    fleet's registry.  No suffix = all tenants, the pre-tenancy
    semantics."""
    if "@" in path:
        head, tenant = path.rsplit("@", 1)
        return head, tenant
    return path, None


def _tenant_match(peer: dict, tenant: str | None) -> bool:
    if tenant is None:
        return True
    return (peer.get("tenant") or "t0") == tenant


def resolve_signal(summary: dict, path: str):
    """Resolve one signal path against a fleet-summary-shaped dict;
    ``None`` for anything missing/non-numeric (a missing signal is a
    skipped verdict, never a crash — observability must not take the
    learner down).  Peer-walking paths (``gauge:``/``derived.``) accept
    an ``@tenant`` suffix restricting the walk to one tenant's peers."""
    try:
        path, tenant = _tenant_split(path)
        if path.startswith("gauge:"):
            _, role, gauge, agg = path.split(":")
            vals = []
            for p in summary.get("peers") or []:
                if p.get("role") != role or p.get("state") == "DEAD" \
                        or not _tenant_match(p, tenant):
                    continue
                v = (p.get("gauges") or {}).get(gauge)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    vals.append(float(v))
            if not vals:
                return None
            if agg == "max":
                return max(vals)
            if agg == "min":
                return min(vals)
            if agg == "sum":
                return sum(vals)
            return sum(vals) / len(vals)            # mean
        if path.startswith("derived.dead_frac."):
            role = path.rsplit(".", 1)[-1]
            peers = [p for p in summary.get("peers") or []
                     if (role == "all" or p.get("role") == role)
                     and _tenant_match(p, tenant)]
            if not peers:
                return None
            return sum(p.get("state") == "DEAD" for p in peers) / len(peers)
        if path.startswith("derived.role_fps."):
            role = path.rsplit(".", 1)[-1]
            peers = [p for p in summary.get("peers") or []
                     if p.get("role") == role
                     and _tenant_match(p, tenant)]
            if not peers:
                return None
            return sum(float(p.get("fps", 0.0)) for p in peers
                       if p.get("state") != "DEAD")
        node = summary
        for part in path.split("."):
            if not isinstance(node, dict):
                return None
            node = node.get(part)
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return None
        return float(node)
    except (TypeError, ValueError, KeyError):
        return None


# -- burn-rate knobs ---------------------------------------------------------


@dataclass(frozen=True)
class SloKnobs:
    """Window/damping parameters; every field has an ``APEX_SLO_*`` env
    twin so a CI drill can compress the whole alert cycle into a
    3-minute soak without touching the production defaults."""

    fast: tuple = (60.0, 300.0)         # page-grade window pair, s
    slow: tuple = (1800.0, 21600.0)     # warn-grade window pair, s
    page_burn: float = 14.4             # SRE 30d-budget "2% in 1h" rate
    warn_burn: float = 3.0
    breach_after_s: float = 10.0        # sustained burn before the page
    resolve_after_s: float = 30.0       # sustained quiet before resolve
    ok_after_s: float = 60.0            # resolved -> ok cooldown
    min_samples: int = 2                # verdicts before a window judges


def knobs_from_env(environ=None) -> SloKnobs:
    e = environ if environ is not None else os.environ

    def pair(name: str, default: tuple) -> tuple:
        v = e.get(name, "")
        if not v:
            return default
        parts = tuple(float(x) for x in v.split(","))
        return parts if len(parts) == 2 else (parts[0], parts[0])

    def num(name: str, default: float) -> float:
        v = e.get(name, "")
        return default if not v else float(v)

    return SloKnobs(
        fast=pair("APEX_SLO_FAST", SloKnobs.fast),
        slow=pair("APEX_SLO_SLOW", SloKnobs.slow),
        page_burn=num("APEX_SLO_PAGE_BURN", SloKnobs.page_burn),
        warn_burn=num("APEX_SLO_WARN_BURN", SloKnobs.warn_burn),
        breach_after_s=num("APEX_SLO_BREACH_AFTER",
                           SloKnobs.breach_after_s),
        resolve_after_s=num("APEX_SLO_RESOLVE_AFTER",
                            SloKnobs.resolve_after_s),
        ok_after_s=num("APEX_SLO_OK_AFTER", SloKnobs.ok_after_s),
        min_samples=int(num("APEX_SLO_MIN_SAMPLES",
                            SloKnobs.min_samples)))


@dataclass(frozen=True)
class SloKnobOverrides:
    """Per-objective window/damping overrides: non-None fields win over
    the engine-global :class:`SloKnobs`, everything else inherits — so
    "tighter eval_score windows for the canary gate" is one field, not a
    whole parallel knob set."""

    fast: tuple | None = None
    slow: tuple | None = None
    page_burn: float | None = None
    warn_burn: float | None = None
    breach_after_s: float | None = None
    resolve_after_s: float | None = None
    ok_after_s: float | None = None
    min_samples: int | None = None


def objective_knobs_from_env(name: str,
                             environ=None) -> SloKnobOverrides | None:
    """Parse ``APEX_SLO_<NAME>_*`` twins (name uppercased) into an
    overrides record; None when no twin is set (the engine-global knobs
    stay in charge — the common case)."""
    e = environ if environ is not None else os.environ
    prefix = f"APEX_SLO_{name.upper()}_"

    def pair(suffix):
        v = e.get(prefix + suffix, "")
        if not v:
            return None
        parts = tuple(float(x) for x in v.split(","))
        return parts if len(parts) == 2 else (parts[0], parts[0])

    def num(suffix):
        v = e.get(prefix + suffix, "")
        return None if not v else float(v)

    ms = num("MIN_SAMPLES")
    over = SloKnobOverrides(
        fast=pair("FAST"), slow=pair("SLOW"),
        page_burn=num("PAGE_BURN"), warn_burn=num("WARN_BURN"),
        breach_after_s=num("BREACH_AFTER"),
        resolve_after_s=num("RESOLVE_AFTER"),
        ok_after_s=num("OK_AFTER"),
        min_samples=None if ms is None else int(ms))
    if all(getattr(over, f.name) is None
           for f in over.__dataclass_fields__.values()):
        return None
    return over


def resolve_knobs(base: SloKnobs, objective: SloObjective) -> SloKnobs:
    """The knobs one objective is judged under: the engine-global base
    with the objective's non-None overrides applied."""
    over = objective.knobs
    if over is None:
        return base
    import dataclasses as _dc
    fields = {f.name: getattr(over, f.name)
              for f in over.__dataclass_fields__.values()
              if getattr(over, f.name) is not None}
    return _dc.replace(base, **fields) if fields else base


# -- the alert state machine -------------------------------------------------


class _Alert:
    """One objective's flap-damped machine (module docstring diagram)."""

    __slots__ = ("state", "burning_since", "clear_since", "resolved_at",
                 "breaches", "warn")

    def __init__(self):
        self.state = OK
        self.burning_since: float | None = None
        self.clear_since: float | None = None
        self.resolved_at: float | None = None
        self.breaches = 0
        self.warn = False

    def step(self, page: bool, warn: bool, now: float,
             k: SloKnobs) -> tuple[str, str] | None:
        self.warn = bool(warn)
        old = self.state
        if self.state == OK:
            if page:
                self.state = BURNING
                self.burning_since = now
        elif self.state == BURNING:
            if not page:
                self.state = OK                 # transient: damped, no page
            elif now - self.burning_since >= k.breach_after_s:
                self.state = BREACHED
                self.breaches += 1
                self.clear_since = None
        elif self.state == BREACHED:
            if page:
                self.clear_since = None         # still burning: hold
            elif self.clear_since is None:
                self.clear_since = now
            elif now - self.clear_since >= k.resolve_after_s:
                self.state = RESOLVED
                self.resolved_at = now
        elif self.state == RESOLVED:
            if page:                            # re-breach: counted
                self.state = BREACHED
                self.breaches += 1
                self.clear_since = None
            elif now - self.resolved_at >= k.ok_after_s:
                self.state = OK
        return (old, self.state) if self.state != old else None


# -- the engine --------------------------------------------------------------


class SloEngine:
    """Continuous objective evaluation over health-tick samples.

    Thread contract: :meth:`sample` runs on the trainer thread (once per
    health tick — NOT per status scrape, or burn windows would depend on
    scrape traffic); :meth:`snapshot`/:meth:`state_of`/:meth:`severity`
    take the same lock and are safe from the status-server thread.
    """

    def __init__(self, objectives: list[SloObjective] | None = None,
                 knobs: SloKnobs | None = None, clock=time.monotonic,
                 wall=time.time, timeline_cap: int = 128):
        self.objectives = list(objectives if objectives is not None
                               else default_slos())
        self.knobs = knobs if knobs is not None else knobs_from_env()
        # per-objective knobs resolved once: engine-global base + the
        # objective's non-None overrides (SloObjective.knobs)
        self._knobs_by: dict[str, SloKnobs] = {
            o.name: resolve_knobs(self.knobs, o) for o in self.objectives}
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._t0: float | None = None
        self._verdicts: dict[str, deque] = {
            o.name: deque(maxlen=8192) for o in self.objectives}
        self._alerts: dict[str, _Alert] = {
            o.name: _Alert() for o in self.objectives}
        self._value: dict[str, float | None] = {}
        self._good: dict[str, int] = {o.name: 0 for o in self.objectives}
        self._total: dict[str, int] = {o.name: 0 for o in self.objectives}
        self.timeline: deque = deque(maxlen=timeline_cap)
        self.ticks = 0

    # -- the clock-driven half --------------------------------------------

    def _burn(self, name: str, now: float, window: float, budget: float,
              min_samples: int | None = None) -> float | None:
        """Burn rate over the trailing window (run-length-scaled for
        free: verdicts only exist after start), or None below
        ``min_samples``."""
        if min_samples is None:
            min_samples = self._knobs_by[name].min_samples
        cut = now - window
        sel = [bad for (t, bad) in self._verdicts[name] if t >= cut]
        if len(sel) < min_samples:
            return None
        return (sum(sel) / len(sel)) / max(budget, 1e-9)

    def _firing(self, o: SloObjective, now: float) -> tuple[bool, bool]:
        k = self._knobs_by[o.name]      # per-objective windows/damping
        fast = [self._burn(o.name, now, w, o.budget, k.min_samples)
                for w in k.fast]
        slow = [self._burn(o.name, now, w, o.budget, k.min_samples)
                for w in k.slow]
        page = all(b is not None and b >= k.page_burn for b in fast)
        warn = all(b is not None and b >= k.warn_burn for b in slow)
        return page, warn

    def sample(self, summary: dict) -> list[dict]:
        """One health-tick evaluation round; returns the transitions
        taken (also appended to the bounded alert timeline)."""
        now = self._clock()
        out: list[dict] = []
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            for o in self.objectives:
                v = resolve_signal(summary, o.signal)
                self._value[o.name] = v
                verdict = o.judge(v)
                if verdict is not None and now - self._t0 >= o.grace_s:
                    self._verdicts[o.name].append(
                        (now, 0 if verdict else 1))
                    self._good[o.name] += int(verdict)
                    self._total[o.name] += 1
                page, warn = self._firing(o, now)
                tr = self._alerts[o.name].step(page, warn, now,
                                               self._knobs_by[o.name])
                if tr is not None:
                    event = {"t_s": round(now - self._t0, 3),
                             "wall": round(self._wall(), 3),
                             "objective": o.name,
                             "from": tr[0], "to": tr[1],
                             "value": v}
                    self.timeline.append(event)
                    out.append(event)
            self.ticks += 1
        return out

    # -- read surface ------------------------------------------------------

    def state_of(self, name: str) -> str | None:
        with self._lock:
            a = self._alerts.get(name)
            return None if a is None else a.state

    def severity(self) -> int:
        with self._lock:
            return self._severity_locked()

    def _severity_locked(self) -> int:
        sev = 0
        for a in self._alerts.values():
            sev = max(sev, SEVERITY[a.state], 1 if a.warn else 0)
        return sev

    def _idle_locked(self, now: float) -> bool:
        """True when no enabled objective has burned ANY budget over the
        slow-long window (and none is alerting) — the scale-down hint:
        capacity is comfortably above objective."""
        judged = 0
        for o in self.objectives:
            k = self._knobs_by[o.name]
            cut = now - k.slow[-1]
            a = self._alerts[o.name]
            if a.state != OK or a.warn:
                return False
            sel = [bad for (t, bad) in self._verdicts[o.name] if t >= cut]
            if len(sel) >= k.min_samples:
                judged += 1
                if any(sel):
                    return False
        return judged > 0

    def compliance(self) -> dict:
        """Lifetime GOOD percentage per judged objective (the soak
        artifact's headline number)."""
        with self._lock:
            return {name: round(100.0 * self._good[name] / total, 2)
                    for name, total in self._total.items() if total}

    def snapshot(self) -> dict:
        """Serializable engine view (fleet_summary.json ``slo`` section,
        status table, soak artifact): plain builtins only."""
        now = self._clock()
        with self._lock:
            objectives = []
            for o in self.objectives:
                a = self._alerts[o.name]
                k = self._knobs_by[o.name]
                bf = self._burn(o.name, now, k.fast[-1], o.budget)
                bs = self._burn(o.name, now, k.slow[-1], o.budget)
                total = self._total[o.name]
                objectives.append({
                    "name": o.name, "signal": o.signal, "op": o.op,
                    "threshold": o.threshold,
                    "enabled": o.threshold is not None,
                    "value": self._value.get(o.name),
                    "state": a.state, "warn": a.warn,
                    "breaches": a.breaches,
                    "burn_fast": None if bf is None else round(bf, 3),
                    "burn_slow": None if bs is None else round(bs, 3),
                    "verdicts": total,
                    "compliance_pct": (round(100.0 * self._good[o.name]
                                             / total, 2) if total
                                       else None),
                })
            return {
                "objectives": objectives,
                "severity": self._severity_locked(),
                "idle": self._idle_locked(now),
                "ticks": self.ticks,
                "elapsed_s": (round(now - self._t0, 3)
                              if self._t0 is not None else 0.0),
                "timeline": list(self.timeline),
            }


# -- prometheus rows ---------------------------------------------------------


def prometheus_sections(slo_snap: dict) -> tuple[dict, dict]:
    """(gauges, labeled) sections for :func:`apex_tpu.obs.metrics.render`
    — the ``apex_slo_*`` row family the scrape surface serves."""
    gauges = {"slo_severity": slo_snap.get("severity", 0),
              "slo_ticks": slo_snap.get("ticks", 0)}
    objectives = slo_snap.get("objectives", [])
    labeled = {
        "slo_state": [({"objective": o["name"], "state": o["state"]},
                       SEVERITY.get(o["state"], 0)) for o in objectives],
        "slo_value": [({"objective": o["name"]}, o["value"])
                      for o in objectives if o.get("value") is not None],
        "slo_burn_fast": [({"objective": o["name"]}, o["burn_fast"])
                          for o in objectives
                          if o.get("burn_fast") is not None],
        "slo_breaches": [({"objective": o["name"]}, o.get("breaches", 0))
                         for o in objectives],
        "slo_compliance_pct": [({"objective": o["name"]},
                                o["compliance_pct"]) for o in objectives
                               if o.get("compliance_pct") is not None],
    }
    return gauges, labeled


def format_slo_lines(slo_snap: dict) -> list[str]:
    """Human objective lines for the ``--role status`` table."""
    lines = []
    for o in slo_snap.get("objectives", []):
        if not o.get("enabled") and o.get("value") is None:
            continue
        v = o.get("value")
        bf = o.get("burn_fast")
        bar = ("observe-only" if o.get("threshold") is None
               else f"{o['op']}{o['threshold']}")
        lines.append(
            f"slo {o['name']}: {o['state']}"
            f"{' (warn)' if o.get('warn') else ''} "
            f"value={'-' if v is None else round(v, 3)} {bar}"
            f" burn={'-' if bf is None else bf}"
            f" breaches={o.get('breaches', 0)}")
    if lines:
        lines.append(
            f"slo severity={slo_snap.get('severity', 0)} "
            f"idle={slo_snap.get('idle', False)} "
            f"ticks={slo_snap.get('ticks', 0)}")
    return lines


# -- the regression differ (--check) ----------------------------------------

#: leaf-name tokens classifying comparison direction.  Lower-better wins
#: ties on purpose: "frame_age_p99_s" contains both "age" and "_s"-ish
#: rate lookalikes, and a latency leaf misclassified as a throughput
#: would invert the gate.
_LOWER_TOKENS = ("p50", "p90", "p99", "mean_s", "max_s", "_ms", "lag",
                 "age", "gap", "wait", "coalesce", "fallback", "drop",
                 "dead", "breach", "stale", "resend", "reroute",
                 # wire-codec lanes (bench part-1g): bytes shipped per
                 # transition/chunk — an improved (smaller) byte count
                 # must never read as a regression
                 "bytes")
_HIGHER_TOKENS = ("per_sec", "per_s", "rate", "throughput", "frames",
                  "steps", "chunks", "compliance", "effective_cores",
                  "score", "bps", "fps",
                  # compression ratios (raw/encoded): bigger is better;
                  # lower tokens win ties, so "bytes_ratio"-style leaves
                  # would classify lower-better — part-1g names its
                  # ratio lanes "*_ratio" with no byte token on purpose
                  "_ratio")


def _direction(path: str) -> int:
    """+1 higher-better, -1 lower-better, 0 informational (skipped)."""
    leaf = path.rsplit(".", 1)[-1].lower()
    for t in _LOWER_TOKENS:
        if t in leaf:
            return -1
    for t in _HIGHER_TOKENS:
        if t in leaf:
            return 1
    return 0


def _flatten(obj, prefix: str = "") -> dict[str, float]:
    """Numeric leaves by dotted path.  Lists are skipped on purpose —
    positional entries (soak sample arrays, shard-size vectors) are not
    comparable lane-for-lane across runs; the gate compares named
    lanes."""
    out: dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(_flatten(v, key))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    return out


def check_regression(base: dict, cand: dict,
                     tol: float = 0.15) -> list[dict]:
    """Lane-by-lane comparison of two bench/soak JSONs.  Returns one row
    per compared leaf with a verdict: ``REGRESSED`` when the candidate
    is worse than base by more than ``tol`` (relative), ``improved``
    when better by the same margin, ``ok`` inside the band.  Leaves
    present in only one file are ignored (new lanes are not
    regressions); near-zero pairs are skipped (relative change on noise
    floors gates nothing)."""
    fa, fb = _flatten(base), _flatten(cand)
    rows: list[dict] = []
    for path in sorted(set(fa) & set(fb)):
        d = _direction(path)
        if d == 0:
            continue
        a, b = fa[path], fb[path]
        if max(abs(a), abs(b)) < 1e-9 or a == 0:
            continue
        change = (b - a) / abs(a)
        if d < 0:
            verdict = ("REGRESSED" if change > tol
                       else "improved" if change < -tol else "ok")
        else:
            verdict = ("REGRESSED" if change < -tol
                       else "improved" if change > tol else "ok")
        rows.append({"path": path, "base": a, "cand": b,
                     "change_pct": round(100.0 * change, 1),
                     "direction": "lower" if d < 0 else "higher",
                     "verdict": verdict})
    return rows


# -- CLI ---------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.obs.slo",
        description="fleet SLO objective table / bench-vs-bench "
                    "regression gate")
    p.add_argument("--check", nargs=2, metavar=("BASE", "CAND"),
                   help="compare two bench/soak JSONs lane-by-lane; "
                        "exit 1 on any regression beyond --tol")
    p.add_argument("--tol", type=float, default=0.15,
                   help="relative tolerance band (default 0.15)")
    p.add_argument("--json", action="store_true",
                   help="--check: machine-readable row dump")
    args = p.parse_args(argv)
    if args.check:
        with open(args.check[0], "r", encoding="utf-8") as fh:
            base = json.load(fh)
        with open(args.check[1], "r", encoding="utf-8") as fh:
            cand = json.load(fh)
        rows = check_regression(base, cand, tol=args.tol)
        regressed = [r for r in rows if r["verdict"] == "REGRESSED"]
        if args.json:
            print(json.dumps({"rows": rows,
                              "regressed": len(regressed),
                              "compared": len(rows),
                              "tol": args.tol}))
        else:
            for r in rows:
                if r["verdict"] == "ok":
                    continue
                print(f"{r['verdict']:9s} {r['path']}  "
                      f"{r['base']:.6g} -> {r['cand']:.6g}  "
                      f"({r['change_pct']:+.1f}%, "
                      f"{r['direction']}-better)")
            print(f"compared {len(rows)} lanes, "
                  f"{len(regressed)} regressed (tol {args.tol:.0%})")
        return 1 if regressed else 0
    # no --check: print the shipped objective table (docs aid)
    k = knobs_from_env()
    print(f"burn windows: fast={k.fast} slow={k.slow} "
          f"page_burn={k.page_burn} warn_burn={k.warn_burn}")
    for o in default_slos():
        bar = ("observe-only" if o.threshold is None
               else f"{o.op} {o.threshold}")
        print(f"{o.name:20s} {o.signal:45s} {bar}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
