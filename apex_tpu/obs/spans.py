"""Chunk lineage spans: timestamp metadata following one chunk fleet-wide.

A span is a tiny dict riding OUTSIDE the tensor payload on the chunk
message (``msg[SPAN_KEY]`` is a list of spans — one per source chunk
after merges), so the ingest path's bit-parity contracts (PR 2/3:
``merge_chunk_messages`` / ``merge_group_messages`` compare payloads
field for field) never see it:

    {"pv": <param version the chunk was acted under>,
     "hops": {hop: (monotonic, wall), ...}}

Hops, in stream order (all optional — a transport that skips one just
leaves the histogram that needs it un-fed):

    sealed   actor: chunk materialized by the FrameChunkBuilder drain
    send     actor: handed to the chunk queue / socket sender
    recv     learner: decoded off the wire (or polled off the mp queue)
    merge    learner: coalesced into a merged/stacked ingest payload
    stage    learner: H2D staged by the ingest pipeline
    consume  learner: fused/ingest dispatch issued with this chunk
    prio_wb  learner: dispatch returned (the on-device priority
             write-back is fused into that program — this is its host
             issue-complete time, the closest host-observable proxy)

Both clocks are stamped because neither alone survives the fleet:
monotonic is comparable only within one process (frame-age across the
actor->learner boundary uses wall), wall is comparable across hosts only
up to skew (the heartbeat-derived offsets in
:mod:`apex_tpu.fleet.registry` measure that skew; ``obs.merge`` applies
it).  Stamping is first-wins per hop, so a double-instrumented path
(socket recv + pipeline poll) keeps the earlier, truer time.

The learner-side join lives in :class:`LearnerObs`: a bounded
publish-time ledger (version -> publish clocks) plus the two headline
:class:`LatencyHistogram`\\ s — *frame-age-at-train* (consume wall -
sealed wall) and *param-propagation-lag* (consume mono - publish mono of
the version the chunk was ACTED under: how long a published policy takes
to come back as trainable experience, the Ape-X staleness loop measured
end to end).

Everything is stdlib + host clocks: safe on hot loops (J006), and J010
flags any of these calls straying into jit/shard_map trace scope.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, deque

from apex_tpu.utils.metrics import percentile

#: chunk-message metadata key (a LIST of span dicts)
SPAN_KEY = "obs_spans"

#: canonical hop order (lineage trace events pair consecutive present
#: hops).  The three shard_* hops exist only on the sharded replay
#: service path (apex_tpu/replay_service): chunk decoded on the shard
#: socket -> folded into a pre-sampled batch -> batch handed to the
#: learner's pull — so frame-age-at-train stays measurable across the
#: extra network hop (a batch carries the spans of the freshest source
#: chunks folded into it since the previous sample).  The three infer_*
#: hops ride POLICY-REQUEST messages on the inference plane
#: (apex_tpu/infer_service): request shipped by the actor -> coalesced
#: into a server batch -> reply issued — they precede ``sealed`` because
#: acting happens before the transition is recorded, and they keep the
#: extra acting-time network hop visible in the same span vocabulary.
HOPS = ("infer_send", "infer_batch", "infer_reply",
        "sealed", "send", "shard_recv", "shard_sample", "batch_send",
        "recv", "merge", "stage", "consume", "prio_wb")


def enabled() -> bool:
    """Span stamping is on by default; ``APEX_OBS_SPANS=0`` disables it
    (the A/B for "does stamping cost anything on this box")."""
    return os.environ.get("APEX_OBS_SPANS", "1").lower() not in (
        "0", "false", "no")


def _now() -> tuple[float, float]:
    return (time.monotonic(), time.time())


def new_span(param_version: int = 0, hop: str = "sealed") -> dict:
    return {"pv": int(param_version), "hops": {hop: _now()}}


def spans_of(msg) -> list:
    """The message's span list ([] when unstamped/disabled)."""
    if isinstance(msg, dict):
        return msg.get(SPAN_KEY) or []
    return []


def stamp_spans(spans, hop: str) -> None:
    """Stamp ``hop`` on every span that lacks it (first wins: pipeline
    order is monotone, so the earliest stamp is the true hop time)."""
    if not spans:
        return
    t = _now()
    for span in spans:
        span["hops"].setdefault(hop, t)


def stamp(msg, hop: str) -> None:
    """Stamp ``hop`` on a chunk message's spans; no-op when unstamped."""
    stamp_spans(spans_of(msg), hop)


def mark_send(msg, param_version: int = 0) -> None:
    """Actor-side send site: ensure the message carries a span (sealed is
    stamped by ``drain_builder_chunks``; a bare message gets one here),
    record the param version the chunk was acted under, and stamp
    ``send``.  One call per chunk put, both worker loops."""
    if not enabled() or not isinstance(msg, dict):
        return
    spans = msg.get(SPAN_KEY)
    if not spans:
        spans = msg[SPAN_KEY] = [new_span(param_version, hop="sealed")]
    t = _now()
    for span in spans:
        span["pv"] = int(param_version)
        span["hops"].setdefault("send", t)


def merge_spans(msgs: list, hop: str = "merge") -> list:
    """Flatten the span lists of ``msgs`` (merge/stack/aggregate sites)
    and stamp ``hop`` — the merged message carries one span per SOURCE
    chunk, so per-chunk ages survive coalescing."""
    out: list = []
    for m in msgs:
        out.extend(spans_of(m))
    stamp_spans(out, hop)
    return out


class LatencyHistogram:
    """Bounded sliding-window histogram (seconds): record floats, read
    nearest-rank percentiles.  Pure host bookkeeping."""

    def __init__(self, window: int = 4096):
        self._vals: deque[float] = deque(maxlen=window)
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, v: float) -> None:
        v = float(v)
        self._vals.append(v)
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v

    def snapshot(self) -> dict:
        s = sorted(self._vals)
        return {
            "count": self.count,
            "mean_s": round(self.total / self.count, 6) if self.count else 0.0,
            "p50_s": round(percentile(s, 0.50), 6),
            "p90_s": round(percentile(s, 0.90), 6),
            "p99_s": round(percentile(s, 0.99), 6),
            "max_s": round(self.max, 6),
        }


class LearnerObs:
    """Learner-side span join: publish ledger + the two headline
    histograms + sampled chunk-lineage trace events.

    Call order per consumed slot (both the pipelined and serial drains):
    :meth:`pre_consume` immediately before the dispatch (stamps
    ``consume``), :meth:`post_consume` right after the dispatch call
    returns (stamps ``prio_wb``, feeds the histograms, emits lineage
    events).  :meth:`note_publish` records each version's publish time —
    the join key for param-propagation-lag.
    """

    def __init__(self, ring=None, max_versions: int = 1024,
                 clock=time.monotonic, wall=time.time):
        self.frame_age = LatencyHistogram()
        self.param_lag = LatencyHistogram()
        self._pub: OrderedDict[int, tuple[float, float]] = OrderedDict()
        self._max_versions = max_versions
        self.ring = ring
        self._clock = clock
        self._wall = wall
        self.spans_consumed = 0

    # -- publish ledger ----------------------------------------------------

    def note_publish(self, version: int) -> None:
        self._pub[int(version)] = (self._clock(), self._wall())
        while len(self._pub) > self._max_versions:
            self._pub.popitem(last=False)

    # -- consume join ------------------------------------------------------

    def pre_consume(self, spans) -> None:
        stamp_spans(spans, "consume")

    def post_consume(self, spans) -> None:
        if not spans:
            return
        stamp_spans(spans, "prio_wb")
        now_mono, now_wall = self._clock(), self._wall()
        for span in spans:
            self.spans_consumed += 1
            hops = span.get("hops", {})
            sealed = hops.get("sealed")
            if sealed is not None:
                # wall clocks: the only pair comparable across the
                # actor->learner process (or host) boundary
                age = now_wall - sealed[1]
                if age >= 0:
                    self.frame_age.record(age)
            pub = self._pub.get(int(span.get("pv", -1)))
            if pub is not None:
                # mono clocks: publish and consume both happen HERE
                self.param_lag.record(max(0.0, now_mono - pub[0]))
            if self.ring is not None:
                self._emit_lineage(span)

    def _emit_lineage(self, span: dict) -> None:
        """One trace event per consecutive hop pair, on the learner
        ring's wall timebase — the chunk's whole journey renders as one
        stacked track in the merged perfetto timeline."""
        hops = span.get("hops", {})
        present = [(h, hops[h]) for h in HOPS if h in hops]
        for (h1, t1), (h2, t2) in zip(present, present[1:]):
            dur = t2[1] - t1[1]
            if dur < 0:          # cross-host wall skew can invert a hop
                continue
            self.ring.complete_wall(f"{h1}→{h2}", t1[1], dur,
                                    track="chunk-lineage",
                                    args={"pv": span.get("pv", 0)})

    # -- read surface ------------------------------------------------------

    def scalars(self) -> dict:
        """The ``obs_*`` learner scalar set (logged at the trainer's log
        cadence)."""
        fa, pl = self.frame_age.snapshot(), self.param_lag.snapshot()
        return {
            "obs_frame_age_p50_s": fa["p50_s"],
            "obs_frame_age_p99_s": fa["p99_s"],
            "obs_param_lag_p50_s": pl["p50_s"],
            "obs_param_lag_p99_s": pl["p99_s"],
            "obs_spans_consumed": self.spans_consumed,
        }

    def summary(self) -> dict:
        """The e2e bench ``latency`` section body."""
        return {
            "frame_age_at_train_s": self.frame_age.snapshot(),
            "param_propagation_lag_s": self.param_lag.snapshot(),
            "spans_consumed": self.spans_consumed,
        }
