"""Per-role trace ring: bounded, sampled, host-only Chrome trace events.

One :class:`TraceRing` per process, enabled when ``APEX_TRACE_DIR`` is
set (else :func:`get_ring` returns a disabled stub whose methods cost one
attribute check).  Producers are the existing hook points — the actor
families' :class:`~apex_tpu.utils.profiling.PhaseTimer` /
:class:`~apex_tpu.utils.profiling.DispatchGapTimer`, the ingest
pipeline's staging thread, and the learner's chunk-lineage join
(:class:`apex_tpu.obs.spans.LearnerObs`) — all of which record plain
host clock reads into a ``deque(maxlen=...)``: no device sync ever
(apexlint J006), no lock on the append path (GIL-atomic), and a
``sample`` stride bounds the recording rate independently of the ring
bound.

Two timebases per event: ``perf`` (``time.perf_counter`` — in-process
phases/gaps) and ``wall`` (``time.time`` — chunk-lineage hops, whose
stamps cross process boundaries).  At dump time everything is emitted in
WALL microseconds using the anchor captured at ring creation, so each
per-process file is immediately perfetto-loadable and
:mod:`apex_tpu.obs.merge` only has to apply cross-host skew offsets and
re-zero the fleet timeline.

Dump triggers: atexit, a periodic flusher thread (every
``APEX_TRACE_FLUSH_S``, default 10 — so SIGKILLed/terminated roles still
leave a near-complete trace, the same evidence-survival discipline as
``fleet_summary.json``), and SIGUSR2 when the process's main thread can
install handlers.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from collections import deque

#: env knobs (read at ring creation)
TRACE_DIR_ENV = "APEX_TRACE_DIR"
SAMPLE_ENV = "APEX_TRACE_SAMPLE"
CAPACITY_ENV = "APEX_TRACE_CAPACITY"
FLUSH_ENV = "APEX_TRACE_FLUSH_S"


class TraceRing:
    """Bounded ring of trace events for one process."""

    def __init__(self, label: str, enabled: bool = True,
                 capacity: int = 65536, sample: int = 1):
        self.label = label
        self.enabled = enabled
        self.sample = max(1, int(sample))
        self._events: deque[tuple] = deque(maxlen=capacity)
        self._n = 0
        self._tracks: dict[str, int] = {}
        self._tracks_lock = threading.Lock()
        # wall<->perf anchor: dump converts perf-timebase events to wall
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()

    # -- producers (hot-loop safe) ----------------------------------------

    def _tid(self, track: str | None) -> int:
        if track is None:
            return threading.get_ident() % 100_000
        tid = self._tracks.get(track)
        if tid is None:
            with self._tracks_lock:
                tid = self._tracks.setdefault(track,
                                              1000 + len(self._tracks))
        return tid

    def complete(self, name: str, t0_perf: float, dur_s: float,
                 track: str | None = None, args: dict | None = None) -> None:
        """One complete ("X") event on the perf_counter timebase."""
        if not self.enabled:
            return
        self._n += 1
        if self._n % self.sample:
            return
        self._events.append(("perf", name, t0_perf, dur_s,
                             self._tid(track), args))

    def complete_wall(self, name: str, t0_wall: float, dur_s: float,
                      track: str | None = None,
                      args: dict | None = None) -> None:
        """One complete event whose start is a WALL timestamp (lineage
        hops stamped in another process)."""
        if not self.enabled:
            return
        self._n += 1
        if self._n % self.sample:
            return
        self._events.append(("wall", name, t0_wall, dur_s,
                             self._tid(track), args))

    def instant(self, name: str, track: str | None = None,
                args: dict | None = None) -> None:
        if not self.enabled:
            return
        self._events.append(("perf", name, time.perf_counter(), None,
                             self._tid(track), args))

    # -- dump --------------------------------------------------------------

    def _to_wall(self, timebase: str, t: float) -> float:
        if timebase == "wall":
            return t
        return self._anchor_wall + (t - self._anchor_perf)

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (ts/dur in wall microseconds) with the
        clock anchor + label in metadata."""
        pid = os.getpid()
        events: list[dict] = [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": self.label}},
        ]
        with self._tracks_lock:
            tracks = dict(self._tracks)
        for track, tid in tracks.items():
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": track}})
        for timebase, name, t0, dur, tid, args in list(self._events):
            ev = {"name": name, "pid": pid, "tid": tid,
                  "ts": round(self._to_wall(timebase, t0) * 1e6, 1)}
            if dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 1)
            if args:
                ev["args"] = args
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "label": self.label, "pid": pid,
                "clock_sync": {"wall": self._anchor_wall,
                               "perf": self._anchor_perf},
            },
        }

    def dump(self, path: str) -> None:
        """Atomic write (readers of a mid-run flush never see a torn
        file)."""
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.to_chrome(), fh)
        os.replace(tmp, path)


# -- process-global ring ----------------------------------------------------

_RING: TraceRing | None = None
_RING_LOCK = threading.Lock()
_FLUSHER: threading.Thread | None = None


def trace_dir() -> str | None:
    return os.environ.get(TRACE_DIR_ENV) or None


def _ring_path() -> str | None:
    d = trace_dir()
    if d is None or _RING is None:
        return None
    label = _RING.label.replace("/", "_")
    return os.path.join(d, f"trace-{label}-{os.getpid()}.json")


def dump_ring() -> str | None:
    """Flush the process ring to its trace file; returns the path (None
    when disabled).  Never raises — observability must not kill a run."""
    path = _ring_path()
    if path is None or not _RING.enabled:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _RING.dump(path)
        return path
    except OSError:
        return None


def _flusher_loop(interval_s: float) -> None:
    while True:
        time.sleep(interval_s)
        dump_ring()


def _install_triggers() -> None:
    global _FLUSHER
    atexit.register(dump_ring)
    interval = float(os.environ.get(FLUSH_ENV, "10"))
    if interval > 0 and _FLUSHER is None:
        _FLUSHER = threading.Thread(target=_flusher_loop, args=(interval,),
                                    daemon=True, name="apex-trace-flush")
        _FLUSHER.start()
    try:
        # SIGUSR2 -> on-demand dump (main thread only; worker children
        # spawned by mp enter here on their own main threads)
        signal.signal(signal.SIGUSR2, lambda *_: dump_ring())
    except (ValueError, OSError, AttributeError):
        pass                        # non-main thread / platform without it


def get_ring() -> TraceRing:
    """The process's trace ring — a real one when ``APEX_TRACE_DIR`` is
    set, else a disabled stub (every producer call is one attr check)."""
    global _RING
    if _RING is not None:
        return _RING
    with _RING_LOCK:
        if _RING is None:
            d = trace_dir()
            _RING = TraceRing(
                label=f"pid{os.getpid()}",
                enabled=d is not None,
                capacity=int(os.environ.get(CAPACITY_ENV, "65536")),
                sample=int(os.environ.get(SAMPLE_ENV, "1")))
            if d is not None:
                _install_triggers()
    return _RING


def set_process_label(label: str) -> None:
    """Name this process's trace track by its role identity ("actor-3",
    "learner") — the merge tool joins these against the fleet registry's
    peer identities for clock-offset correction."""
    get_ring().label = label


def reset_for_tests() -> None:
    """Drop the process-global ring (tests re-enter with fresh env)."""
    global _RING
    with _RING_LOCK:
        _RING = None
