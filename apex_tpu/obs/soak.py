"""Standing saturation soak: the load study ROADMAP has owed since PR 10.

Drives a loadgen-saturated localhost fleet through ``scripts/
run_local.sh`` for a configurable WALL budget (not a step target — the
learner's step count is an outcome, not an input), samples the fleet SLO
engine (:mod:`apex_tpu.obs.slo`) off the learner's status port every
tick, and emits one machine-readable ``SOAK_*.json``: SLO compliance %
per objective, the alert timeline, throughput vs offered load, and the
measured ``effective_cores`` that makes numbers comparable across boxes
(the bench discipline since part-1d).

The topology is whatever ``run_local.sh`` env twins say — the soak adds
``APEX_LOADGEN=N`` (on-device traffic sources saturating the chunk
plane) and a huge step target so only the wall budget ends the run.
Chaos composes for free: export ``CHAOS_SEED``/``CHAOS_SPEC`` before
launching and the soak records how the SLO engine rode the fault out —
the CI ``slo-smoke`` drill is exactly that (a seeded kill of the
supervised infer server, asserted BURNING -> BREACHED -> RESOLVED from
the artifact this module writes).

Teardown is SIGINT-first to the whole process group: the learner's
train() finally then dumps the final ``fleet_summary.json`` (with the
engine's timeline) that the artifact folds in — a SIGKILL would cost the
last few ticks of evidence.

Usage::

    python -m apex_tpu.obs.soak --seconds 600 --env-id ApexCatchSmall-v0 \
        --actors 2 --envs-per-actor 2 --loadgen 1 --out SOAK_local.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time


def _repo_root() -> str:
    return os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        "..", ".."))


# -- sampling ----------------------------------------------------------------


def sample_status(status_port: int, learner_ip: str = "127.0.0.1",
                  timeout_s: float = 2.0) -> dict | None:
    """One status round-trip to the learner (the trainer's full fleet
    summary, ``slo`` section included), or None while nothing answers
    (pre-barrier, post-teardown)."""
    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.registry import status_request

    comms = dataclasses.replace(CommsConfig(), status_port=status_port)
    try:
        return status_request(comms, learner_ip=learner_ip,
                              timeout_s=timeout_s)
    except Exception:
        return None


def offered_frames(summary: dict) -> int:
    """Offered load: frames the loadgen plane has SEALED device-side
    (its heartbeat gauges), independent of what the learner accepted —
    the offered-vs-ingested gap is the saturation headroom the soak
    measures."""
    total = 0
    for p in summary.get("peers") or []:
        if p.get("role") == "loadgen":
            v = (p.get("gauges") or {}).get("ondevice_frames")
            if isinstance(v, (int, float)):
                total += int(v)
    return total


def make_sample(summary: dict, t_s: float) -> dict:
    """One tick's record in the artifact's ``samples`` array."""
    slo = summary.get("slo") or {}
    return {
        "t_s": round(t_s, 2),
        "steps": summary.get("steps"),
        "ingested": summary.get("ingested"),
        "offered_frames": offered_frames(summary),
        "rates": summary.get("rates") or {},
        "severity": slo.get("severity"),
        "states": {o["name"]: o["state"]
                   for o in slo.get("objectives", [])},
        "alive": (summary.get("metrics") or {}).get("alive"),
        "dead": (summary.get("metrics") or {}).get("dead"),
    }


# -- the artifact ------------------------------------------------------------


def build_artifact(meta: dict, samples: list[dict],
                   final_summary: dict | None) -> dict:
    """The SOAK_*.json body.  Pure — the schema pin in tests/test_slo.py
    drives this directly, no subprocess."""
    final_summary = final_summary or {}
    slo = final_summary.get("slo") or {}
    objectives = slo.get("objectives", [])
    compliance = {o["name"]: o["compliance_pct"] for o in objectives
                  if o.get("compliance_pct") is not None}
    breaches = {o["name"]: o["breaches"] for o in objectives
                if o.get("breaches")}
    steps = final_summary.get("steps") or 0
    ingested = final_summary.get("ingested") or 0
    offered = (samples[-1]["offered_frames"] if samples
               else offered_frames(final_summary))
    span = samples[-1]["t_s"] - samples[0]["t_s"] if len(samples) > 1 \
        else 0.0
    d_steps = (samples[-1]["steps"] or 0) - (samples[0]["steps"] or 0) \
        if len(samples) > 1 else 0
    d_ing = ((samples[-1]["ingested"] or 0)
             - (samples[0]["ingested"] or 0)) if len(samples) > 1 else 0
    d_off = (samples[-1]["offered_frames"]
             - samples[0]["offered_frames"]) if len(samples) > 1 else 0
    return {
        "kind": "apex_soak",
        "version": 1,
        "meta": meta,
        "samples": samples,
        "slo": {
            "compliance": compliance,
            "breaches": breaches,
            "timeline": slo.get("timeline", []),
            "severity_final": slo.get("severity"),
            "objectives": objectives,
        },
        "throughput": {
            "steps_final": steps,
            "ingested_final": ingested,
            "offered_frames_final": offered,
            "steps_per_s": round(d_steps / span, 3) if span > 0 else None,
            "ingest_per_s": round(d_ing / span, 3) if span > 0 else None,
            "offered_per_s": round(d_off / span, 3) if span > 0 else None,
            # loadgen-offered vs fleet-ingested over the sampled span:
            # the share of accepted traffic the device-rate plane
            # supplied (> 1 = loadgen alone outran the learner and the
            # credit windows held the excess back; host-actor chunks in
            # the denominator pull it under 1 on mixed topologies)
            "saturation": (round(d_off / d_ing, 3)
                           if d_ing > 0 and d_off > 0 else None),
        },
    }


def _effective_cores() -> float | None:
    """Measured parallel CPU capacity (the bench part-1d helper), or
    None when the bench module is unimportable here (soak must run from
    a bare checkout without it)."""
    try:
        sys.path.insert(0, _repo_root())
        from bench import _effective_cores as measure
        return round(float(measure()), 3)
    except Exception:
        return None


# -- the drive ---------------------------------------------------------------


def _stop_group(proc: subprocess.Popen) -> None:
    """SIGINT first (learner finally -> final summary dump), escalate to
    SIGTERM/SIGKILL only for stragglers."""
    for sig, wait_s in ((signal.SIGINT, 25.0), (signal.SIGTERM, 10.0),
                        (signal.SIGKILL, 5.0)):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            return
        try:
            proc.wait(timeout=wait_s)
            return
        except subprocess.TimeoutExpired:
            continue


def run_soak(args: argparse.Namespace) -> dict:
    root = _repo_root()
    trace_dir = os.environ.get(
        "APEX_TRACE_DIR", os.path.join("/tmp", f"apex-soak-{os.getpid()}"))
    os.makedirs(trace_dir, exist_ok=True)
    env = dict(os.environ,
               APEX_TRACE_DIR=trace_dir,
               APEX_LOADGEN=str(args.loadgen))
    meta = {
        "env_id": args.env_id, "actors": args.actors,
        "envs_per_actor": args.envs_per_actor, "loadgen": args.loadgen,
        "budget_s": args.seconds, "tick_s": args.tick,
        "started_unix": round(time.time(), 1),
        "chaos_seed": os.environ.get("CHAOS_SEED") or None,
        "chaos_spec": os.environ.get("CHAOS_SPEC") or None,
        "remote_policy": os.environ.get("APEX_REMOTE_POLICY") or None,
        "effective_cores": (None if args.no_effective_cores
                            else _effective_cores()),
    }
    cmd = ["bash", os.path.join(root, "scripts", "run_local.sh"),
           args.env_id, str(args.actors), str(args.steps),
           str(args.envs_per_actor)]
    print(f"soak: {args.seconds:.0f}s budget, topology "
          f"{args.actors} actors x {args.envs_per_actor} envs + "
          f"{args.loadgen} loadgen on {args.env_id} "
          f"(trace dir {trace_dir})", flush=True)
    proc = subprocess.Popen(cmd, env=env, start_new_session=True)
    t0 = time.monotonic()
    deadline = t0 + args.seconds
    samples: list[dict] = []
    try:
        while time.monotonic() < deadline and proc.poll() is None:
            time.sleep(args.tick)
            got = sample_status(args.status_port)
            if got is None:
                continue
            s = make_sample(got, time.monotonic() - t0)
            samples.append(s)
            if args.verbose:
                print(f"soak t={s['t_s']:7.1f}s steps={s['steps']} "
                      f"offered={s['offered_frames']} "
                      f"severity={s['severity']}", flush=True)
    finally:
        if proc.poll() is None:
            _stop_group(proc)
    final = None
    summary_path = os.path.join(trace_dir, "fleet_summary.json")
    try:
        with open(summary_path, "r", encoding="utf-8") as fh:
            final = json.load(fh)
    except (OSError, ValueError):
        pass                         # a dead-on-arrival fleet still
    #                                  yields the sampled half
    artifact = build_artifact(meta, samples, final)
    out = args.out or f"SOAK_{args.env_id}_{int(meta['started_unix'])}.json"
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2)
    os.replace(tmp, out)
    comp = artifact["slo"]["compliance"]
    print(f"soak: wrote {out} — {len(samples)} samples, "
          f"steps={artifact['throughput']['steps_final']}, "
          f"saturation={artifact['throughput']['saturation']}, "
          f"compliance={ {k: comp[k] for k in sorted(comp)} }",
          flush=True)
    return artifact


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.obs.soak",
        description="loadgen saturation soak with SLO sampling "
                    "(emits SOAK_*.json)")
    p.add_argument("--seconds", type=float, default=600.0,
                   help="wall budget (default 600)")
    p.add_argument("--env-id", default="ApexCatchSmall-v0",
                   help="jittable env when --loadgen > 0 (the loadgen "
                        "role fails loud otherwise)")
    p.add_argument("--actors", type=int, default=2)
    p.add_argument("--envs-per-actor", type=int, default=2)
    p.add_argument("--loadgen", type=int, default=1,
                   help="standalone on-device traffic sources "
                        "(APEX_LOADGEN twin; 0 = host actors only)")
    p.add_argument("--steps", type=int, default=10_000_000,
                   help="learner step TARGET handed to run_local.sh — "
                        "deliberately unreachable so the wall budget "
                        "ends the run")
    p.add_argument("--tick", type=float, default=2.0,
                   help="status sampling period, s")
    p.add_argument("--status-port", type=int, default=52003)
    p.add_argument("--out", default=None,
                   help="artifact path (default SOAK_<env>_<ts>.json)")
    p.add_argument("--no-effective-cores", action="store_true",
                   help="skip the parallel-capacity measurement")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)
    artifact = run_soak(args)
    # a soak that never got one sample is a failed soak, loudly
    return 0 if artifact["samples"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
