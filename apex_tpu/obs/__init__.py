"""Experience-lifecycle tracing + metrics export (the obs plane).

Three surfaces, one unit of account — a frame chunk:

* :mod:`apex_tpu.obs.spans` — chunk lineage spans: compact
  (monotonic, wall) timestamp pairs stamped into chunk-message METADATA
  at each hop (sealed -> send -> recv -> merge -> stage -> consume ->
  prio_wb), never into tensor payloads, so the merge/stack bit-parity
  contracts of the ingest pipeline are untouched.  The learner joins
  them against its publish-time ledger into the two headline
  histograms: *frame-age-at-train* and *param-propagation-lag*.
* :mod:`apex_tpu.obs.trace` — a bounded, sampled, host-only trace-event
  ring per process, dumped as Chrome trace-event JSON (perfetto-loadable)
  on exit, periodically, or on SIGUSR2; :mod:`apex_tpu.obs.merge` aligns
  the per-process clocks (heartbeat-derived offsets when a
  ``fleet_summary.json`` is present) into ONE fleet timeline.
* :mod:`apex_tpu.obs.metrics` — Prometheus text exposition served from
  the existing fleet-status REP server (port 52003), so MetricLogger
  tails, rates, fleet states, and the latency histograms are pollable
  by standard tooling — plus the declared metric registry
  (``REGISTERED_GAUGES``/``REGISTERED_FAMILIES``) apexlint J015
  enforces on every literal gauge/family name.

Two judging layers sit on top of those signals:

* :mod:`apex_tpu.obs.slo` — the fleet SLO engine: declarative
  objectives over the fleet-summary signal space, multi-window
  burn-rate evaluation on the learner's health tick, flap-damped
  OK -> BURNING -> BREACHED -> RESOLVED alert machines, ``apex_slo_*``
  exposition rows, the ``--scale-signal slo`` autoscaling input, and
  the ``--check`` bench/soak regression differ.
* :mod:`apex_tpu.obs.soak` — the standing saturation soak: a
  loadgen-saturated fleet driven for a wall budget with the engine
  sampled each tick, emitting the machine-readable ``SOAK_*.json``
  artifact (compliance %, alert timeline, throughput vs offered load).

Everything here is stdlib-only and hot-loop-safe: clock reads and deque
appends, no device syncs (apexlint J006) — and apexlint J010 flags any
clock read or span emission that strays inside jit/shard_map scope.
"""

from apex_tpu.obs.spans import (HOPS, SPAN_KEY, LatencyHistogram,
                                LearnerObs, mark_send, merge_spans,
                                spans_of, stamp, stamp_spans)
from apex_tpu.obs.trace import TraceRing, get_ring, set_process_label

__all__ = ["HOPS", "SPAN_KEY", "LatencyHistogram", "LearnerObs",
           "mark_send", "merge_spans", "spans_of", "stamp", "stamp_spans",
           "TraceRing", "get_ring", "set_process_label"]
