"""Merge per-role trace dumps into ONE perfetto-loadable fleet timeline.

    python -m apex_tpu.obs.merge TRACE_DIR [-o merged_trace.json]
                                 [--fleet-summary fleet_summary.json]

Each role process dumps ``trace-<label>-<pid>.json`` (Chrome trace-event
JSON, timestamps already in its own wall-clock microseconds —
:mod:`apex_tpu.obs.trace`).  Merging is then two corrections plus a
concatenation:

* **Clock alignment.**  Wall clocks agree on one host but skew across
  hosts.  The learner's registry already measures each peer's offset
  from the heartbeat timestamps flowing through
  :mod:`apex_tpu.fleet.heartbeat` (each beat samples
  learner-wall-at-receive - peer-wall-at-send = skew + transit;
  ``clock_offset_s`` is the min-transit median over the recent sample
  window — transit only ever ADDS, so the smallest samples are the
  closest to pure skew, and the median over that low half rides out
  one anomalous beat) and persists it in ``fleet_summary.json``
  together with ``clock_offset_n`` (samples behind the estimate); when
  a summary is given (or found next to the traces), each file whose
  label matches a peer identity is shifted onto the learner's
  timeline.  Files without a matching peer (the learner itself,
  same-host workers) shift by zero.
* **Pid remapping.**  Every file becomes one perfetto process group
  (sequential pids, ``process_name`` = the role label), so two roles
  that happened to share an OS pid across hosts cannot collide.

Finally the whole timeline is re-zeroed at the earliest event, so the
merged view opens at t=0 instead of at the unix epoch.
"""

from __future__ import annotations

import argparse
import glob
import json
import os


def load_offsets(summary: dict) -> dict[str, float]:
    """identity -> clock_offset_s from a ``fleet_summary.json`` snapshot
    (peers without a measured offset map to 0).  The registry's offset is
    already the min-transit median over its sample window (module
    docstring); single-sample peers (``clock_offset_n`` <= 1) still align
    — their estimate just carries that one beat's transit."""
    out: dict[str, float] = {}
    for peer in summary.get("peers", []):
        off = peer.get("clock_offset_s")
        if off is not None:
            out[peer["identity"]] = float(off)
    return out


def offset_quality(summary: dict) -> dict[str, int]:
    """identity -> sample count behind each offset estimate — surfaced in
    the merged trace metadata so a timeline with suspicious alignment can
    be triaged without re-running the fleet (n=1 means one transit of
    noise; n near the window size means the estimator had data)."""
    return {peer["identity"]: int(peer.get("clock_offset_n", 0))
            for peer in summary.get("peers", [])
            if peer.get("clock_offset_s") is not None}


def merge_traces(traces: list[dict],
                 offsets: dict[str, float] | None = None) -> dict:
    """Merge loaded per-process trace dicts into one Chrome trace.

    ``offsets``: seconds to ADD to a file's timestamps, keyed by its
    metadata label (peer wall + offset = learner wall).  Pure function —
    the unit tests drive it with fake skewed clocks.
    """
    offsets = offsets or {}
    merged: list[dict] = []
    labels: list[str] = []
    for i, trace in enumerate(traces):
        meta = trace.get("metadata", {})
        label = meta.get("label", f"proc{i}")
        labels.append(label)
        shift_us = offsets.get(label, 0.0) * 1e6
        pid = i + 1
        for ev in trace.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            merged.append(ev)
        # ensure a process_name row even for files dumped without one
        if not any(ev.get("ph") == "M" and ev.get("name") == "process_name"
                   and ev.get("pid") == pid for ev in merged):
            merged.append({"ph": "M", "pid": pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": label}})
    timed = [ev["ts"] for ev in merged if "ts" in ev]
    t0 = min(timed) if timed else 0.0
    for ev in merged:
        if "ts" in ev:
            ev["ts"] = round(ev["ts"] - t0, 1)
    merged.sort(key=lambda ev: (ev.get("ts", -1.0), ev.get("pid", 0)))
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {"merged_from": labels,
                     "t0_wall_us": round(t0, 1),
                     "offsets_applied": {k: v for k, v in offsets.items()
                                         if k in labels}},
    }


def merge_dir(trace_dir: str, out_path: str,
              fleet_summary: str | None = None) -> dict:
    """Load every ``trace-*.json`` under ``trace_dir``, align, merge,
    write ``out_path``.  Returns the merged trace dict."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace-*.json")))
    if not paths:
        raise FileNotFoundError(f"no trace-*.json files in {trace_dir!r} "
                                f"(set APEX_TRACE_DIR for the run)")
    traces = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as fh:
                traces.append(json.load(fh))
        except (OSError, json.JSONDecodeError) as e:
            print(f"obs.merge: skipping {p}: {e}")
    offsets: dict[str, float] = {}
    quality: dict[str, int] = {}
    if fleet_summary is None:
        candidate = os.path.join(trace_dir, "fleet_summary.json")
        fleet_summary = candidate if os.path.exists(candidate) else None
    if fleet_summary:
        with open(fleet_summary, "r", encoding="utf-8") as fh:
            summary = json.load(fh)
        offsets = load_offsets(summary)
        quality = offset_quality(summary)
    merged = merge_traces(traces, offsets)
    if quality:
        merged["metadata"]["offset_samples"] = {
            k: v for k, v in quality.items()
            if k in merged["metadata"]["merged_from"]}
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(merged, fh)
    os.replace(tmp, out_path)
    return merged


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="apex_tpu.obs.merge",
        description="merge per-role trace dumps into one perfetto timeline")
    p.add_argument("trace_dir", help="directory holding trace-*.json dumps")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default TRACE_DIR/merged_trace.json)")
    p.add_argument("--fleet-summary", default=None,
                   help="fleet_summary.json with per-peer clock_offset_s "
                        "(default: TRACE_DIR/fleet_summary.json if present)")
    args = p.parse_args(argv)
    out = args.out or os.path.join(args.trace_dir, "merged_trace.json")
    try:
        merged = merge_dir(args.trace_dir, out, args.fleet_summary)
    except FileNotFoundError as e:
        print(f"obs.merge: {e}")
        return 1
    n = sum(1 for ev in merged["traceEvents"] if ev.get("ph") != "M")
    print(f"obs.merge: {len(merged['metadata']['merged_from'])} processes, "
          f"{n} events -> {out} (load in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
