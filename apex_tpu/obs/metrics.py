"""Prometheus text exposition for the fleet-status surface.

The learner already serves registry snapshots on the status REP socket
(port 52003, :class:`apex_tpu.fleet.registry.FleetStatusServer`); this
module renders the same process's live state — MetricLogger history
tails, RateCounter rates, fleet registry counts + per-peer gauges, and
the obs-plane latency histograms — as Prometheus text exposition
(version 0.0.4), served from that same socket for the ``b"metrics"``
request frame.  ``python -m apex_tpu.runtime --role status --metrics``
is the bundled scraper (one REQ round-trip, prints the text), and any
tool that can issue the two-frame zmq REQ gets the same document — a
fleet becomes pollable instead of only greppable from stdout and
``fleet_summary.json``.
"""

from __future__ import annotations

import re

#: Declared heartbeat-gauge keys.  Every literal key a role puts into
#: ``Heartbeat.gauges`` (directly, via a ``gauges_fn`` hook, or from a
#: method named ``gauges``) must come from this set — apexlint J015
#: (``unregistered-gauge``) enforces it, so a typo'd or undeclared gauge
#: is a lint failure instead of a silently unscrapeable metric the SLO
#: engine can never objective on.  Grow this set WITH the emitter.
REGISTERED_GAUGES = frozenset({
    # infer server serving gauges (infer_service/service.py)
    "queue_depth", "batch_p50", "batch_p90", "coalesce_ms_p50",
    "requests", "replies", "dry_replies", "rejected",
    # remote-policy actor health (infer_service/client.py); infer_shard/
    # infer_epoch_seen attribute fallback + stale-epoch counts to the
    # worker's home shard in the sharded serving tier (serving/shard.py)
    "infer_remote", "infer_fallbacks", "infer_stale_epoch",
    "infer_reprobes", "infer_rt_ms_p50", "infer_rt_ms_p90",
    "infer_rt_ms_p99", "infer_shard", "infer_epoch_seen",
    # serving-tier version gate, per shard (infer_service/service.py)
    # and the deployment controller's own beats (serving/deploy.py)
    "serve_epoch", "serve_version", "serve_pinned", "serve_held",
    "serve_rollbacks", "serve_state_code", "serve_deployments",
    "serve_promotions",
    # on-device rollout planes (training/anakin.py, --role loadgen)
    "ondevice_chunks", "ondevice_frames", "ondevice_dispatches",
    "dispatches", "chunks", "frames", "transitions", "rollout_len",
    "n_envs",
    # fused on-device training plane (apex_tpu/ondevice/fused.py):
    # the fused-0 heartbeat's counter block, also the fleet_summary
    # "ondevice" section the fused-smoke CI drill asserts on
    "macro_steps", "train_steps", "prio_writebacks", "external_ingest",
    "steps_per_dispatch", "train_per_step", "dp", "train_ratio",
    # evaluator eval-ladder scores (runtime/roles.py — the SLO engine's
    # model-quality signal and the future canary/promotion gate input)
    "eval_band", "eval_episodes", "eval_score_last", "eval_score_mean",
    # multi-tenant plane (apex_tpu/tenancy): partition/entry counts on
    # shared-plane beats, the host's accelerator flag (the placement
    # scheduler's 2311.09445 input)
    "tenants", "backend_accel",
    # population plane (apex_tpu/population): live lineage count on the
    # pbt-ctl controller's beats
    "lineages",
    # wire codec (runtime/codec.py): sender-side byte counters + the
    # realized compression ratio on actor/loadgen beats, and the
    # publisher's cumulative delta-frame bytes on the learner side
    "wire_bytes_out", "wire_bytes_raw", "codec_ratio",
    "param_delta_bytes",
})

#: Declared Prometheus exposition families: the fixed row names the
#: scrape surface serves (literal keys of the ``counters``/
#: ``histograms``/``labeled`` dicts handed to :func:`render`).  J015's
#: other half — dynamic names (scalar tails, per-peer gauges) ride the
#: registered ``fleet_peer_gauge``/``slo_*`` families instead of
#: minting rows ad hoc.
REGISTERED_FAMILIES = frozenset({
    # fleet registry exposition (render_fleet)
    "fleet_peer_up", "fleet_peer_fps", "fleet_peer_chunks_sent",
    "fleet_peer_gauge",
    # learner exposition (training/apex.py _metrics_text)
    "learner_steps_total", "transitions_ingested_total", "param_version",
    "stat_drops_total", "frame_age_at_train_seconds",
    "param_propagation_lag_seconds",
    # SLO engine rows (obs/slo.py prometheus_sections)
    "slo_severity", "slo_ticks", "slo_state", "slo_value",
    "slo_burn_fast", "slo_breaches", "slo_compliance_pct",
    # serving-tier deployment rows (serving/deploy.py
    # prometheus_sections): the canary machine + per-shard pin view
    "serving_state", "serving_deployments", "serving_promotions",
    "serving_rollbacks", "serving_canary_shards",
    "serving_incumbent_epoch", "serving_incumbent_version",
    "serving_shard_pinned", "serving_shard_version",
    # tenancy rows (tenancy/scheduler.py prometheus_sections): the
    # placement controller's admission counts + per-tenant state/bands
    "tenancy_tenants", "tenancy_admissions", "tenancy_evictions",
    "tenancy_rebalances", "tenancy_tenant_state",
    "tenancy_tenant_shards",
    # population rows (population/controller.py prometheus_sections):
    # the PBT machine — decision counts + per-lineage state/generation/
    # score
    "population_lineages", "population_decisions",
    "population_exploits", "population_explores",
    "population_lineage_state", "population_lineage_generation",
    "population_lineage_score",
    # wire-codec rows (training/apex.py _metrics_text): learner-side
    # decode counts + the param-delta publisher's byte counters; the
    # per-actor codec_ratio/wire_bytes_* gauges ride fleet_peer_gauge
    "wire_codec_chunks", "wire_codec_rejected", "wire_param_publishes",
    "wire_param_keyframes", "wire_param_deltas", "wire_param_delta_bytes",
    "wire_param_bytes_out", "wire_param_bytes_raw",
    "wire_keyframes_forced",
})

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Metric-name-safe spelling of a scalar tag ("learner/loss" ->
    "learner_loss")."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if f != f:
        return "NaN"
    return repr(f)


def render(gauges: dict | None = None,
           counters: dict | None = None,
           histograms: dict | None = None,
           labeled: dict | None = None,
           prefix: str = "apex") -> str:
    """Render one exposition document.

    ``gauges`` / ``counters``: name -> value.
    ``histograms``: name -> a :class:`~apex_tpu.obs.spans.LatencyHistogram`
    snapshot dict (rendered as a Prometheus summary: quantile series +
    ``_count``).
    ``labeled``: name -> list of ``(label_dict, value)`` gauge rows
    (per-peer fleet state).
    """
    lines: list[str] = []

    def emit(name: str, kind: str, rows: list[str]) -> None:
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(rows)

    for name, value in sorted((gauges or {}).items()):
        if value is None:
            continue
        emit(f"{prefix}_{sanitize(name)}", "gauge",
             [f"{prefix}_{sanitize(name)} {_fmt(value)}"])
    for name, value in sorted((counters or {}).items()):
        if value is None:
            continue
        emit(f"{prefix}_{sanitize(name)}", "counter",
             [f"{prefix}_{sanitize(name)} {_fmt(value)}"])
    for name, snap in sorted((histograms or {}).items()):
        base = f"{prefix}_{sanitize(name)}"
        rows = [f'{base}{{quantile="{q}"}} {_fmt(snap.get(key))}'
                for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"),
                               ("0.99", "p99_s"))
                if snap.get(key) is not None]
        rows.append(f"{base}_count {int(snap.get('count', 0))}")
        emit(base, "summary", rows)
    for name, series in sorted((labeled or {}).items()):
        base = f"{prefix}_{sanitize(name)}"
        rows = []
        for labels, value in series:
            body = ",".join(
                f'{sanitize(k)}="{str(v).replace(chr(34), "")}"'
                for k, v in sorted(labels.items()))
            rows.append(f"{base}{{{body}}} {_fmt(value)}")
        if rows:
            emit(base, "gauge", rows)
    return "\n".join(lines) + "\n"


def render_fleet(snapshot: dict, prefix: str = "apex") -> tuple[dict, dict]:
    """(gauges, labeled) sections from a FleetRegistry snapshot — shared
    by the trainer's metrics_fn and the tests."""
    m = snapshot.get("metrics", {})
    gauges = {f"fleet_{k}": v for k, v in m.items() if v is not None}
    labeled = {
        "fleet_peer_up": [({"identity": p["identity"], "role": p["role"],
                            "tenant": p.get("tenant") or "t0",
                            "state": p["state"]},
                           1.0 if p["state"] == "ALIVE" else 0.0)
                          for p in snapshot.get("peers", [])],
        "fleet_peer_fps": [({"identity": p["identity"]}, p.get("fps", 0.0))
                           for p in snapshot.get("peers", [])],
        "fleet_peer_chunks_sent": [({"identity": p["identity"]},
                                    p.get("chunks_sent", 0))
                                   for p in snapshot.get("peers", [])],
        # role-specific serving gauges off the heartbeats (infer server
        # queue depth / batch percentiles, remote-policy actor fallback
        # counts) — labeled by peer and gauge name so a new role's
        # numbers scrape without a code change here
        "fleet_peer_gauge": [({"identity": p["identity"], "gauge": k}, v)
                             for p in snapshot.get("peers", [])
                             for k, v in sorted(
                                 (p.get("gauges") or {}).items())],
    }
    return gauges, labeled


def scalar_tails(history: dict) -> dict:
    """Latest value per MetricLogger tag (history is ``tag ->
    deque[(step, value)]``; reads race benignly with the trainer's
    appends — deque append/[-1] are GIL-atomic)."""
    out = {}
    for tag, dq in list(history.items()):
        try:
            out[tag] = dq[-1][1]
        except (IndexError, TypeError):
            continue
    return out


def make_http_sidecar(comms, port: int, learner_ip: str | None = None,
                      bind: str = "0.0.0.0", timeout_s: float = 5.0):
    """Plain-HTTP adapter over the zmq-REQ metrics surface (PR 6
    follow-up): returns an ``http.server`` instance whose ``GET
    /metrics`` (or ``/``) proxies one :func:`metrics_request` round-trip
    per scrape, so a stock Prometheus server polls the fleet directly —
    no textfile collector, no custom scrape tooling.  The caller drives
    ``serve_forever()``; an unreachable learner answers 503 with a
    comment line, never an empty 200 (Prometheus marks the target down
    instead of recording a silent gap)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):           # noqa: N802 (http.server's spelling)
            path = self.path.split("?", 1)[0]
            if path not in ("/", "/metrics"):
                self.send_error(404)
                return
            try:
                text = metrics_request(comms, learner_ip=learner_ip,
                                       timeout_s=timeout_s)
            except Exception as e:  # a scrape must never kill the sidecar
                text = None
                err = f"{type(e).__name__}"
            else:
                err = "no reply"
            if text is None:
                body = (f"# learner metrics unavailable ({err}) at "
                        f"{learner_ip or comms.learner_ip}:"
                        f"{comms.status_port}\n").encode()
                self.send_response(503)
            else:
                body = text.encode("utf-8", errors="replace")
                self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args) -> None:
            pass                    # scrape-per-15s noise stays off stdout

    return ThreadingHTTPServer((bind, port), _Handler)


def metrics_request(comms, learner_ip: str | None = None,
                    timeout_s: float = 5.0) -> str | None:
    """Client half of the scrape: one REQ ``b"metrics"`` round-trip to
    the learner's status server; the exposition text, or None when
    nothing answers."""
    import zmq

    sock = zmq.Context.instance().socket(zmq.REQ)
    ip = learner_ip or comms.learner_ip
    sock.connect(f"tcp://{ip}:{comms.status_port}")
    try:
        sock.send(b"metrics")
        if sock.poll(int(timeout_s * 1000), zmq.POLLIN):
            return sock.recv().decode("utf-8", errors="replace")
        return None
    finally:
        sock.close(linger=0)
