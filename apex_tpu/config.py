"""Configuration system.

Replaces the reference's single shared argparse (``origin_repo/arguments.py:5-83``)
plus env-var role identity (``origin_repo/actor.py:18-25``,
``origin_repo/learner.py:23-27``) with typed dataclasses.  Defaults reproduce the
reference's hyperparameters behind its published numbers
(``origin_repo/arguments.py:9-74``), with TPU-specific knobs added (mesh shape,
compute dtype, replay residency).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Sequence


@dataclass(frozen=True)
class ReplayConfig:
    """Prioritized replay hyperparameters (reference: arguments.py:41-50)."""

    # PER-CHIP transition capacity.  The reference's single buffer holds 2e6
    # transitions on a 128GB replay host (arguments.py:45-46); here replay is
    # HBM-resident and SHARDED over the dp mesh, so per-chip capacity stays
    # modest (2**19 ~ 524k transitions ~ 4.1 GiB of 84x84 frames) and an
    # 8-chip slice holds 2**22 ~ 4.2M transitions total — above reference
    # parity without overflowing any one chip's 16GB HBM.
    capacity: int = 2 ** 19
    alpha: float = 0.6               # priority exponent
    beta: float = 0.4                # IS-weight exponent (annealed toward 1 by drivers)
    # Transitions over which beta anneals linearly to 1.  A fixed horizon —
    # NOT derived from warmup, which CI configs shrink to nothing (full IS
    # correction against a tiny fresh buffer is high-variance and was
    # destabilizing the concurrent pipeline's learning).
    beta_anneal: int = 500_000
    warmup: int = 50_000             # learner gated until this many transitions (arguments.py:47-48)
    # Clamp floor for priorities entering the sum/min trees (pre-alpha).  The
    # reference's ADDITIVE 1e-6 on |td| (utils.py:77, memory.py:464) stays
    # hard-coded in the loss/actor priority calcs, exactly as it does there.
    eps: float = 1e-6
    # TPU knobs
    device_resident: bool = True     # HBM struct-of-arrays vs. host (C++/numpy) buffer
    frame_pool: bool = False         # dedup frame-pool storage layout for stacked pixels
    # Drivers refuse to allocate a replay shard whose estimated footprint
    # exceeds this (leaving headroom for params/activations on a 16GB chip).
    hbm_budget_gb: float = 12.0

    def __post_init__(self) -> None:
        if self.capacity <= 0 or self.capacity & (self.capacity - 1):
            raise ValueError(f"capacity must be a power of 2, got {self.capacity}")


@dataclass(frozen=True)
class LearnerConfig:
    """Learner-loop hyperparameters (reference: arguments.py:49-66, ApeX.py:37)."""

    batch_size: int = 512
    lr: float = 6.25e-5
    # StepLR(step_size=1000, gamma=0.99) parity (DQN.py:39, ApeX.py:38);
    # 0 = constant lr (the reference's distributed learner,
    # origin_repo/learner.py:145)
    lr_decay_steps: int = 1000
    lr_decay_rate: float = 0.99
    rmsprop_decay: float = 0.95      # torch RMSprop alpha (ApeX.py:37)
    rmsprop_eps: float = 1.5e-7
    rmsprop_centered: bool = True
    gamma: float = 0.99
    n_steps: int = 3
    max_grad_norm: float = 40.0
    target_update_interval: int = 2500
    publish_interval: int = 25       # param publish period, learner steps
    save_interval: int = 5000
    # TPU knobs
    compute_dtype: str = "bfloat16"  # MXU-native matmul dtype; params stay f32
    ingest_chunk: int = 512          # transitions folded into each fused step
    mesh_shape: tuple[int, ...] = (1,)
    mesh_axes: tuple[str, ...] = ("dp",)
    # >1: when at least this many chunks are queued (i.e. the learner is
    # the bottleneck), drain and run them as ONE lax.scan dispatch of
    # scan_steps bit-identical fused steps — amortizes host->device
    # round-trip latency, the dominant per-step overhead on relay-backed
    # chips (training/learner.py:scan_fused_steps).  Both families (DQN
    # and AQL), single-shard only; on a dp>1 mesh it quietly stays at 1.
    scan_steps: int = 1
    # Async ingest pipeline (training/ingest_pipeline.py): a staging thread
    # drains worker chunks, merges ingest-only chunks into one payload, and
    # device_puts the next dispatch's data into a bounded on-device ring
    # while the current fused step runs — host decode, H2D staging, and
    # device compute overlap instead of serializing.  Order-preserving and
    # numerics-neutral (bit-parity pinned in tests/test_ingest_pipeline.py
    # and, for dp>1, tests/test_sharded_pipeline.py).  Covers every
    # concurrent trainer: single-shard learners stage chunk-granular
    # slots; dp>1 meshes stage whole round-robin groups (per-shard merged
    # when ingest-only, NamedSharding device_put over the dp axis) with
    # per-chip PRNG keys pre-split + pre-placed off the hot loop.  The
    # single-process drivers quietly ignore it.  False = the serial
    # drain (kept reachable for A/B).
    ingest_pipeline: bool = True
    # Staged-slot ring depth.  2 = classic double buffering (the next
    # dispatch's data is in HBM while the current one runs); deeper rings
    # buy nothing but memory and backpressure latency.
    pipeline_depth: int = 2
    # Max frame chunks (dp>1: round-robin groups) coalesced into ONE
    # ingest payload when the learner is not train-eligible (warmup fill /
    # replay-ratio cap) — each merge of m turns m dispatches + m H2D
    # copies into one.
    pipeline_merge: int = 8


@dataclass(frozen=True)
class ActorConfig:
    """Actor-fleet hyperparameters (reference: arguments.py:9-40, batchrecorder.py:121)."""

    n_actors: int = 8
    # Env slots driven by EACH worker process through one batched policy
    # call per step (apex_tpu/actors/vector.py).  The exploration ladder
    # spans all n_actors * n_envs_per_actor slots, so 8 x 32 reproduces the
    # exploration spectrum of 256 scalar actor processes.  1 = the
    # reference's one-env-per-process topology (batchrecorder.py:79).
    n_envs_per_actor: int = 1
    send_interval: int = 50          # transitions per shipped batch
    update_interval: int = 400       # env steps between param refresh polls
    eps_base: float = 0.4            # per-actor ladder eps_base^(1 + i/(N-1)*eps_alpha)
    eps_alpha: float = 7.0
    # Anneal each worker's epsilon 1.0 -> its ladder value over this many of
    # its own env steps (exp decay).  0 = reference behavior (fixed ladder,
    # batchrecorder.py:121) — correct for large fleets where low-eps actors
    # can free-ride on the explorers' data; small fleets (CI, few actors)
    # need the anneal or greedy actors feed degenerate data from step 0.
    eps_anneal_steps: int = 0
    # None = the env's own limit; reference Atari deployments use 50_000
    # (wrapper.py:282-298 TimeLimit via arguments.py max_episode_length)
    max_episode_length: int | None = None
    # In-host chunk transport: the native shared-memory ring
    # (apex_tpu/native/) when it is buildable, else mp.Queue.  The reference
    # always pays mp.Queue's pickle->pipe->feeder-thread copies
    # (batchrecorder.py:111-112).
    shm_data_plane: bool = True
    # Ring slot size; 0 = drivers compute it from the frame spec (or a 4MiB
    # default when they can't).  A chunk message must fit one slot.
    shm_slot_bytes: int = 0
    # Alternating double-buffered sampling (actors/vector.py, the Stooke &
    # Abbeel alternating sampler): the B env slots split into two
    # half-groups whose jitted policy calls dispatch asynchronously, so one
    # group's env stepping overlaps the other group's inference.  Per-group
    # PRNG keys derive via fold_in(group) on the per-step key IN BOTH
    # MODES, so on/off trajectories are bit-identical per slot
    # (tests/test_vector.py pins it) — the knob is a pure scheduling A/B,
    # same discipline as LearnerConfig.ingest_pipeline.  Families fall
    # back to the serial interleave when B < 2 (one group: nothing to
    # overlap).  The win needs a spare host core or an off-host policy
    # device; a 1-core box shows parity, not regression.
    double_buffer: bool = True
    # Vector steps between periodic ActorTimingStat emissions (policy-wait
    # / env-step / drain fractions + frames/s, shipped on the stat queue
    # and surfaced in the learner logs and bench "actor_plane").  0 = off.
    timing_interval: int = 256
    # Centralized batched inference (apex_tpu/infer_service): instead of
    # running the policy on the actor host's CPU, each half-group's
    # stacked observations ship to the `--role infer` server, which
    # batches requests ACROSS actor processes into one device dispatch
    # and returns (actions, q, param_version).  Rides the double-buffer
    # split: one group's round-trip overlaps the other group's env
    # stepping.  Remote-served results are BIT-IDENTICAL to the local
    # policy for the same params + key chain (tests/test_infer.py pins
    # it), and every actor keeps its local policy as the fallback — a
    # wedged/dead server costs comms.infer_wait_s once, then the actor
    # runs local until the re-probe finds the server again.  DQN vector
    # families only (the AQL/R2D2 remote families are ROADMAP items).
    remote_policy: bool = False


@dataclass(frozen=True)
class EnvConfig:
    env_id: str = "SeaquestNoFrameskip-v4"   # reference default (arguments.py:9-10)
    frame_stack: int = 4
    frame_skip: int = 4
    episodic_life: bool = True
    clip_rewards: bool = True
    seed: int = 1122                 # reference default seed (arguments.py:14)


@dataclass(frozen=True)
class R2D2Config:
    """Recurrent-family (R2D2-style) hyperparameters.

    The reference lists recurrent DQN as an unimplemented TODO
    (``README.md:5``); these defaults follow the R2D2 recipe scaled to the
    reference's network widths.  Sequence length stored per replay item is
    ``burn_in + unroll + n_steps``.
    """

    burn_in: int = 8            # state-warmup prefix, no loss/gradient
    unroll: int = 16            # loss positions per sequence
    # sequence start spacing; None derives unroll // 2 (R2D2's 1/2
    # overlap) so raising unroll keeps the documented overlap invariant
    stride: int | None = None
    lstm_features: int = 128    # recurrent width (reference head scale;
                                # R2D2 itself uses 512 — raise for Atari)
    # sequences per ingest batch / pool message — ONE constant shared by
    # the single-process driver, the concurrent trainer, and the socket
    # actor role so every message has the same fixed shape (the scan
    # dispatch and shm slot sizing both assume it)
    sequence_group: int = 4


@dataclass(frozen=True)
class AQLConfig:
    """AQL proposal-action Q-learning knobs (reference: model.py:170, AQL.py:41-42)."""

    propose_sample: int = 100
    uniform_sample: int = 400
    action_var: float = 0.25
    proposal_lr: float = 1e-4
    q_lr: float = 1e-4
    entropy_coef: float = 0.01
    # CosineAnnealingLR(T_max=max_step, eta_min=lr/1000) horizon for the
    # single-process driver (AQL.py:18,48-49); the concurrent driver
    # ignores it (AQL_dis constructs no schedulers)
    cosine_lr_steps: int = 1_000_000


@dataclass(frozen=True)
class CommsConfig:
    """Multi-host plane (reference: replay.py:48-74, learner.py:57-68, actor.py:110-114)."""

    replay_ip: str = "127.0.0.1"
    learner_ip: str = "127.0.0.1"
    batch_port: int = 51001          # actor -> replay transition stream
    prios_port: int = 51002          # learner -> replay priority updates
    sample_port: int = 51003         # replay -> learner sampled batches
    param_port: int = 52001          # learner PUB param broadcast
    barrier_port: int = 52002        # startup handshake ROUTER
    max_outstanding_sends: int = 3   # actor credit window (actor.py:110-112)
    max_outstanding_prios: int = 16  # learner->replay window (learner.py:121-127)
    param_hwm: int = 3               # PUB high-water mark (learner.py:60)
    status_port: int = 52003         # fleet-status REP (--role status)
    # Learner-side decoder threads unpickling chunk payloads off the
    # socket thread — the reference's N recv_batch pullers
    # (learner.py:71-114, count arguments.py:73-74)
    n_recv_batch_procs: int = 4
    # -- fleet control plane (apex_tpu/fleet) ------------------------------
    # Every role beats on the stat channel at this cadence; the learner's
    # FleetRegistry drives the JOINING -> ALIVE -> SUSPECT -> DEAD machine
    # from the thresholds below.  dead_after_s must comfortably exceed
    # suspect_after_s, and suspect_after_s the beat interval, or healthy
    # peers flap under ordinary queue backpressure.
    heartbeat_interval_s: float = 2.0
    suspect_after_s: float = 6.0
    dead_after_s: float = 15.0
    # Actor/evaluator park threshold: no param publish for this long means
    # the learner is gone (a live learner republishes at least every
    # ~10 * publish_min_seconds ~ 2s) — stop stepping, keep env + builder
    # state, and retry the barrier/param race with jittered backoff.
    park_after_s: float = 10.0
    rejoin_backoff_s: float = 1.0    # first retry delay (doubles per miss)
    rejoin_backoff_max_s: float = 8.0
    rejoin_attempt_s: float = 5.0    # per-attempt barrier/param race window
    # -- registry reactions (PR 8: the registry ACTS, not just observes) ---
    # When at least this fraction of actor-role peers is DEAD, the learner
    # RELAXES its replay-ratio floor (min_train_ratio) so the surviving
    # actors are not backpressured into starvation by a throughput target
    # sized for the full fleet; the floor restores as peers rejoin.
    # None = never relax.
    relax_floor_dead_frac: float | None = 0.5
    # A dead/respawned shard's traffic falls back to the learner; the
    # actor re-probes the shard (credit window reset + one real send)
    # every this many seconds so a RECOVERED shard gets its stream back
    # without an actor restart (the stale credit window used to wedge it
    # out forever).
    shard_reprobe_s: float = 10.0
    # -- sharded replay service (apex_tpu/replay_service) ------------------
    # 0 = in-learner replay (replay dissolved into the learner's HBM, the
    # default since PR 0).  N > 0 restores the reference's standalone
    # replay role (origin_repo/replay.py) as N shard processes: actors
    # hash sealed chunks to shards (stable chunk-id hash, per-shard
    # credit window), each shard owns one FramePoolReplay segment tree
    # and serves pre-sampled batches, and the learner pulls round-robin
    # + ships priority write-backs to the owning shard.
    replay_shards: int = 0
    # shard s binds ONE ROUTER at replay_port_base + s (chunk ingest from
    # actors AND pull/prio traffic from the learner multiplex on it)
    replay_port_base: int = 53001
    # strict: a shard samples batch j+1 only after batch j's priority
    # write-back lands (and defers the next ingest behind it), so the
    # shard replays the exact in-learner ingest->sample->write-back
    # interleave — N=1 is bit-identical to in-learner replay (pinned in
    # tests/test_replay_service.py).  False = the reference's loose
    # semantics: pre-sample ahead, apply write-backs whenever they land.
    replay_strict_order: bool = True
    # loose-mode pre-sample depth (batches staged ahead of the learner's
    # pulls); strict mode is structurally depth-1
    replay_presample: int = 2
    # Shard durability: a shard snapshots its whole replay state (segment
    # tree + frame pool + PRNG chain + counters) to the snapshot dir
    # (--replay-snapshot-dir) at most every this many seconds — atomic
    # tmp+rename, same discipline as fleet_summary.json — and a
    # supervised respawn restores it, rejoining WARM instead of refilling
    # from live streams.  0 = snapshots off (the pre-PR-8 behavior).
    replay_snapshot_s: float = 0.0
    # -- centralized inference plane (apex_tpu/infer_service) --------------
    # `--role infer` binds ONE ROUTER here; remote-policy actors connect
    # their per-worker DEALERs to it (ActorConfig.remote_policy).
    infer_port: int = 54001
    infer_ip: str = "127.0.0.1"      # host the infer server runs on
    # Adaptive request coalescing: the server collects policy requests
    # until infer_batch_max are queued OR infer_window_ms elapsed since
    # the first, then runs them as ONE scan-stacked device dispatch
    # (request count padded to pow2-quantized widths so compile count
    # stays bounded — the PR 2 scan-stack discipline).
    infer_batch_max: int = 16
    infer_window_ms: float = 2.0
    # Actor-side fallback: a request unanswered for this long falls back
    # to the LOCAL policy (bit-identical by the parity contract, so the
    # fallback changes scheduling, never trajectories) and marks the
    # server down — a dead/wedged infer server never stalls an actor
    # beyond one wait (the learner-direct fallback contract from the
    # replay service, applied to inference).
    infer_wait_s: float = 1.0
    # While the server is marked down the actor runs local-only and
    # re-probes with one real request every this many seconds, so a
    # supervised respawn gets its traffic back without an actor restart
    # (the PR 8 dead-shard re-probe discipline).
    infer_reprobe_s: float = 5.0
    # Keep the server's params device-placed (device_put on every
    # subscribed publish).  On a shared-device deployment this is the
    # device-to-device copy path; skipped automatically on the CPU
    # backend (same gate as the ingest pipeline's staging ring).
    infer_device_params: bool = False
    # -- sharded serving tier (apex_tpu/serving) ---------------------------
    # N infer servers, shard s binding infer_port + s (the replay
    # service's port-base discipline); remote-policy workers route to a
    # home shard by a stable identity hash (serving/shard.py), each
    # shard keeping the single-server down-marker/fallback/re-probe
    # semantics.  1 (default) IS the PR 9 topology — one server on
    # infer_port.  The whole fleet must agree, so it rides COMMON like
    # the ports.  The `--role serve-ctl` deployment controller canaries
    # new model versions onto a shard fraction via the servers'
    # epoch-fenced param gate (serving/deploy.py).
    infer_shards: int = 1
    # -- wire codec (apex_tpu/runtime/codec.py) ----------------------------
    # Chunk wire codec for every ChunkSender this process builds: "raw"
    # (legacy pickle, bit-identical wire), "delta" (XOR frame-delta +
    # RLE, the ~sparse Catch shape) or "dict" (per-chunk deflate
    # dictionary, the pixel-stack shape).  Empty = resolve from the
    # APEX_WIRE_CODEC env twin, default raw.  Receivers negotiate per
    # chunk off the wire tag, so senders never need fleet agreement.
    wire_codec: str = ""
    # Sparse param-delta publish: deltas carry only the leaves changed
    # since the last keyframe; first publish and every learner-epoch
    # bump stay dense, so fencing semantics are untouched.
    param_delta: bool = False
    # Dense keyframe at least every N publishes (bounds how long a
    # CONFLATE subscriber that missed a keyframe waits for recovery).
    param_keyframe_every: int = 16


@dataclass(frozen=True)
class ApexConfig:
    """Top-level bundle; one object configures every role."""

    env: EnvConfig = field(default_factory=EnvConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)
    learner: LearnerConfig = field(default_factory=LearnerConfig)
    actor: ActorConfig = field(default_factory=ActorConfig)
    aql: AQLConfig = field(default_factory=AQLConfig)
    r2d2: R2D2Config = field(default_factory=R2D2Config)
    comms: CommsConfig = field(default_factory=CommsConfig)

    def replace(self, **sections: Any) -> "ApexConfig":
        return dataclasses.replace(self, **sections)


@dataclass(frozen=True)
class RoleIdentity:
    """Process role identity, injected via env vars by deploy scripts
    (reference: deploy/actor.sh:4-9; actor.py:18-25)."""

    role: str = "learner"            # learner | actor | replay | evaluator
    actor_id: int = 0
    n_actors: int = 1
    replay_ip: str = "127.0.0.1"
    learner_ip: str = "127.0.0.1"

    @classmethod
    def from_env(cls, environ: os._Environ | dict | None = None) -> "RoleIdentity":
        e = dict(environ if environ is not None else os.environ)
        return cls(
            role=e.get("APEX_ROLE", "learner"),
            actor_id=int(e.get("ACTOR_ID", 0)),
            n_actors=int(e.get("N_ACTORS", 1)),
            replay_ip=e.get("REPLAY_IP", "127.0.0.1"),
            learner_ip=e.get("LEARNER_IP", "127.0.0.1"),
        )


def small_test_config(
    capacity: int = 1024,
    batch_size: int = 32,
    n_actors: int = 2,
    env_id: str = "ApexCartPole-v0",
) -> ApexConfig:
    """A config sized for CI: tiny buffer, tiny batch, numpy-native env."""
    return ApexConfig(
        env=EnvConfig(env_id=env_id, frame_stack=1, clip_rewards=False,
                      episodic_life=False),
        replay=ReplayConfig(capacity=capacity, warmup=max(2 * batch_size, 64)),
        learner=LearnerConfig(batch_size=batch_size, ingest_chunk=batch_size,
                              target_update_interval=100, compute_dtype="float32"),
        actor=ActorConfig(n_actors=n_actors, send_interval=16),
    )


def flat_dict(cfg: ApexConfig) -> dict[str, Any]:
    """Pretty/loggable flattened view (reference: utils.print_args, utils.py:9-12)."""
    out: dict[str, Any] = {}
    for section in dataclasses.fields(cfg):
        sub = getattr(cfg, section.name)
        for f in dataclasses.fields(sub):
            out[f"{section.name}.{f.name}"] = getattr(sub, f.name)
    return out
