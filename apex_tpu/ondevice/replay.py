"""HBM-resident prioritized replay: the stateful device twin of
:class:`~apex_tpu.replay.frame_pool.FramePoolReplay`.

The host pool is a frozen SPEC of three pure programs (add / sample /
update_priorities) that drivers orchestrate from the hot loop.
:class:`DeviceFramePool` binds those SAME programs — jit-compiled with
donated state so HBM never double-buffers — to one resident
:class:`~apex_tpu.replay.frame_pool.FramePoolState` plus its own PRNG
chain, with the exact key-split discipline the concurrent trainer uses
(``self.key, k = split(self.key)`` before every sample).  Bit-parity
against a host-orchestrated pool — every tree field, the key chain, the
sampled indices and batches — is pinned in
``tests/test_ondevice_replay.py``; there is no second implementation to
drift.

Durability is the PR 8 host-spill path: :meth:`snapshot` serializes the
whole pool (state + key chain + counters + spec pins) through the
checkpoint machinery (:func:`apex_tpu.training.checkpoint.save_bundle`,
atomic tmp+rename) and :meth:`restore` refuses a shape-shifting restore
with an actionable error, exactly like the replay-shard snapshots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.replay.frame_pool import FramePoolReplay


class DeviceFramePool:
    """One HBM-resident frame-pool replay shard driven from the host.

    ``spec`` is the frozen :class:`FramePoolReplay`; the pool owns the
    state, the sample-key chain, and host-side counters.  All three
    mutating methods re-point ``self.state`` at the donated result — the
    previous buffers are invalid the moment a method returns, which is
    the point: replay never leaves HBM and never double-buffers.
    """

    def __init__(self, spec: FramePoolReplay, seed: int = 0, key=None):
        self.spec = spec
        self.state = spec.init()
        self.key = jax.random.key(seed) if key is None else key
        self._add = jax.jit(spec.add, donate_argnums=(0,))
        self._update = jax.jit(spec.update_priorities, donate_argnums=(0,))
        self._sample_jits: dict[int, object] = {}
        # host observability (snapshot meta; the fused loop keeps its own)
        self.adds = 0
        self.samples = 0
        self.updates = 0
        self.ingested = 0

    # -- the three programs ------------------------------------------------

    def add(self, chunk: dict, priorities) -> None:
        # host-driven twin of the jitted spec program — this method body
        # never traces (J002's name-based jit-scope match sees the
        # spec.add jit above and cannot tell the two apart)
        n = int(chunk["n_trans"])  # apexlint: disable=J002
        self.state = self._add(self.state, chunk,
                               jnp.asarray(priorities, jnp.float32))
        self.adds += 1
        self.ingested += n

    def sample(self, batch_size: int, beta):
        """``(batch, weights, idx)`` — advances the key chain exactly as
        the concurrent trainer's ``self.key, k = split(self.key)`` does,
        so a host-pool replay of the same chunk stream samples the same
        indices (the parity pin)."""
        fn = self._sample_jits.get(batch_size)
        if fn is None:
            fn = jax.jit(self.spec.sample, static_argnums=(2,))
            self._sample_jits[batch_size] = fn
        self.key, k = jax.random.split(self.key)
        self.samples += 1
        return fn(self.state, k, batch_size, jnp.float32(beta))

    def update_priorities(self, idx, priorities) -> None:
        self.state = self._update(self.state, jnp.asarray(idx),
                                  jnp.asarray(priorities, jnp.float32))
        self.updates += 1

    # -- host-spill durability (PR 8 checkpoint machinery) -----------------

    def _spec_pins(self) -> dict:
        s = self.spec
        return dict(capacity=s.capacity,
                    frame_shape=list(s.frame_shape),
                    frame_stack=s.frame_stack,
                    frame_capacity=s.f_capacity,
                    frame_dtype=s.frame_dtype,
                    alpha=s.alpha, eps=s.eps)

    def snapshot(self, path: str) -> str:
        """Spill the whole pool to ``path`` (atomic tmp+rename)."""
        from apex_tpu.training.checkpoint import save_bundle
        bundle = dict(state=self.state, key=jax.random.key_data(self.key))
        meta = dict(counters=dict(adds=self.adds, samples=self.samples,
                                  updates=self.updates,
                                  ingested=self.ingested),
                    **self._spec_pins())
        return save_bundle(path, bundle, meta)

    def restore(self, path: str) -> None:
        """Warm-restore state + key chain + counters; a snapshot written
        by a DIFFERENT spec refuses loudly instead of silently reshaping
        the ring (the replay-shard snapshot contract)."""
        from apex_tpu.training.checkpoint import restore_bundle
        pins = self._spec_pins()
        target = dict(state=self.spec.init(),
                      key=jax.random.key_data(self.key))
        bundle, meta = restore_bundle(path, target)
        for k, want in pins.items():
            got = meta.get(k)
            if got != want:
                raise ValueError(
                    f"snapshot {path!r} was written by a different pool "
                    f"spec: {k}={got!r} != {want!r} — restore into a "
                    f"matching FramePoolReplay or discard the snapshot")
        self.state = bundle["state"]
        self.key = jax.random.wrap_key_data(bundle["key"])
        c = meta.get("counters", {})
        self.adds = int(c.get("adds", 0))
        self.samples = int(c.get("samples", 0))
        self.updates = int(c.get("updates", 0))
        self.ingested = int(c.get("ingested", 0))

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        return {"adds": self.adds, "samples": self.samples,
                "updates": self.updates, "ingested": self.ingested,
                "size": int(np.asarray(jax.device_get(self.state.size))),
                "hbm_bytes": self.spec.hbm_bytes()}
