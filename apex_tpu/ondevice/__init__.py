"""On-device replay plane: HBM-resident prioritized replay + the fused
Sebulba train step.

PR 10 moved rollouts on-device (:mod:`apex_tpu.training.anakin`); this
package moves the REST of the training loop after it, so one jitted
program per dispatch runs the whole

    rollout -> ingest -> prioritized sample -> train -> priority write-back

cycle with the host in the loop only for checkpoints, obs spans, and the
socket fleet (arxiv 1803.02811's co-location argument taken to its
Podracer/Sebulba limit).

* :mod:`apex_tpu.ondevice.replay` — :class:`DeviceFramePool`, the
  stateful HBM-resident twin of
  :class:`apex_tpu.replay.frame_pool.FramePoolReplay` (same three pure
  programs, jit-compiled with donated state, own PRNG chain, host-spill
  snapshots riding the checkpoint machinery).
* :mod:`apex_tpu.ondevice.fused` — :class:`FusedStep` (the scanned
  macro-step program) and :class:`FusedApexTrainer` (the
  ``--rollout fused`` driver on the ConcurrentTrainer path).
"""

from apex_tpu.ondevice.replay import DeviceFramePool  # noqa: F401
