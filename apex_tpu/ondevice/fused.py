"""The fused Sebulba train step: the whole Ape-X cycle in one dispatch.

PR 10's ``--rollout ondevice`` fused acting (env + policy + chunk
assembly in one scan) but still woke the host per chunk: poll -> ingest
dispatch -> train dispatch -> write-back, with the replay-ratio loop in
between.  :class:`FusedStep` closes the remaining hops — ONE jitted
program per dispatch scans ``steps_per_dispatch`` macro steps of

    rollout segment (AnakinRollout._dispatch, verbatim)
    -> acting-TD priorities (device twin of the numpy epilogue)
    -> masked ingest of every sealed chunk (FramePoolReplay.add, valid=)
    -> [warm] P x (prioritized sample -> update_from_batch
                   -> priority write-back)

donating the train state AND the replay state so HBM never
double-buffers.  The host wakes once per dispatch for the epilogue:
episode stats, counters, publish/checkpoint/obs cadence.

Contracts (pinned in tests/test_ondevice_replay.py):

* **fused == serial.**  A ``steps_per_dispatch=N`` dispatch is
  bit-identical to N ``steps_per_dispatch=1`` dispatches — same macro
  body, same pre-split key chains — so the scan composition is pure
  dispatch-latency amortization (the ``scan_fused_steps`` contract,
  lifted to the whole training cycle).
* **device priorities are self-consistent, not host-identical.**  The
  acting-TD priorities compute in-program, where XLA's backend contracts
  ``reward + discount*max`` into one FMA rounding; the host builder's
  numpy rounds twice (the 1-ulp drift :mod:`apex_tpu.training.anakin`
  documents — measured to survive ``lax.optimization_barrier``, bitcast
  round-trips, and f64 detours on XLA:CPU, which is why PR 10 put its
  priorities in the host epilogue).  The fused plane's replay is fed
  exclusively by this program, so the contract that matters — the same
  priorities on every path that can meet in one tree — holds by
  construction; the <= 1-ulp envelope vs the numpy epilogue is pinned.
* **masked ingest.**  Unsealed slots of the fixed ``[B, M]`` chunk grid
  ingest with ``valid=False`` — a bit-exact no-op on every replay field
  (see :meth:`FramePoolReplay.add`).

Differences from the host loop, by design: acting params are the LIVE
``train_state.params`` (zero staleness — the Anakin end-state), the
replay ratio is STRUCTURAL (``B * rollout_len`` transitions ingested per
``train_per_step`` updates; there is no host band controller inside the
program), warmup gates training via ``lax.cond`` on the device ingest
counter, and beta anneals on-device in f32 off that same counter (which
saturates at ``max(warmup, beta_anneal)+1`` — past both thresholds the
exact count is irrelevant, so i32 never wraps).
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.training.apex import ApexTrainer

#: metric keys td_update returns — the cond's cold branch must mirror
#: the structure exactly
_METRIC_KEYS = ("loss", "grad_norm", "q_mean", "td_mean")


def acting_priorities(out):
    """Device twin of ``AnakinRollout.rollout``'s numpy priority
    epilogue: ``|reward + discount*max(qn) - q_taken| + 1e-6`` over the
    ``[B, M, K]`` chunk grid.  XLA contracts the multiply-add into one
    FMA rounding where numpy rounds twice — a <= 1-ulp divergence the
    module docstring scopes (the fused replay never mixes these with
    host-computed priorities for the same transition)."""
    import jax.numpy as jnp

    q_taken = jnp.take_along_axis(
        out["q0"], out["action"][..., None], -1)[..., 0]
    target = out["reward"] + out["discount"] * out["qn"].max(-1)
    return jnp.abs(target - q_taken) + jnp.float32(1e-6)


class FusedStep:
    """The jitted dispatch program plus its host-side chain/counters.

    ``core`` is the family's :class:`~apex_tpu.training.learner.
    LearnerCore` (``update_from_batch`` is the one family hook — AQL's
    proposal sampler and R2D2's carry slot in behind it), ``replay`` the
    :class:`FramePoolReplay` spec, ``engine`` a PR 10
    :class:`~apex_tpu.training.anakin.AnakinRollout` whose carry/key
    this object now owns.
    """

    def __init__(self, core, replay, engine, *, warmup: int,
                 beta: float, beta_anneal: int,
                 steps_per_dispatch: int = 4, train_per_step: int = 1):
        import jax
        import jax.numpy as jnp

        if steps_per_dispatch < 1 or train_per_step < 1:
            raise ValueError(
                f"steps_per_dispatch={steps_per_dispatch} and "
                f"train_per_step={train_per_step} must be >= 1 "
                f"(--steps-per-dispatch / APEX_STEPS_PER_DISPATCH)")
        self.core = core
        self.replay = replay
        self.engine = engine
        self.N = int(steps_per_dispatch)
        self.P = int(train_per_step)
        self.warmup = int(warmup)
        self.beta0 = float(beta)
        self.anneal = max(1, int(beta_anneal))
        # the device warm/anneal counter saturates here: beyond both
        # thresholds the exact count no longer matters, so i32 is safe
        # for arbitrarily long runs
        self._ing_cap = np.int32(max(self.warmup, self.anneal) + 1)
        self.ingested_dev = jnp.int32(0)
        self._jit = jax.jit(self._dispatch, donate_argnums=(0, 1, 2, 3, 4))
        # host counters (fleet_summary "ondevice" block; CI asserts)
        self.dispatches = 0
        self.macro_steps = 0
        self.train_steps = 0
        self.prio_writebacks = 0
        self.chunks = 0
        self.frames = 0
        self.transitions = 0
        self.external_ingest = 0

    # -- device program ----------------------------------------------------

    def _beta_at(self, ing):
        import jax.numpy as jnp
        frac = jnp.minimum(jnp.float32(1.0),
                           ing.astype(jnp.float32) / self.anneal)
        return (jnp.float32(self.beta0)
                + jnp.float32(1.0 - self.beta0) * frac)

    def _train_block(self, ts, rs, keys, ing):
        from jax import lax
        beta = self._beta_at(ing)

        def body(carry, k):
            ts2, rs2 = carry
            batch, weights, idx = self.replay.sample(
                rs2, k, self.core.batch_size, beta)
            ts2, prios, metrics = self.core.update_from_batch(
                ts2, batch, weights)
            rs2 = self.replay.update_priorities(rs2, idx, prios)
            return (ts2, rs2), metrics

        (ts, rs), metrics = lax.scan(body, (ts, rs), keys)
        return ts, rs, metrics

    def _macro(self, carry, xs):
        import jax.numpy as jnp
        from jax import lax

        ts, rs, c, cf, ing = carry
        rkey, skeys = xs
        eng = self.engine
        c, cf, out = eng._dispatch(ts.params, eng.epsilons, c, cf, rkey)
        B, M = eng.B, eng.M
        prios = acting_priorities(out)                       # [B, M, K]
        sealed = out["sealed"]                               # [B]
        mask = jnp.arange(M, dtype=jnp.int32)[None, :] < sealed[:, None]

        def flat(a):
            return a.reshape((B * M,) + a.shape[2:])

        slots = {k: flat(out[k]) for k in
                 ("frames", "action", "reward", "discount",
                  "obs_ref", "next_ref", "nf", "nt")}

        def ingest(carry2, xs2):
            rs2, ing2 = carry2
            sl, pr, do = xs2
            chunk = dict(frames=sl["frames"], n_frames=sl["nf"],
                         n_trans=sl["nt"], action=sl["action"],
                         reward=sl["reward"], discount=sl["discount"],
                         obs_ref=sl["obs_ref"], next_ref=sl["next_ref"])
            rs2 = self.replay.add(rs2, chunk, pr, valid=do)
            ing2 = jnp.minimum(ing2 + jnp.where(do, sl["nt"], 0),
                               self._ing_cap)
            return (rs2, ing2), ()

        (rs, ing), _ = lax.scan(ingest, (rs, ing),
                                (slots, flat(prios), mask.reshape(-1)))

        warm = ing >= jnp.int32(self.warmup)

        def do_train(args):
            ts2, rs2 = args
            return self._train_block(ts2, rs2, skeys, ing)

        def skip(args):
            ts2, rs2 = args
            zero = jnp.zeros((self.P,), jnp.float32)
            return ts2, rs2, {k: zero for k in _METRIC_KEYS}

        ts, rs, metrics = lax.cond(warm, do_train, skip, (ts, rs))
        done, ep_ret, ep_len = out["stepped"]
        ys = dict(metrics=metrics, trained=warm,
                  sealed=sealed.sum(), sealed_max=sealed.max(),
                  n_trans=jnp.where(mask, out["nt"], 0).sum(),
                  done=done, ep_ret=ep_ret, ep_len=ep_len)
        return (ts, rs, c, cf, ing), ys

    def _dispatch(self, ts, rs, c, cf, ing, rkeys, skeys):
        from jax import lax
        (ts, rs, c, cf, ing), ys = lax.scan(
            self._macro, (ts, rs, c, cf, ing), (rkeys, skeys))
        return ts, rs, c, cf, ing, ys

    # -- host surface ------------------------------------------------------

    def dispatch(self, train_state, replay_state, sample_key):
        """One device program: N macro steps.  Advances the engine's
        rollout chain and the caller's sample chain with the exact split
        discipline a serial run would, returns ``(train_state,
        replay_state, sample_key, info)``."""
        import jax
        import jax.numpy as jnp

        from apex_tpu.actors.pool import EpisodeStat

        eng = self.engine
        rkeys, skeys = [], []
        for _ in range(self.N):
            eng.key, rk = jax.random.split(eng.key)
            rkeys.append(rk)
            row = []
            for _ in range(self.P):
                sample_key, k = jax.random.split(sample_key)
                row.append(k)
            skeys.append(jnp.stack(row))
        (train_state, replay_state, eng.carry, eng.carry_frames,
         self.ingested_dev, ys) = self._jit(
            train_state, replay_state, eng.carry, eng.carry_frames,
            self.ingested_dev, jnp.stack(rkeys), jnp.stack(skeys))
        got = jax.device_get(ys)
        if int(got["sealed_max"].max(initial=0)) > eng.M - 1:
            raise RuntimeError(
                f"fused outbox overflow: {int(got['sealed_max'].max())} "
                f"seals > {eng.M - 1} sealed slots — raise rollout_len "
                f"headroom")
        done, ep_ret, ep_len = got["done"], got["ep_ret"], got["ep_len"]
        stats = [EpisodeStat(eng.slot_ids[b], float(ep_ret[m, t, b]),
                             int(ep_len[m, t, b]))
                 for m in range(self.N) for t in range(eng.T)
                 for b in range(eng.B) if done[m, t, b]]
        trained_mask = np.asarray(got["trained"], bool)
        trained = int(trained_mask.sum()) * self.P
        metrics = None
        if trained:
            metrics = {k: float(np.asarray(v)[trained_mask].mean())
                       for k, v in got["metrics"].items()}
        transitions = int(got["n_trans"].sum())
        self.dispatches += 1
        self.macro_steps += self.N
        self.train_steps += trained
        self.prio_writebacks += trained
        self.chunks += int(got["sealed"].sum())
        self.frames += self.N * eng.T * eng.B
        self.transitions += transitions
        info = dict(stats=stats, metrics=metrics, train_steps=trained,
                    transitions=transitions,
                    frames=self.N * eng.T * eng.B)
        return train_state, replay_state, sample_key, info

    def note_external_ingest(self, n: int) -> None:
        """Host-path chunks (hybrid socket actors) ingested outside the
        fused program still advance the device warm/anneal counter."""
        import jax.numpy as jnp
        self.ingested_dev = jnp.minimum(
            self.ingested_dev + jnp.int32(n), self._ing_cap)
        self.external_ingest += int(n)

    def sync_ingested(self, n: int) -> None:
        """Re-seed the device counter after a checkpoint restore."""
        import jax.numpy as jnp
        self.ingested_dev = jnp.minimum(jnp.int32(min(n, 2 ** 31 - 1)),
                                        self._ing_cap)

    def rebind(self, core) -> None:
        """Re-jit against a rebuilt core (live lr application — one
        recompile per explore, the apply_hparams contract)."""
        import jax
        self.core = core
        self._jit = jax.jit(self._dispatch, donate_argnums=(0, 1, 2, 3, 4))

    def counters(self) -> dict:
        """``fleet_summary.json``'s ``ondevice`` block (the fused-smoke
        CI job asserts these are nonzero)."""
        return {"dispatches": self.dispatches,
                "macro_steps": self.macro_steps,
                "train_steps": self.train_steps,
                "prio_writebacks": self.prio_writebacks,
                "chunks": self.chunks, "frames": self.frames,
                "transitions": self.transitions,
                "external_ingest": self.external_ingest,
                "steps_per_dispatch": self.N,
                "train_per_step": self.P,
                "rollout_len": self.engine.T, "n_envs": self.engine.B}


class _IdlePool:
    """The in-host fused topology has no actor plane at all: rollouts
    live inside the dispatch.  This is the minimal pool surface the
    ConcurrentTrainer helpers probe."""

    def start(self) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def publish_params(self, version: int, params) -> None:
        pass

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        return []

    def poll_stats(self) -> list:
        return []


class FusedApexTrainer(ApexTrainer):
    """``--rollout fused``: the ConcurrentTrainer-path driver whose hot
    loop is one :class:`FusedStep` dispatch per iteration.

    Reuses the whole ApexTrainer substrate — model/replay/optimizer
    construction, checkpoint bundle (``replay_state`` IS the on-device
    pool, so the PR 8 machinery host-spills it for free), fleet
    registry/status/ctl surface, SLO engine, publish cadence — and
    replaces only the chunk-driven drain with the fused dispatch.  The
    socket pool (when one is attached) keeps serving evaluators and the
    param channel; any host-actor chunks that arrive are absorbed into
    the same replay state between dispatches (hybrid mode).

    Graceful refusals name their knobs: non-jittable envs fail in
    ``make_jax_env``'s ValueError, a dp>1 mesh fails here before any
    pool spawns, and non-DQN families fail in the CLI/role wiring.
    """

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio=None, min_train_ratio=None,
                 checkpoint_dir: str | None = None, pool=None,
                 respawn_workers: bool = True,
                 rollout_len: int | None = None,
                 steps_per_dispatch: int = 4, train_per_step: int = 1):
        cfg = config or ApexConfig()
        if int(np.prod(cfg.learner.mesh_shape)) > 1:
            raise ValueError(
                f"--rollout fused requires a single-chip learner mesh "
                f"(mesh_shape={cfg.learner.mesh_shape}) — set --mesh-dp 1 "
                f"(APEX_MESH_DP=1); dp>1 learners stay on --rollout "
                f"ondevice/host (ROADMAP: fused x dp mesh)")
        # non-jittable env ids refuse HERE, before any pool/worker spawns
        from apex_tpu.envs.registry import make_jax_env
        make_jax_env(cfg.env.env_id, cfg.env)
        super().__init__(cfg, logdir=logdir, verbose=verbose,
                         publish_min_seconds=publish_min_seconds,
                         train_ratio=train_ratio,
                         min_train_ratio=min_train_ratio,
                         checkpoint_dir=checkpoint_dir,
                         pool=pool if pool is not None else _IdlePool(),
                         respawn_workers=respawn_workers)
        from apex_tpu.training.anakin import make_anakin_engine
        engine = make_anakin_engine(cfg, rollout_len=rollout_len)
        self.fused = FusedStep(
            self.core, self.replay, engine,
            warmup=cfg.replay.warmup, beta=cfg.replay.beta,
            beta_anneal=cfg.replay.beta_anneal,
            steps_per_dispatch=steps_per_dispatch,
            train_per_step=train_per_step)

    # -- the fused hot loop ------------------------------------------------

    def train(self, total_steps: int, max_seconds: float = 3600.0,
              log_every: int = 200):
        """Run (at least) ``total_steps`` MORE learner updates — the
        dispatch granularity means up to ``steps_per_dispatch *
        train_per_step - 1`` overshoot."""
        import jax.numpy as jnp

        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        from apex_tpu.fleet.registry import FleetRegistry
        from apex_tpu.obs import spans as obs_spans
        from apex_tpu.obs.trace import get_ring, set_process_label
        from apex_tpu.utils.profiling import DispatchGapTimer

        cfg = self.cfg
        pool = self.pool
        target_steps = self.steps_rate.total + total_steps
        if self.actor_timing is None:
            self.actor_timing = {}
        set_process_label("learner")
        ring = get_ring()
        if self._obs is None:
            self._obs = obs_spans.LearnerObs(ring=ring)
        gap = self._dispatch_gap = DispatchGapTimer(
            ring=ring, track="learner-fused-loop")
        if self.fleet is None:
            self.fleet = FleetRegistry(cfg.comms)
        pool.start()
        set_epoch = getattr(pool, "set_learner_epoch", None)
        if set_epoch is not None:
            set_epoch(self.learner_epoch)
        self._start_status_server()
        # the fused plane beats into the registry like AnakinPool's
        # ondevice-0 does, so the status table shows it next to any
        # socket peers
        beat = HeartbeatEmitter(
            "fused-0", role="rollout",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self.fused.counters)
        try:
            self._publish()
            last_publish = time.monotonic()
            t_end = last_publish + max_seconds
            last_pub_step = self.steps_rate.total
            last_health = last_publish
            self._episode_idx = 0
            metrics = None

            while self.steps_rate.total < target_steps:
                now = time.monotonic()
                stop = self._stop_requested
                if now > t_end or (stop is not None and stop.is_set()):
                    break
                gap.about_to_dispatch()
                (self.train_state, self.replay_state, self.key,
                 info) = self.fused.dispatch(
                    self.train_state, self.replay_state, self.key)
                gap.dispatch_returned()
                if info["train_steps"]:
                    self.steps_rate.tick(info["train_steps"])
                    if info["metrics"] is not None:
                        metrics = info["metrics"]
                self.ingested += info["transitions"]
                self.frames_rate.tick(info["transitions"])
                for stat in info["stats"]:
                    self.log.scalars(
                        {"episode_reward": stat.reward,
                         "episode_length": stat.length,
                         "actor_id": stat.actor_id}, self._episode_idx)
                    self._episode_idx += 1
                # hybrid: host-actor chunks absorb between dispatches
                # (ingest-only — the fused program owns the train cadence)
                for msg in pool.poll_chunks(64, timeout=0):
                    self.replay_state = self._ingest(
                        self.replay_state, msg["payload"],
                        jnp.asarray(msg["priorities"]))
                    n_new = int(msg["n_trans"])
                    self.ingested += n_new
                    self.frames_rate.tick(n_new)
                    self.fused.note_external_ingest(n_new)
                beat.tick(info["frames"])
                hb = beat.maybe_beat(self.param_version)
                if hb is not None:
                    self.fleet.observe(hb)

                steps = self.steps_rate.total
                if (self.checkpointer is not None
                        and steps - self._last_save
                        >= cfg.learner.save_interval):
                    self.save_checkpoint()
                    self._last_save = steps
                if steps:
                    due = (now - last_publish >= self.publish_min_seconds
                           and (steps - last_pub_step
                                >= cfg.learner.publish_interval
                                or now - last_publish
                                > 10 * self.publish_min_seconds))
                else:
                    due = (getattr(pool, "needs_warmup_republish", False)
                           and now - last_publish
                           > 10 * self.publish_min_seconds)
                if due:
                    self._publish()
                    last_publish = now
                    last_pub_step = steps
                if self.respawn_workers and now - last_health >= 5.0:
                    self._health_tick(steps)
                    last_health = now
                self._drain_stats(steps)
                if metrics is not None \
                        and steps - self._last_log >= log_every:
                    extra = gap.snapshot()
                    if self._obs is not None:
                        extra |= self._obs.scalars()
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate,
                           "param_version": self.param_version,
                           "ingested": self.ingested} | extra, steps)
                    self._last_log = steps
        finally:
            if self._fleet_status is not None:
                self._fleet_status.stop()
                self._fleet_status = None
            self._dump_fleet_summary()
            pool.cleanup()
            stop = self._stop_requested
            if stop is not None:
                stop.clear()
        return self

    # -- surface integration ----------------------------------------------

    def fleet_summary(self):
        snap = super().fleet_summary()
        if snap is not None and getattr(self, "fused", None) is not None:
            # the fused-smoke CI drill asserts these from the persisted
            # summary (dispatches/chunks/transitions + >=1 write-back)
            snap["metrics"]["ondevice"] = self.fused.counters()
        return snap

    def _apply_counters(self, meta: dict) -> None:
        super()._apply_counters(meta)
        self.fused.sync_ingested(self.ingested)

    def apply_hparams(self, h: dict) -> dict:
        applied = super().apply_hparams(h)
        if "lr" in applied:
            # the fused program closed over the old core's optimizer —
            # rebind + re-jit (one recompile per explore, same contract
            # as the host loop's hot-fn rebuild)
            self.fused.rebind(self.core)
        return applied
