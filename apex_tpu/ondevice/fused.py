"""The fused Sebulba train step: the whole Ape-X cycle in one dispatch.

PR 10's ``--rollout ondevice`` fused acting (env + policy + chunk
assembly in one scan) but still woke the host per chunk: poll -> ingest
dispatch -> train dispatch -> write-back, with the replay-ratio loop in
between.  :class:`FusedStep` closes the remaining hops — ONE jitted
program per dispatch scans ``steps_per_dispatch`` macro steps of

    rollout segment (AnakinRollout._dispatch, verbatim)
    -> acting-TD priorities (device twin of the numpy epilogue)
    -> masked ingest of every sealed chunk (FramePoolReplay.add, valid=)
    -> [warm] P x (prioritized sample -> update_from_batch
                   -> priority write-back)

donating the train state AND the replay state so HBM never
double-buffers.  The host wakes once per dispatch for the epilogue:
episode stats, counters, publish/checkpoint/obs cadence.

Contracts (pinned in tests/test_ondevice_replay.py):

* **fused == serial.**  A ``steps_per_dispatch=N`` dispatch is
  bit-identical to N ``steps_per_dispatch=1`` dispatches — same macro
  body, same pre-split key chains — so the scan composition is pure
  dispatch-latency amortization (the ``scan_fused_steps`` contract,
  lifted to the whole training cycle).
* **device priorities are self-consistent, not host-identical.**  The
  acting-TD priorities compute in-program, where XLA's backend contracts
  ``reward + discount*max`` into one FMA rounding; the host builder's
  numpy rounds twice (the 1-ulp drift :mod:`apex_tpu.training.anakin`
  documents — measured to survive ``lax.optimization_barrier``, bitcast
  round-trips, and f64 detours on XLA:CPU, which is why PR 10 put its
  priorities in the host epilogue).  The fused plane's replay is fed
  exclusively by this program, so the contract that matters — the same
  priorities on every path that can meet in one tree — holds by
  construction; the <= 1-ulp envelope vs the numpy epilogue is pinned.
* **masked ingest.**  Unsealed slots of the fixed ``[B, M]`` chunk grid
  ingest with ``valid=False`` — a bit-exact no-op on every replay field
  (see :meth:`FramePoolReplay.add`).

Differences from the host loop, by design: acting params are the LIVE
``train_state.params`` (zero staleness — the Anakin end-state), the
replay ratio is STRUCTURAL by default (``B * rollout_len`` transitions
ingested per ``train_per_step`` updates) unless ``train_ratio`` is set —
then a device-side budget (f32 saturating at 2**24, exact-integer range)
accumulates ``ratio`` per ingested transition, spends ``batch_size`` per
update, and gates each train slot with ``lax.cond`` so the one host knob
serves fused and serial modes alike.  Warmup gates training via
``lax.cond`` on the device ingest counter, and beta anneals on-device in
f32 off that same counter (which saturates at ``max(warmup,
beta_anneal)+1`` — past both thresholds the exact count is irrelevant,
so i32 never wraps).

**dp mesh (PR 17).**  With ``mesh=`` the whole macro-scan runs under
``shard_map`` over the ``dp`` axis: env lanes partition as contiguous
blocks (chip ``s`` owns lanes ``[s*B/dp, (s+1)*B/dp)``), each chip
feeds its OWN replay-pool partition (the replay state arrives stacked
``[dp, ...]`` from :meth:`ShardedLearner.shard_replay_state`), each
train slot samples ``batch_size/dp`` per chip and ``pmean``s gradients
inside ``update_from_batch(axis_name="dp")``, and the warm/anneal
counter ``psum``s the per-chip ingest so warmup/beta stay GLOBAL
quantities.  Per-chip PRNG chains are split host-side with the serial
discipline (one ``split`` per macro / per train slot, then fanned
``split(key, dp)`` across chips), so the dp=1 chain is the dp=N chain's
prefix and the scan-composition parity holds at every width.
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig
from apex_tpu.training.apex import ApexTrainer

#: metric keys td_update returns — the cond's cold branch must mirror
#: the structure exactly
_METRIC_KEYS = ("loss", "grad_norm", "q_mean", "td_mean")


def acting_priorities(out):
    """Device twin of ``AnakinRollout.rollout``'s numpy priority
    epilogue: ``|reward + discount*max(qn) - q_taken| + 1e-6`` over the
    ``[B, M, K]`` chunk grid.  XLA contracts the multiply-add into one
    FMA rounding where numpy rounds twice — a <= 1-ulp divergence the
    module docstring scopes (the fused replay never mixes these with
    host-computed priorities for the same transition)."""
    import jax.numpy as jnp

    q_taken = jnp.take_along_axis(
        out["q0"], out["action"][..., None], -1)[..., 0]
    target = out["reward"] + out["discount"] * out["qn"].max(-1)
    return jnp.abs(target - q_taken) + jnp.float32(1e-6)


class FusedStep:
    """The jitted dispatch program plus its host-side chain/counters.

    ``core`` is the family's :class:`~apex_tpu.training.learner.
    LearnerCore` (``update_from_batch`` is the one family hook — AQL's
    proposal sampler and R2D2's carry slot in behind it), ``replay`` the
    :class:`FramePoolReplay` spec, ``engine`` a PR 10
    :class:`~apex_tpu.training.anakin.AnakinRollout` whose carry/key
    this object now owns.
    """

    def __init__(self, core, replay, engine, *, warmup: int,
                 beta: float, beta_anneal: int,
                 steps_per_dispatch: int = 4, train_per_step: int = 1,
                 mesh=None, train_ratio: float | None = None):
        import jax
        import jax.numpy as jnp

        if steps_per_dispatch < 1 or train_per_step < 1:
            raise ValueError(
                f"steps_per_dispatch={steps_per_dispatch} and "
                f"train_per_step={train_per_step} must be >= 1 "
                f"(--steps-per-dispatch / APEX_STEPS_PER_DISPATCH)")
        self.core = core
        self.replay = replay
        self.engine = engine
        self.mesh = mesh
        self.n_dp = 1 if mesh is None else int(mesh.shape["dp"])
        self._axis = None if self.n_dp == 1 else "dp"
        self.ratio = None if train_ratio is None else float(train_ratio)
        if core.batch_size % self.n_dp:
            raise ValueError(
                f"learner.batch_size={core.batch_size} must be divisible "
                f"by the dp axis (dp={self.n_dp}, from learner.mesh_shape "
                f"/ --mesh-dp) — raise batch_size or shrink the mesh")
        self._batch_chip = core.batch_size // self.n_dp
        if engine.B % self.n_dp:
            raise ValueError(
                f"fused dp={self.n_dp} shards the env lanes: "
                f"B={engine.B} envs (actor.n_actors x "
                f"actor.n_envs_per_actor) % dp={self.n_dp} != 0 — align "
                f"--n-envs-per-actor with the mesh (--mesh-dp / "
                f"APEX_MESH_DP) so every chip gets whole lanes")
        self.N = int(steps_per_dispatch)
        self.P = int(train_per_step)
        self.warmup = int(warmup)
        self.beta0 = float(beta)
        self.anneal = max(1, int(beta_anneal))
        # the device warm/anneal counter saturates here: beyond both
        # thresholds the exact count no longer matters, so i32 is safe
        # for arbitrarily long runs
        self._ing_cap = np.int32(max(self.warmup, self.anneal) + 1)
        self.ingested_dev = jnp.int32(0)
        # train_ratio budget: f32 stays integer-exact below 2**24, and a
        # budget that far ahead means training is the bottleneck anyway
        self._bud_cap = np.float32(2 ** 24)
        self.budget_dev = jnp.float32(0.0)
        if mesh is not None:
            import copy

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            # per-chip engine: the device program depends on B alone
            # among the per-instance sizes (epsilons/slot_ids are
            # host-epilogue surfaces), so a shallow copy with B = B/dp
            # IS the chip's rollout program
            chip = copy.copy(engine)
            chip.B = engine.B // self.n_dp
            self._chip_engine = chip
            shard = NamedSharding(mesh, P("dp"))
            self._eps_dev = jax.device_put(
                np.asarray(jax.device_get(engine.epsilons)), shard)
            # lay the engine carries out on the mesh once, lane-sharded;
            # every later dispatch rebinds them from (sharded) results
            engine.carry = jax.device_put(engine.carry, shard)
            engine.carry_frames = jax.device_put(engine.carry_frames,
                                                 shard)
        else:
            self._chip_engine = engine
            self._eps_dev = None
        self._build_jit()
        # host counters (fleet_summary "ondevice" block; CI asserts)
        self.dispatches = 0
        self.macro_steps = 0
        self.train_steps = 0
        self.prio_writebacks = 0
        self.chunks = 0
        self.frames = 0
        self.transitions = 0
        self.external_ingest = 0

    # -- device program ----------------------------------------------------

    def _beta_at(self, ing):
        import jax.numpy as jnp
        frac = jnp.minimum(jnp.float32(1.0),
                           ing.astype(jnp.float32) / self.anneal)
        return (jnp.float32(self.beta0)
                + jnp.float32(1.0 - self.beta0) * frac)

    def _train_block(self, ts, rs, keys, ing, bud):
        import jax.numpy as jnp
        from jax import lax
        beta = self._beta_at(ing)

        def train1(ts2, rs2, k):
            batch, weights, idx = self.replay.sample(
                rs2, k, self._batch_chip, beta, axis_name=self._axis)
            ts2, prios, metrics = self.core.update_from_batch(
                ts2, batch, weights, axis_name=self._axis)
            rs2 = self.replay.update_priorities(rs2, idx, prios)
            return ts2, rs2, metrics

        if self.ratio is None:
            def body(carry, k):
                ts2, rs2 = carry
                ts2, rs2, metrics = train1(ts2, rs2, k)
                return (ts2, rs2), metrics

            (ts, rs), metrics = lax.scan(body, (ts, rs), keys)
            smask = jnp.ones((self.P,), bool)
            return ts, rs, bud, metrics, smask

        def body(carry, k):
            ts2, rs2, bud2 = carry
            go = bud2 > jnp.float32(0.0)

            def step(args):
                ts3, rs3 = args
                return train1(ts3, rs3, k)

            def hold(args):
                ts3, rs3 = args
                zero = jnp.float32(0.0)
                return ts3, rs3, {m: zero for m in _METRIC_KEYS}

            ts2, rs2, metrics = lax.cond(go, step, hold, (ts2, rs2))
            bud2 = bud2 - jnp.where(go, jnp.float32(self.core.batch_size),
                                    jnp.float32(0.0))
            return (ts2, rs2, bud2), (metrics, go)

        (ts, rs, bud), (metrics, smask) = lax.scan(body, (ts, rs, bud),
                                                   keys)
        return ts, rs, bud, metrics, smask

    def _macro(self, eng, eps, carry, xs):
        import jax.numpy as jnp
        from jax import lax

        ts, rs, c, cf, ing, bud = carry
        rkey, skeys = xs
        c, cf, out = eng._dispatch(ts.params, eps, c, cf, rkey)
        B, M = eng.B, eng.M
        prios = acting_priorities(out)                       # [B, M, K]
        sealed = out["sealed"]                               # [B]
        mask = jnp.arange(M, dtype=jnp.int32)[None, :] < sealed[:, None]

        def flat(a):
            return a.reshape((B * M,) + a.shape[2:])

        slots = {k: flat(out[k]) for k in
                 ("frames", "action", "reward", "discount",
                  "obs_ref", "next_ref", "nf", "nt")}

        def ingest(carry2, xs2):
            rs2, d2 = carry2
            sl, pr, do = xs2
            chunk = dict(frames=sl["frames"], n_frames=sl["nf"],
                         n_trans=sl["nt"], action=sl["action"],
                         reward=sl["reward"], discount=sl["discount"],
                         obs_ref=sl["obs_ref"], next_ref=sl["next_ref"])
            rs2 = self.replay.add(rs2, chunk, pr, valid=do)
            d2 = d2 + jnp.where(do, sl["nt"], 0)
            return (rs2, d2), ()

        (rs, delta), _ = lax.scan(ingest, (rs, jnp.int32(0)),
                                  (slots, flat(prios), mask.reshape(-1)))

        sealed_n = sealed.sum()
        sealed_mx = sealed.max()
        n_trans = jnp.where(mask, out["nt"], 0).sum()
        if self._axis is not None:
            # warmup/anneal/ratio are GLOBAL quantities: count every
            # chip's ingest (the collectives also make these ys leaves
            # honestly replicated for the out_specs=P() assembly)
            delta = lax.psum(delta, self._axis)
            sealed_n = lax.psum(sealed_n, self._axis)
            n_trans = lax.psum(n_trans, self._axis)
            sealed_mx = lax.pmax(sealed_mx, self._axis)
        # end-of-macro min == the per-chunk saturating add (i32, d >= 0)
        ing = jnp.minimum(ing + delta, self._ing_cap)
        if self.ratio is not None:
            bud = jnp.minimum(
                bud + delta.astype(jnp.float32) * jnp.float32(self.ratio),
                self._bud_cap)

        warm = ing >= jnp.int32(self.warmup)

        def do_train(args):
            ts2, rs2, bud2 = args
            return self._train_block(ts2, rs2, skeys, ing, bud2)

        def skip(args):
            ts2, rs2, bud2 = args
            zero = jnp.zeros((self.P,), jnp.float32)
            return (ts2, rs2, bud2, {k: zero for k in _METRIC_KEYS},
                    jnp.zeros((self.P,), bool))

        ts, rs, bud, metrics, smask = lax.cond(warm, do_train, skip,
                                               (ts, rs, bud))
        done, ep_ret, ep_len = out["stepped"]
        ys = dict(metrics=metrics, trained=warm, step_mask=smask,
                  sealed=sealed_n, sealed_max=sealed_mx,
                  n_trans=n_trans,
                  done=done, ep_ret=ep_ret, ep_len=ep_len)
        return (ts, rs, c, cf, ing, bud), ys

    def _scan_dispatch(self, eng, eps, ts, rs, c, cf, ing, bud,
                       rkeys, skeys):
        import functools

        from jax import lax
        (ts, rs, c, cf, ing, bud), ys = lax.scan(
            functools.partial(self._macro, eng, eps),
            (ts, rs, c, cf, ing, bud), (rkeys, skeys))
        return ts, rs, c, cf, ing, bud, ys

    def _build_jit(self):
        """(Re)build the jitted dispatch — plain jit at dp=1, a
        ``shard_map`` over the dp mesh otherwise.  The donation set is
        the device-resident carry (ts, rs, carries, ingest counter); the
        budget scalar and the lane-sharded epsilons are NOT donated (the
        epsilons buffer is reused every dispatch)."""
        import jax

        if self.mesh is None:
            def run(ts, rs, c, cf, ing, bud, rkeys, skeys):
                return self._scan_dispatch(
                    self.engine, self.engine.epsilons,
                    ts, rs, c, cf, ing, bud, rkeys, skeys)

            self._jit = jax.jit(run, donate_argnums=(0, 1, 2, 3, 4))
            return

        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel.mesh import shard_map_compat

        chip = self._chip_engine

        def per_chip(ts, rs, c, cf, ing, bud, eps, rkeys, skeys):
            # replay state arrives stacked [dp, ...] sharded on axis 0:
            # strip this chip's partition, restore the axis on the way
            # out (the ShardedLearner per-chip idiom); the engine
            # carries shard on their native lane axis, no strip needed
            rs = jax.tree.map(lambda x: x[0], rs)
            rk = jax.random.wrap_key_data(rkeys[:, 0])
            sk = jax.random.wrap_key_data(skeys[:, :, 0])
            ts, rs, c, cf, ing, bud, ys = self._scan_dispatch(
                chip, eps, ts, rs, c, cf, ing, bud, rk, sk)
            rs = jax.tree.map(lambda x: x[None], rs)
            return ts, rs, c, cf, ing, bud, ys

        repl, shard = P(), P("dp")
        lanes = P(None, None, "dp")       # [N, T, B] episode-lane leaves
        ys_spec = dict(metrics=repl, trained=repl, step_mask=repl,
                       sealed=repl, sealed_max=repl, n_trans=repl,
                       done=lanes, ep_ret=lanes, ep_len=lanes)
        mapped = shard_map_compat(
            per_chip, mesh=self.mesh,
            in_specs=(repl, shard, shard, shard, repl, repl, shard,
                      P(None, "dp"), P(None, None, "dp")),
            out_specs=(repl, shard, shard, shard, repl, repl, ys_spec),
            check_vma=False)
        self._jit = jax.jit(mapped, donate_argnums=(0, 1, 2, 3, 4))

    # -- host surface ------------------------------------------------------

    def dispatch(self, train_state, replay_state, sample_key):
        """One device program: N macro steps.  Advances the engine's
        rollout chain and the caller's sample chain with the exact split
        discipline a serial run would, returns ``(train_state,
        replay_state, sample_key, info)``."""
        import jax
        import jax.numpy as jnp

        from apex_tpu.actors.pool import EpisodeStat

        eng = self.engine
        fan = self.n_dp
        rkeys, skeys = [], []
        for _ in range(self.N):
            # ONE split per macro step off the engine chain — the serial
            # discipline at every dp width; dp>1 fans the macro key into
            # per-chip keys shipped as raw key data ([N, dp, 2] u32,
            # lane-sharded), re-wrapped per chip inside the shard_map
            eng.key, rk = jax.random.split(eng.key)
            rkeys.append(np.asarray(jax.random.key_data(
                jax.random.split(rk, fan))) if fan > 1 else rk)
            row = []
            for _ in range(self.P):
                sample_key, k = jax.random.split(sample_key)
                row.append(np.asarray(jax.random.key_data(
                    jax.random.split(k, fan))) if fan > 1 else k)
            skeys.append(np.stack(row) if fan > 1 else jnp.stack(row))
        rk_arr = np.stack(rkeys) if fan > 1 else jnp.stack(rkeys)
        sk_arr = np.stack(skeys) if fan > 1 else jnp.stack(skeys)
        args = [train_state, replay_state, eng.carry, eng.carry_frames,
                self.ingested_dev, self.budget_dev]
        if fan > 1:
            args.append(self._eps_dev)
        (train_state, replay_state, eng.carry, eng.carry_frames,
         self.ingested_dev, self.budget_dev, ys) = self._jit(
            *args, rk_arr, sk_arr)
        got = jax.device_get(ys)
        if int(got["sealed_max"].max(initial=0)) > eng.M - 1:
            raise RuntimeError(
                f"fused outbox overflow: {int(got['sealed_max'].max())} "
                f"seals > {eng.M - 1} sealed slots — raise rollout_len "
                f"headroom")
        done, ep_ret, ep_len = got["done"], got["ep_ret"], got["ep_len"]
        stats = [EpisodeStat(eng.slot_ids[b], float(ep_ret[m, t, b]),
                             int(ep_len[m, t, b]))
                 for m in range(self.N) for t in range(eng.T)
                 for b in range(eng.B) if done[m, t, b]]
        # [N, P] per-slot mask: all warm slots without train_ratio, the
        # budget-gated subset with it — identical aggregation either way
        smask = np.asarray(got["step_mask"], bool)
        trained = int(smask.sum())
        metrics = None
        if trained:
            metrics = {k: float(np.asarray(v)[smask].mean())
                       for k, v in got["metrics"].items()}
        transitions = int(got["n_trans"].sum())
        self.dispatches += 1
        self.macro_steps += self.N
        self.train_steps += trained
        self.prio_writebacks += trained
        self.chunks += int(got["sealed"].sum())
        self.frames += self.N * eng.T * eng.B
        self.transitions += transitions
        info = dict(stats=stats, metrics=metrics, train_steps=trained,
                    transitions=transitions,
                    frames=self.N * eng.T * eng.B)
        return train_state, replay_state, sample_key, info

    def note_external_ingest(self, n: int) -> None:
        """Host-path chunks (hybrid socket actors) ingested outside the
        fused program still advance the device warm/anneal counter (and
        the train-ratio budget, when one is live)."""
        import jax.numpy as jnp
        self.ingested_dev = jnp.minimum(
            self.ingested_dev + jnp.int32(n), self._ing_cap)
        if self.ratio is not None:
            self.budget_dev = jnp.minimum(
                self.budget_dev + jnp.float32(float(n) * self.ratio),
                self._bud_cap)
        self.external_ingest += int(n)

    def sync_ingested(self, n: int, steps: int = 0) -> None:
        """Re-seed the device counters after a checkpoint restore —
        ``n`` transitions ingested, ``steps`` learner updates taken."""
        import jax.numpy as jnp
        self.ingested_dev = jnp.minimum(jnp.int32(min(n, 2 ** 31 - 1)),
                                        self._ing_cap)
        if self.ratio is not None:
            self.budget_dev = jnp.minimum(
                jnp.float32(float(n) * self.ratio
                            - float(steps) * self.core.batch_size),
                self._bud_cap)

    def rebind(self, core) -> None:
        """Re-jit against a rebuilt core (live lr application — one
        recompile per explore, the apply_hparams contract)."""
        self.core = core
        self._build_jit()

    def counters(self) -> dict:
        """``fleet_summary.json``'s ``ondevice`` block (the fused-smoke
        CI job asserts these are nonzero)."""
        return {"dispatches": self.dispatches,
                "macro_steps": self.macro_steps,
                "train_steps": self.train_steps,
                "prio_writebacks": self.prio_writebacks,
                "chunks": self.chunks, "frames": self.frames,
                "transitions": self.transitions,
                "external_ingest": self.external_ingest,
                "steps_per_dispatch": self.N,
                "train_per_step": self.P,
                "dp": self.n_dp,
                "train_ratio": float(self.ratio or 0.0),
                "rollout_len": self.engine.T, "n_envs": self.engine.B}


class _IdlePool:
    """The in-host fused topology has no actor plane at all: rollouts
    live inside the dispatch.  This is the minimal pool surface the
    ConcurrentTrainer helpers probe."""

    def start(self) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def publish_params(self, version: int, params) -> None:
        pass

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        return []

    def poll_stats(self) -> list:
        return []


class FusedApexTrainer(ApexTrainer):
    """``--rollout fused``: the ConcurrentTrainer-path driver whose hot
    loop is one :class:`FusedStep` dispatch per iteration.

    Reuses the whole ApexTrainer substrate — model/replay/optimizer
    construction, checkpoint bundle (``replay_state`` IS the on-device
    pool, so the PR 8 machinery host-spills it for free), fleet
    registry/status/ctl surface, SLO engine, publish cadence — and
    replaces only the chunk-driven drain with the fused dispatch.  The
    socket pool (when one is attached) keeps serving evaluators and the
    param channel; any host-actor chunks that arrive are absorbed into
    the same replay state between dispatches (hybrid mode).

    A dp>1 learner mesh shards the WHOLE fused program (env lanes,
    replay partitions, pmean'd updates — see :class:`FusedStep`); the
    honest capability limits left are divisibility (lanes and batch must
    split evenly over the mesh) and their ValueErrors name both knobs.
    Graceful refusals otherwise: non-jittable envs fail in
    ``make_jax_env``'s ValueError and non-DQN families fail in the
    CLI/role wiring.
    """

    def __init__(self, config: ApexConfig | None = None,
                 logdir: str | None = None, verbose: bool = False,
                 publish_min_seconds: float = 0.2,
                 train_ratio=None, min_train_ratio=None,
                 checkpoint_dir: str | None = None, pool=None,
                 respawn_workers: bool = True,
                 rollout_len: int | None = None,
                 steps_per_dispatch: int = 4, train_per_step: int = 1):
        cfg = config or ApexConfig()
        # non-jittable env ids refuse HERE, before any pool/worker spawns
        from apex_tpu.envs.registry import make_jax_env
        make_jax_env(cfg.env.env_id, cfg.env)
        super().__init__(cfg, logdir=logdir, verbose=verbose,
                         publish_min_seconds=publish_min_seconds,
                         train_ratio=train_ratio,
                         min_train_ratio=min_train_ratio,
                         checkpoint_dir=checkpoint_dir,
                         pool=pool if pool is not None else _IdlePool(),
                         respawn_workers=respawn_workers)
        from apex_tpu.training.anakin import make_anakin_engine
        engine = make_anakin_engine(cfg, rollout_len=rollout_len)
        # dp>1: ApexTrainer._init_sharded already built the mesh, the
        # stacked per-chip replay partitions, and the replicated train
        # state — the fused program rides the same layout
        mesh = self.sharded.mesh if getattr(self, "n_dp", 1) > 1 else None
        self.fused = FusedStep(
            self.core, self.replay, engine,
            warmup=cfg.replay.warmup, beta=cfg.replay.beta,
            beta_anneal=cfg.replay.beta_anneal,
            steps_per_dispatch=steps_per_dispatch,
            train_per_step=train_per_step,
            mesh=mesh, train_ratio=train_ratio)

    # -- the fused hot loop ------------------------------------------------

    def train(self, total_steps: int, max_seconds: float = 3600.0,
              log_every: int = 200):
        """Run (at least) ``total_steps`` MORE learner updates — the
        dispatch granularity means up to ``steps_per_dispatch *
        train_per_step - 1`` overshoot."""
        import jax.numpy as jnp

        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        from apex_tpu.fleet.registry import FleetRegistry
        from apex_tpu.obs import spans as obs_spans
        from apex_tpu.obs.trace import get_ring, set_process_label
        from apex_tpu.utils.profiling import DispatchGapTimer

        cfg = self.cfg
        pool = self.pool
        target_steps = self.steps_rate.total + total_steps
        if self.actor_timing is None:
            self.actor_timing = {}
        set_process_label("learner")
        ring = get_ring()
        if self._obs is None:
            self._obs = obs_spans.LearnerObs(ring=ring)
        gap = self._dispatch_gap = DispatchGapTimer(
            ring=ring, track="learner-fused-loop")
        if self.fleet is None:
            self.fleet = FleetRegistry(cfg.comms)
        pool.start()
        set_epoch = getattr(pool, "set_learner_epoch", None)
        if set_epoch is not None:
            set_epoch(self.learner_epoch)
        self._start_status_server()
        # the fused plane beats into the registry like AnakinPool's
        # ondevice-0 does, so the status table shows it next to any
        # socket peers
        beat = HeartbeatEmitter(
            "fused-0", role="rollout",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self.fused.counters)
        try:
            self._publish()
            last_publish = time.monotonic()
            t_end = last_publish + max_seconds
            last_pub_step = self.steps_rate.total
            last_health = last_publish
            self._episode_idx = 0
            metrics = None

            while self.steps_rate.total < target_steps:
                now = time.monotonic()
                stop = self._stop_requested
                if now > t_end or (stop is not None and stop.is_set()):
                    break
                gap.about_to_dispatch()
                (self.train_state, self.replay_state, self.key,
                 info) = self.fused.dispatch(
                    self.train_state, self.replay_state, self.key)
                gap.dispatch_returned()
                if info["train_steps"]:
                    self.steps_rate.tick(info["train_steps"])
                    if info["metrics"] is not None:
                        metrics = info["metrics"]
                self.ingested += info["transitions"]
                self.frames_rate.tick(info["transitions"])
                for stat in info["stats"]:
                    self.log.scalars(
                        {"episode_reward": stat.reward,
                         "episode_length": stat.length,
                         "actor_id": stat.actor_id}, self._episode_idx)
                    self._episode_idx += 1
                # hybrid: host-actor chunks absorb between dispatches
                # (ingest-only — the fused program owns the train cadence)
                for msg in pool.poll_chunks(64, timeout=0):
                    self.replay_state = self._ingest(
                        self.replay_state, msg["payload"],
                        jnp.asarray(msg["priorities"]))
                    n_new = int(msg["n_trans"])
                    self.ingested += n_new
                    self.frames_rate.tick(n_new)
                    self.fused.note_external_ingest(n_new)
                beat.tick(info["frames"])
                hb = beat.maybe_beat(self.param_version)
                if hb is not None:
                    self.fleet.observe(hb)

                steps = self.steps_rate.total
                if (self.checkpointer is not None
                        and steps - self._last_save
                        >= cfg.learner.save_interval):
                    self.save_checkpoint()
                    self._last_save = steps
                if steps:
                    due = (now - last_publish >= self.publish_min_seconds
                           and (steps - last_pub_step
                                >= cfg.learner.publish_interval
                                or now - last_publish
                                > 10 * self.publish_min_seconds))
                else:
                    due = (getattr(pool, "needs_warmup_republish", False)
                           and now - last_publish
                           > 10 * self.publish_min_seconds)
                if due:
                    self._publish()
                    last_publish = now
                    last_pub_step = steps
                if self.respawn_workers and now - last_health >= 5.0:
                    self._health_tick(steps)
                    last_health = now
                self._drain_stats(steps)
                if metrics is not None \
                        and steps - self._last_log >= log_every:
                    extra = gap.snapshot()
                    if self._obs is not None:
                        extra |= self._obs.scalars()
                    self.log.scalars(
                        {k: float(v) for k, v in metrics.items()}
                        | {"bps": self.steps_rate.rate,
                           "fps": self.frames_rate.rate,
                           "param_version": self.param_version,
                           "ingested": self.ingested} | extra, steps)
                    self._last_log = steps
        finally:
            if self._fleet_status is not None:
                self._fleet_status.stop()
                self._fleet_status = None
            self._dump_fleet_summary()
            pool.cleanup()
            stop = self._stop_requested
            if stop is not None:
                stop.clear()
        return self

    # -- surface integration ----------------------------------------------

    def fleet_summary(self):
        snap = super().fleet_summary()
        if snap is not None and getattr(self, "fused", None) is not None:
            import jax

            # the fused-smoke CI drills assert these from the persisted
            # summary (dispatches/chunks/transitions + >=1 write-back;
            # the dp drill additionally checks one live pool per shard)
            ond = self.fused.counters()
            ond["pool_size_per_shard"] = [
                int(v) for v in np.asarray(
                    jax.device_get(self.replay_state.size)).reshape(-1)]
            snap["metrics"]["ondevice"] = ond
        return snap

    def _apply_counters(self, meta: dict) -> None:
        super()._apply_counters(meta)
        self.fused.sync_ingested(self.ingested,
                                 steps=self.steps_rate.total)

    def apply_hparams(self, h: dict) -> dict:
        applied = super().apply_hparams(h)
        if "lr" in applied:
            # the fused program closed over the old core's optimizer —
            # rebind + re-jit (one recompile per explore, same contract
            # as the host loop's hot-fn rebuild)
            self.fused.rebind(self.core)
        return applied
