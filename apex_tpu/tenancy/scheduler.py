"""The tenancy placement controller: admission, bands, rebalancing.

``--role tenant-ctl`` is the multi-tenant plane's control loop, built in
the serve-ctl mold (:mod:`apex_tpu.serving.deploy`): a socket-free,
fake-clock-testable :class:`PlacementScheduler` drives the decisions,
and a thin one-thread socket wrapper (:class:`TenantCtl`) feeds it
observations and ships the evidence out.

What it decides:

* **Admission** — every :class:`~apex_tpu.tenancy.namespace.TenantSpec`
  in the ``APEX_TENANTS`` roster is admitted (recorded, counted); an
  operator adds a tenant by growing the roster and relaunching the
  controller, the serve-ctl reconcile discipline.
* **Bands** — the replay and infer tiers split into weight-proportional
  contiguous shard bands (largest-remainder apportionment; every tenant
  gets at least one shard, and with more tenants than shards the bands
  share round-robin).  Bands are the scheduler's capacity PLAN: the
  hash planes stay uniform until a tenant's roles opt into their band
  (:func:`apex_tpu.tenancy.namespace.shard_in_band`), so publishing the
  assignment is safe with zero coordination.
* **Placement** — the 2311.09445 heterogeneous-platform brain, scaled
  to our registry: hosts learned from the shared fleet's heartbeat
  gauges (``backend_accel`` on infer/replay beats) rank accelerator-
  backed hosts first for ``accel`` (conv-heavy) tenants and CPU spares
  first for toy tenants; the preferred host rides the assignment so
  deploy tooling can pin the tenant's heavy roles there.
* **Eviction / rebalance** — a tenant whose learner status port stays
  silent past ``dead_after_s`` is EVICTED (its band redistributes to
  the survivors — one tenant's death grows everyone else's slice); a
  probe answering again re-admits it and rebalances back.  Every edge
  lands in a bounded timeline.

Evidence rides the existing planes, serve-ctl style: the controller
heartbeats like any role, and ships its snapshot to the HOST learner as
a :class:`TenancyStat` on the stat channel — ``fleet_summary.json``
gains a ``tenancy`` section, ``--role status`` prints the timeline
tail, and ``apex_tenancy_*`` Prometheus rows scrape from the same
surface.

Pure stdlib at module level (zmq imports lazily in the socket wrapper),
so the learner imports :class:`TenancyStat` and the exposition builders
without the comms extra.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from apex_tpu.tenancy import namespace

PENDING, ACTIVE, EVICTED = "PENDING", "ACTIVE", "EVICTED"

#: state -> numeric code for gauges/exposition (the slo_state pattern)
STATE_CODE = {PENDING: 0, ACTIVE: 1, EVICTED: 2}


@dataclass
class TenancyStat:
    """The controller's state shipped to the host learner on the stat
    channel (wire-allowlisted): ``snapshot`` is
    :meth:`PlacementScheduler.snapshot` — plain builtins only."""

    identity: str
    snapshot: dict = field(default_factory=dict)


def assign_bands(weights: dict[str, float],
                 n_shards: int) -> dict[str, list[int]]:
    """Weight-proportional contiguous shard bands (largest-remainder
    apportionment, deterministic under sorted tenant order).  Every
    tenant gets at least one shard; with more tenants than shards the
    single-shard bands share round-robin."""
    names = sorted(weights)
    if not names:
        return {}
    n = max(1, int(n_shards))
    if len(names) >= n:
        return {t: [i % n] for i, t in enumerate(names)}
    total = sum(weights[t] for t in names)
    raw = {t: n * weights[t] / total for t in names}
    counts = {t: max(1, int(raw[t])) for t in names}
    while sum(counts.values()) < n:
        # most under-served first; sorted-name order breaks ties
        t = max(names, key=lambda x: (raw[x] - counts[x], x))
        counts[t] += 1
    while sum(counts.values()) > n:
        over = [t for t in names if counts[t] > 1]
        if not over:
            break
        t = min(over, key=lambda x: (raw[x] - counts[x], x))
        counts[t] -= 1
    out: dict[str, list[int]] = {}
    at = 0
    for t in names:
        out[t] = list(range(at, at + counts[t]))
        at += counts[t]
    return out


def place(spec: namespace.TenantSpec,
          host_backends: dict[str, bool]) -> str | None:
    """Preferred host for a tenant's heavy roles: ``accel`` tenants
    rank accelerator-backed hosts first, toy tenants rank CPU spares
    first (don't burn an MXU host on CartPole); sorted-name order makes
    the pick deterministic.  None while no host has reported."""
    if not host_backends:
        return None
    ranked = sorted(host_backends.items(),
                    key=lambda kv: (kv[1] != spec.accel, kv[0]))
    return ranked[0][0]


@dataclass
class _TenantState:
    spec: namespace.TenantSpec
    state: str = PENDING
    last_seen: float | None = None      # newest successful learner probe
    severity: int | None = None         # tenant's own SLO severity
    steps: int | None = None            # tenant learner progress
    host: str | None = None             # placement pick
    evictions: int = 0


class PlacementScheduler:
    """The decision half of tenant-ctl (module docstring): socket-free,
    every clock injectable, every transition in a bounded timeline —
    the DeployController testing discipline."""

    def __init__(self, n_replay_shards: int, n_infer_shards: int,
                 dead_after_s: float = 15.0, clock=time.monotonic,
                 wall=time.time, timeline_cap: int = 128):
        self.n_replay_shards = max(1, int(n_replay_shards))
        self.n_infer_shards = max(1, int(n_infer_shards))
        self.dead_after_s = float(dead_after_s)
        self._clock = clock
        self._wall = wall
        self.tenants: dict[str, _TenantState] = {}
        self.replay_bands: dict[str, list[int]] = {}
        self.infer_bands: dict[str, list[int]] = {}
        self.admissions = 0
        self.evictions = 0
        self.rebalances = 0
        self.timeline: deque = deque(maxlen=timeline_cap)
        self._t0: float | None = None

    # -- the machine -------------------------------------------------------

    def _event(self, kind: str, tenant: str, reason: str) -> dict:
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        e = {"t_s": round(now - self._t0, 3),
             "wall": round(self._wall(), 3),
             "event": kind, "tenant": tenant, "reason": reason}
        self.timeline.append(e)
        return e

    def _active_weights(self) -> dict[str, float]:
        return {name: ts.spec.weight for name, ts in self.tenants.items()
                if ts.state == ACTIVE}

    def _rebalance(self, reason: str) -> None:
        weights = self._active_weights()
        replay = assign_bands(weights, self.n_replay_shards)
        infer = assign_bands(weights, self.n_infer_shards)
        if replay != self.replay_bands or infer != self.infer_bands:
            self.replay_bands, self.infer_bands = replay, infer
            self.rebalances += 1
            self._event("REBALANCED", ",".join(sorted(weights)) or "-",
                        reason)

    def admit(self, spec: namespace.TenantSpec) -> None:
        """Admit (or re-admit) one tenant and rebalance the bands.
        Idempotent for an already-ACTIVE tenant with the same spec —
        the controller reconciles the roster every tick."""
        ts = self.tenants.get(spec.name)
        if ts is not None and ts.state == ACTIVE and ts.spec == spec:
            return
        if ts is None:
            ts = self.tenants[spec.name] = _TenantState(spec)
        readmit = ts.state == EVICTED
        ts.spec, ts.state = spec, ACTIVE
        ts.last_seen = self._clock()
        self.admissions += 1
        self._event("ADMITTED", spec.name,
                    "re-admission" if readmit else
                    f"roster (weight={spec.weight:g}, "
                    f"quota={spec.replay_quota})")
        self._rebalance(f"admit {spec.name}")

    def evict(self, name: str, reason: str) -> bool:
        ts = self.tenants.get(name)
        if ts is None or ts.state != ACTIVE:
            return False
        ts.state = EVICTED
        ts.evictions += 1
        self.evictions += 1
        self._event("EVICTED", name, reason)
        self._rebalance(f"evict {name}")
        return True

    def observe(self, name: str, alive: bool, severity: int | None = None,
                steps: int | None = None) -> None:
        """One probe result for a tenant's learner.  A live probe
        re-admits an evicted tenant (its learner came back — the serve-
        ctl respawn-reconvergence discipline)."""
        ts = self.tenants.get(name)
        if ts is None:
            return
        if alive:
            ts.last_seen = self._clock()
            ts.severity, ts.steps = severity, steps
            if ts.state == EVICTED:
                self.admit(ts.spec)

    def tick(self, host_backends: dict[str, bool] | None = None
             ) -> list[dict]:
        """Apply the silence threshold + refresh placement; returns the
        timeline events appended this tick."""
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        before = len(self.timeline)
        for name, ts in sorted(self.tenants.items()):
            if ts.state != ACTIVE:
                continue
            if ts.last_seen is not None \
                    and now - ts.last_seen > self.dead_after_s:
                self.evict(name, f"learner silent "
                                 f"{now - ts.last_seen:.0f}s")
            elif host_backends:
                ts.host = place(ts.spec, host_backends)
        return list(self.timeline)[before:]

    # -- read surface ------------------------------------------------------

    def snapshot(self) -> dict:
        """Serializable controller view (TenancyStat payload, the
        ``tenancy`` section of fleet_summary.json): plain builtins
        only.  tests/test_tenancy.py pins this schema."""
        now = self._clock()
        tenants = {}
        for name, ts in sorted(self.tenants.items()):
            tenants[name] = {
                "state": ts.state,
                "env_id": ts.spec.env_id,
                "family": ts.spec.family,
                "weight": ts.spec.weight,
                "replay_quota": ts.spec.replay_quota,
                "accel": ts.spec.accel,
                "replay_band": self.replay_bands.get(name, []),
                "infer_band": self.infer_bands.get(name, []),
                "host": ts.host,
                "severity": ts.severity,
                "steps": ts.steps,
                "silent_s": (None if ts.last_seen is None
                             else round(now - ts.last_seen, 1)),
                "evictions": ts.evictions,
            }
        return {
            "kind": "apex_tenancy",
            "version": 1,
            "n_replay_shards": self.n_replay_shards,
            "n_infer_shards": self.n_infer_shards,
            "tenants": tenants,
            "admissions": self.admissions,
            "evictions": self.evictions,
            "rebalances": self.rebalances,
            "timeline": list(self.timeline),
        }


# -- operator/exposition surfaces --------------------------------------------


def prometheus_sections(tenancy: dict) -> tuple[dict, dict]:
    """(gauges, labeled) — the ``apex_tenancy_*`` row family the
    learner's scrape surface serves next to the slo/serving rows."""
    tenants = tenancy.get("tenants") or {}
    gauges = {
        "tenancy_tenants": len(tenants),
        "tenancy_admissions": tenancy.get("admissions", 0),
        "tenancy_evictions": tenancy.get("evictions", 0),
        "tenancy_rebalances": tenancy.get("rebalances", 0),
    }
    labeled = {
        "tenancy_tenant_state": [({"tenant": t, "state": v.get("state")},
                                  STATE_CODE.get(v.get("state"), 0))
                                 for t, v in sorted(tenants.items())],
        "tenancy_tenant_shards": [({"tenant": t, "plane": plane},
                                   len(v.get(key) or []))
                                  for t, v in sorted(tenants.items())
                                  for plane, key in
                                  (("replay", "replay_band"),
                                   ("infer", "infer_band"))],
    }
    return gauges, labeled


def format_tenancy_lines(tenancy: dict) -> list[str]:
    """Human tenancy lines for the ``--role status`` table: one line per
    tenant plus the admission/eviction timeline tail."""
    tenants = tenancy.get("tenants") or {}
    lines = [
        f"tenancy: {len(tenants)} tenant(s) "
        f"admissions={tenancy.get('admissions', 0)} "
        f"evictions={tenancy.get('evictions', 0)} "
        f"rebalances={tenancy.get('rebalances', 0)}"]
    for t, v in sorted(tenants.items()):
        lines.append(
            f"tenant {t}: {v.get('state')} env={v.get('env_id')} "
            f"weight={v.get('weight')} quota={v.get('replay_quota')} "
            f"replay_band={v.get('replay_band')} "
            f"infer_band={v.get('infer_band')} "
            f"host={v.get('host') or '-'} "
            f"severity={v.get('severity') if v.get('severity') is not None else '-'}")
    for e in (tenancy.get("timeline") or [])[-4:]:
        lines.append(f"tenancy t={e['t_s']}s {e['event']} {e['tenant']} "
                     f"({e['reason']})")
    return lines


# -- the socket role ---------------------------------------------------------


class TenantCtl:
    """Socket wrapper around :class:`PlacementScheduler` — the
    ``--role tenant-ctl`` process body (serve-ctl's one-thread shape).

    Per tick: probe each roster tenant's OWN learner status port
    (liveness + its SLO severity + progress), probe the HOST fleet's
    status port once for host/backend gauges, feed the scheduler, and
    ship the snapshot to the host learner as a :class:`TenancyStat`.
    """

    def __init__(self, cfg, interval_s: float = 5.0,
                 roster: dict[str, namespace.TenantSpec] | None = None):
        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        from apex_tpu.obs.slo import SloEngine, roster_slos
        from apex_tpu.runtime import transport

        self.comms = cfg.comms
        self.interval_s = float(interval_s)
        self.roster = (roster if roster is not None
                       else namespace.load_roster())
        # per-tenant objective sets from the roster (PR 13 follow-up):
        # a progress-floor + eval-score objective PER tenant, judged off
        # this controller's own probe stream — the @tenant suffix only
        # covered peers the HOST registry sees; these cover every roster
        # tenant's learner directly
        self.slo = SloEngine(roster_slos(self.roster)) if self.roster \
            else None
        self._probe_marks: dict[str, tuple[float, int]] = {}
        self._probe_rates: dict[str, float | None] = {}
        self._probe_scores: dict[str, float | None] = {}
        # eviction needs SEVERAL missed probe rounds, not one slow
        # status reply: the scheduler's clock ticks at interval_s, so a
        # dead_after_s below ~3 ticks would evict on a single learner
        # GC/compile pause and thrash the bands
        self.sched = PlacementScheduler(
            max(1, cfg.comms.replay_shards),
            max(1, getattr(cfg.comms, "infer_shards", 1)),
            dead_after_s=max(cfg.comms.dead_after_s,
                             3.0 * self.interval_s))
        self.sender = transport.ChunkSender(cfg.comms, "tenant-ctl")
        self.beat = HeartbeatEmitter(
            "tenant-ctl", role="tenant-ctl",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self._gauges)
        self.ticks = 0

    def _gauges(self) -> dict:
        return {"tenants": sum(ts.state == ACTIVE
                               for ts in self.sched.tenants.values())}

    def _probe_tenant(self, spec: namespace.TenantSpec) -> None:
        from apex_tpu.fleet.registry import status_request
        from apex_tpu.obs.slo import resolve_signal

        try:
            snap = status_request(
                namespace.tenant_comms(self.comms, spec),
                timeout_s=min(2.0, self.interval_s))
        except Exception:
            snap = None
        if not snap:
            self.sched.observe(spec.name, alive=False)
            self._probe_rates[spec.name] = None
            self._probe_scores[spec.name] = None
            return
        slo = snap.get("slo") or {}
        steps = snap.get("steps")
        self.sched.observe(spec.name, alive=True,
                           severity=slo.get("severity"),
                           steps=steps)
        # roster-SLO inputs: probe-differenced progress rate + the
        # tenant's eval-ladder mean off its own registry gauges
        self._probe_scores[spec.name] = resolve_signal(
            snap, "gauge:evaluator:eval_score_mean:min")
        now = time.monotonic()
        rate = None
        mark = self._probe_marks.get(spec.name)
        if steps is not None:
            if mark is not None and now > mark[0]:
                rate = max(0.0, (int(steps) - mark[1]) / (now - mark[0]))
            self._probe_marks[spec.name] = (now, int(steps))
        self._probe_rates[spec.name] = rate

    def _slo_summary(self) -> dict:
        """The probe-derived signal space the roster objectives walk
        (:func:`apex_tpu.obs.slo.roster_slos`)."""
        return {"tenants": {
            name: {"steps_rate": self._probe_rates.get(name),
                   "eval_score": self._probe_scores.get(name)}
            for name in self.roster}}

    def _probe_hosts(self) -> dict[str, bool]:
        """Host -> accelerator-backed, from the shared fleet's
        heartbeat gauges (infer/replay roles ship ``backend_accel``)."""
        from apex_tpu.fleet.registry import status_request

        try:
            snap = status_request(self.comms,
                                  timeout_s=min(2.0, self.interval_s))
        except Exception:
            return {}
        out: dict[str, bool] = {}
        for p in (snap or {}).get("peers") or []:
            host = p.get("host")
            if not host or p.get("state") == "DEAD":
                continue
            accel = bool((p.get("gauges") or {}).get("backend_accel"))
            out[host] = out.get(host, False) or accel
        return out

    def step(self) -> None:
        """One control round: reconcile roster -> probe -> tick ->
        report (new timeline events print like serve-ctl's do)."""
        for spec in self.roster.values():
            ts = self.sched.tenants.get(spec.name)
            if ts is None:
                self.sched.admit(spec)
        for spec in self.roster.values():
            self._probe_tenant(spec)
        for e in self.sched.tick(self._probe_hosts()):
            print(f"tenant-ctl: {e['event']} {e['tenant']} "
                  f"({e['reason']})", flush=True)
        if self.slo is not None:
            for tr in self.slo.sample(self._slo_summary()):
                print(f"tenant-ctl: slo {tr['objective']} {tr['from']} "
                      f"-> {tr['to']} (value={tr['value']})", flush=True)
        self.ticks += 1
        snap = self.sched.snapshot()
        if self.slo is not None:
            # per-tenant objective states ride the tenancy section so
            # fleet_summary.json answers "is each tenant in objective"
            snap["slo"] = self.slo.snapshot()
        self.sender.send_stat(TenancyStat("tenant-ctl", snap))
        hb = self.beat.maybe_beat()
        if hb is not None:
            self.sender.send_stat(hb)

    def run(self, stop_event=None, max_seconds: float | None = None):
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        try:
            while True:
                if stop_event is not None and stop_event.is_set():
                    break
                if deadline is not None and time.monotonic() > deadline:
                    break
                t0 = time.monotonic()
                self.step()
                rest = self.interval_s - (time.monotonic() - t0)
                if rest > 0:
                    if stop_event is not None:
                        stop_event.wait(rest)
                    else:
                        time.sleep(rest)
        finally:
            self.close()
        return self.sched.snapshot()

    def close(self) -> None:
        self.sender.close(drain_s=0.0)


def run_tenant_ctl(cfg, interval_s: float = 5.0, stop_event=None,
                   max_seconds: float | None = None) -> dict:
    """The ``--role tenant-ctl`` entry point.  Skips the startup barrier
    like the other controllers — useful the moment any tenant's status
    port answers.  Returns the final scheduler snapshot."""
    from apex_tpu.obs.trace import get_ring, set_process_label

    set_process_label("tenant-ctl")
    get_ring()
    ctl = TenantCtl(cfg, interval_s=interval_s)
    print(f"tenant-ctl: {len(ctl.roster)} roster tenant(s) over "
          f"{ctl.sched.n_replay_shards} replay + "
          f"{ctl.sched.n_infer_shards} infer shard(s), "
          f"tick={interval_s:g}s", flush=True)
    return ctl.run(stop_event=stop_event, max_seconds=max_seconds)
