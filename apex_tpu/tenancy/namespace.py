"""THE tenant namespace: qualified ids, param topics, and TenantSpec.

Every plane the fleet shares — replay shards hashing chunk ids, infer
shards coalescing requests, the registry keying peers, the param channel
tagging publishes — agrees on ONE id grammar::

    peer identity   tenant/actor-3          (default tenant: actor-3)
    chunk id        tenant/actor-3:17       (identity + ":" + sequence)
    param topic     apxt/tenant|<pickle>    (default tenant: bare pickle)

and this module is the one place that grammar is CONSTRUCTED (apexlint
J017 ``cross-tenant-id`` flags tenant-string concatenation anywhere
else): a plane that wants a tenant-qualified id calls :func:`qualify` /
:func:`chunk_id` / :func:`param_topic`, and a plane that wants the
tenant back calls :func:`split` / :func:`tenant_of`.  The payoff is the
same as ``serving/fence.py``'s: the grammar can never fork, so the crc32
chunk hash partitions per tenant for free (a tenant prefix makes every
tenant's chunk-id population disjoint) and "which tenant does this peer
belong to" is a parse, not a lookup.

Default-tenant transparency: the default tenant ``"t0"`` qualifies to
the BARE id and the EMPTY topic — a fleet that never sets
``APEX_TENANT`` produces byte-identical wire traffic, identities, chunk
ids, and replay/infer state to the pre-tenancy code
(tests/test_tenancy.py pins it).  Multi-tenancy is therefore pay-as-you-
go: exporting ``APEX_TENANT=rally`` on a tenant's roles is the whole
opt-in.

:class:`TenantSpec` is the admission unit the placement scheduler
(:mod:`apex_tpu.tenancy.scheduler`) and the shared planes consume: env
id (each tenant's replay partition and infer policy are built from it),
family, per-shard replay quota, band weight, and the tenant's OWN
learner endpoint (the shared infer shards subscribe each tenant's param
channel; ``tenant-ctl`` probes each tenant's status port).  The
``APEX_TENANTS`` env var carries the roster as JSON so every shared-
plane process — shards, infer servers, the controller — loads the same
one: export and go, the chaos-config discipline.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from dataclasses import dataclass

#: the implicit tenant every pre-tenancy fleet runs as: qualifies to the
#: bare id / empty topic, so single-tenant paths stay byte-identical
DEFAULT_TENANT = "t0"

#: id grammar separators (module docstring); names may use neither
_SEP = "/"
_TOPIC_HEAD = "apxt" + _SEP
_TOPIC_TAIL = "|"
_FORBIDDEN = (_SEP, _TOPIC_TAIL, ":")


def valid_name(tenant: str) -> bool:
    """A usable tenant name: nonempty, and free of the grammar's own
    separators (a tenant named ``a/b`` would parse as someone else)."""
    return bool(tenant) and not any(c in tenant for c in _FORBIDDEN)


def _check(tenant: str) -> str:
    if not valid_name(tenant):
        raise ValueError(f"invalid tenant name {tenant!r} — names must be "
                         f"nonempty and contain none of {_FORBIDDEN}")
    return tenant


def current_tenant(environ=None) -> str:
    """This process's tenant (``APEX_TENANT``; empty/unset = the default
    tenant) — env-driven like the chaos config, so a whole tenant's
    roles opt in with one export and zero flag plumbing."""
    e = os.environ if environ is None else environ
    t = str(e.get("APEX_TENANT", "")).strip()
    return _check(t) if t else DEFAULT_TENANT


def is_default(tenant: str) -> bool:
    return tenant == DEFAULT_TENANT


def qualify(tenant: str, base: str) -> str:
    """Tenant-qualified peer identity.  THE construction site for the
    ``tenant/base`` join (J017); default tenant passes through so
    single-tenant identities — and everything hashed off them — stay
    bit-identical."""
    if is_default(tenant):
        return base
    return _check(tenant) + _SEP + base


def split(identity: str) -> tuple[str, str]:
    """``(tenant, base)`` of a possibly-qualified identity; unqualified
    ids belong to the default tenant."""
    if _SEP in identity:
        tenant, base = identity.split(_SEP, 1)
        if valid_name(tenant):
            return tenant, base
    return DEFAULT_TENANT, identity


def tenant_of(id_str: str) -> str:
    """The owning tenant of a peer identity OR a chunk id (chunk ids are
    ``identity:seq``, so the identity parse covers both)."""
    return split(id_str)[0]


def base_of(identity: str) -> str:
    return split(identity)[1]


def chunk_id(identity: str, seq: int) -> str:
    """Canonical chunk id: ``identity:seq``.  The identity is already
    tenant-qualified (or default-bare), so the crc32 shard hash sees
    per-tenant-disjoint id populations with no extra machinery — and
    the replay shards recover the tenant with :func:`tenant_of`."""
    return f"{identity}:{seq}"


def param_topic(tenant: str) -> bytes:
    """Param-channel frame prefix for a tenant's publishes
    (``apxt/<tenant>|`` + pickle).  The default tenant publishes BARE
    pickles — byte-identical to the pre-tenancy wire — and non-default
    SUB sockets subscribe exactly this prefix, so a subscriber pointed
    at the wrong tenant's endpoint receives NOTHING rather than
    silently acting on another tenant's params."""
    if is_default(tenant):
        return b""
    return (_TOPIC_HEAD + _check(tenant) + _TOPIC_TAIL).encode()


def strip_topic(topic: bytes, payload: bytes) -> bytes | None:
    """The pickle bytes behind a topic-tagged frame, or None when the
    frame is not this topic's (a mis-wired endpoint's traffic — the
    caller counts and drops).  The ``apxt/`` head is RESERVED: a
    bare-topic (default tenant) subscriber drops tagged frames by
    grammar instead of feeding another tenant's prefix to the
    unpickler."""
    head = _TOPIC_HEAD.encode()
    if not topic:
        return None if payload.startswith(head) else payload
    if payload.startswith(topic):
        return payload[len(topic):]
    return None


def shard_in_band(key: str, band) -> int:
    """Stable hash of ``key`` onto an explicit shard band (the placement
    scheduler's weighted assignments): same crc32 the unbanded planes
    use, modulo the band instead of the whole tier."""
    band = list(band)
    if not band:
        raise ValueError("empty shard band")
    return band[zlib.crc32(key.encode()) % len(band)]


# -- the admission unit ------------------------------------------------------


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's admission record (module docstring).

    ``replay_quota`` bounds the tenant's RESIDENT transitions per replay
    shard (0 = unlimited): a full partition refuses further ingest
    (counted, acked — the sender's credit window never wedges the shared
    plane) instead of letting one tenant starve the others' HBM.
    ``weight`` sizes the tenant's shard/infer bands in the scheduler's
    weighted assignment; ``accel`` marks conv-heavy tenants the
    placement brain prefers to land on accelerator-backed hosts (toy
    tenants fill the CPU spares).  ``learner_ip``/``param_port``/
    ``status_port`` locate the tenant's OWN learner (0 = the shared
    config's default port): the infer shards subscribe its param channel
    there, and tenant-ctl probes its status port for liveness and SLO
    state."""

    name: str
    env_id: str = "ApexCartPole-v0"
    family: str = "dqn"
    learner_ip: str = "127.0.0.1"
    param_port: int = 0
    status_port: int = 0
    replay_quota: int = 0
    weight: float = 1.0
    accel: bool = False

    def __post_init__(self) -> None:
        _check(self.name)
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown TenantSpec fields {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def load_roster(environ=None) -> dict[str, TenantSpec]:
    """The fleet's tenant roster (``APEX_TENANTS``, JSON list of
    :class:`TenantSpec` dicts) as ``name -> spec``; empty when unset.
    The default tenant needs no roster entry — it is the fleet that was
    already there — but MAY carry one (quota/weight for the shared
    planes).

    Population lineages (``APEX_POPULATION``,
    :mod:`apex_tpu.population.lineage`) fold in as tenants — each
    lineage IS a tenant, so the shared planes admit a population with
    one export; an explicit ``APEX_TENANTS`` entry of the same name
    wins (the operator's word over the controller's)."""
    e = os.environ if environ is None else environ
    raw = str(e.get("APEX_TENANTS", "")).strip()
    out: dict[str, TenantSpec] = {}
    if raw:
        specs = [TenantSpec.from_dict(d) for d in json.loads(raw)]
        for spec in specs:
            if spec.name in out:
                raise ValueError(
                    f"duplicate tenant {spec.name!r} in roster")
            out[spec.name] = spec
    pop_raw = str(e.get("APEX_POPULATION", "")).strip()
    if pop_raw:
        # lazy import: population builds ON this module (LineageSpec
        # extends TenantSpec), so the dependency only runs at call time
        from apex_tpu.population.lineage import parse_population
        for name, lineage in parse_population(pop_raw).items():
            out.setdefault(name, lineage.as_tenant())
    return out


def tenant_comms(comms, spec: TenantSpec):
    """The shared config re-pointed at one tenant's learner endpoint
    (spec ports of 0 inherit the shared defaults) — what the infer
    shards' per-tenant param subscribers and tenant-ctl's status probes
    connect through."""
    return dataclasses.replace(
        comms, learner_ip=spec.learner_ip,
        param_port=spec.param_port or comms.param_port,
        status_port=spec.status_port or comms.status_port)
