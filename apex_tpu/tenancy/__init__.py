"""Multi-tenant fleet-as-a-service (PR 13).

One set of replay shards, infer shards, and supervisors serves MANY
concurrent experiments.  :mod:`apex_tpu.tenancy.namespace` is the ONE
module that constructs and parses tenant-qualified identifiers (peer
identities, chunk ids, param-channel topics — apexlint J017 keeps id
construction out of everywhere else) and defines :class:`TenantSpec` +
the ``APEX_TENANTS`` roster; :mod:`apex_tpu.tenancy.scheduler` is the
placement controller (``--role tenant-ctl``) that admits tenants,
assigns shard/infer bands by weight, and records the admission/eviction
timeline in ``fleet_summary.json``.
"""

from apex_tpu.tenancy.namespace import (DEFAULT_TENANT, TenantSpec,
                                        current_tenant, load_roster,
                                        qualify, split, tenant_of)

__all__ = ["DEFAULT_TENANT", "TenantSpec", "current_tenant",
           "load_roster", "qualify", "split", "tenant_of"]
