"""apex_tpu — a TPU-native distributed prioritized experience replay (Ape-X) framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
``Bing-Jing/Ape-X`` (PyTorch+CUDA): dueling double-DQN with n-step returns,
prioritized replay on sum/min segment trees, ladder-epsilon CPU actor fleets,
an asynchronous actor->replay->learner pipeline, an evaluator role, and the
AQL action-proposal extension for continuous action spaces.

Architecture stance (TPU-first, not a port):

* The learner step — replay ingest, stratified prioritized sampling, loss,
  backward, optimizer update, and priority write-back — is ONE jit-compiled
  XLA program operating on donated HBM buffers (``apex_tpu.training.learner``).
* The prioritized replay buffer is HBM-resident: flat ``jnp`` sum/min trees
  with a vectorized fixed-depth descent instead of the reference's pointer-
  chasing Python trees guarded by a single lock (``apex_tpu.replay``).
* Multi-chip scaling uses ``jax.sharding.Mesh`` + ``shard_map`` with
  ``psum`` gradient all-reduce over ICI, in place of the role NCCL would
  play (``apex_tpu.parallel``).
* Actors stay host-CPU Python processes; the host<->device plane is
  double-buffered staging feeding ``jax.device_put``; the host<->host plane
  is ZeroMQ with the reference's backpressure semantics (``apex_tpu.runtime``).
"""

__version__ = "0.1.0"

from apex_tpu import config as config

__all__ = ["config", "__version__"]
