"""Rendering for the ``enjoy`` role (reference ``origin_repo/enjoy.py:29-48``).

The reference calls ``env.render()`` to a screen; this image (and any
cluster host) is headless, so the equivalents are terminal ASCII rendering
and frame capture to disk:

* ``ascii`` — pixel observations are downsampled to a character raster and
  redrawn in place (ANSI cursor-home), vector observations print as one
  line per step;
* ``save`` — every observation is appended to an in-memory episode buffer
  and written as ``.npy`` stacks per episode (dependency-free; convert to
  video offline with any tool).

``make_render_hook`` returns a callable matching
:func:`apex_tpu.training.checkpoint.evaluate_checkpoint`'s ``render_hook``
contract (called with the raw observation every step).
"""

from __future__ import annotations

import os
import sys

import numpy as np

# dark -> bright luminance ramp
_RAMP = " .:-=+*#%@"


def ascii_frame(obs: np.ndarray, width: int = 64) -> str:
    """One pixel observation -> a character raster.  Stacked frames render
    their NEWEST channel (the current frame; stacks are oldest-first)."""
    arr = np.asarray(obs)
    if arr.ndim == 3:
        arr = arr[..., -1]
    h, w = arr.shape
    cols = min(width, w)
    rows = max(1, int(h * cols / w / 2))      # terminal cells are ~2:1
    ys = (np.arange(rows) * (h / rows)).astype(int)
    xs = (np.arange(cols) * (w / cols)).astype(int)
    small = arr[ys][:, xs].astype(np.float32)
    lo, hi = float(small.min()), float(small.max())
    norm = (small - lo) / (hi - lo) if hi > lo else np.zeros_like(small)
    idx = (norm * (len(_RAMP) - 1)).astype(int)
    return "\n".join("".join(_RAMP[i] for i in row) for row in idx)


def make_render_hook(mode: str, out_dir: str | None = None,
                     stream=None):
    """``mode``: ``ascii`` | ``save`` (requires ``out_dir``).  Returns
    ``hook(obs)``; the hook carries a ``flush_episode()`` method the enjoy
    loop calls between episodes (save mode writes one stack per episode)."""
    stream = stream or sys.stdout

    if mode == "ascii":
        def hook(obs):
            arr = np.asarray(obs)
            if arr.ndim >= 2:
                # cursor home + clear-to-end redraws the raster in place
                stream.write("\x1b[H\x1b[J" + ascii_frame(arr) + "\n")
            else:
                stream.write(" ".join(f"{v:+.3f}" for v in arr.ravel())
                             + "\n")
            stream.flush()

        hook.flush_episode = lambda: None
        return hook

    if mode == "save":
        if not out_dir:
            raise ValueError("render mode 'save' needs --render-dir")
        os.makedirs(out_dir, exist_ok=True)
        frames: list[np.ndarray] = []
        episode = [0]

        def hook(obs):
            frames.append(np.asarray(obs).copy())

        def flush_episode():
            if frames:
                path = os.path.join(out_dir, f"episode_{episode[0]:03d}.npy")
                np.save(path, np.stack(frames))
                frames.clear()
                episode[0] += 1

        hook.flush_episode = flush_episode
        return hook

    raise ValueError(f"unknown render mode {mode!r}: ascii | save")
