"""Throughput counters and metric logging.

The reference's observability is wall-clock BPS prints on the learner
(``origin_repo/learner.py:171-175``) and per-role tensorboardX scalars
(``learner.py:160-174``, ``actor.py:91-92``, ``eval.py:79-80``).  We keep the
same name-spaced scalar scheme and add steps/sec/chip + env-frames/sec — the
BASELINE.json primary metric."""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of a PRE-SORTED sequence: the smallest
    element with at least ``q`` of the mass at or below it
    (``ceil(q*n) - 1``).  For an even-length median this is the LOWER
    middle element — the naive ``vals[n // 2]`` picks the upper one,
    which biases short windows upward (the DispatchGapTimer defect this
    replaced).  Returns 0.0 on empty input.  Pure stdlib — the obs plane
    imports it from worker processes before JAX initializes."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    return sorted_vals[max(0, math.ceil(q * n) - 1)]


class RateCounter:
    """Sliding-window events/sec (learner BPS, actor FPS)."""

    def __init__(self, window: int = 100):
        self._ticks: deque[tuple[float, int]] = deque(maxlen=window)
        self.total = 0

    def tick(self, n: int = 1) -> None:
        self.total += n
        self._ticks.append((time.perf_counter(), n))

    @property
    def rate(self) -> float:
        if len(self._ticks) < 2:
            return 0.0
        span = self._ticks[-1][0] - self._ticks[0][0]
        events = sum(n for _, n in list(self._ticks)[1:])
        return 0.0 if span <= 0 else events / span


class MetricLogger:
    """Name-spaced scalar logger; tensorboardX if available, always stdout-capable."""

    def __init__(self, role: str, logdir: str | None = None, verbose: bool = False):
        self.role = role
        self.logdir = logdir        # sidecar artifacts (fleet_summary.json)
        self.verbose = verbose
        self._writer = None
        if logdir is not None:
            try:
                from tensorboardX import SummaryWriter
                self._writer = SummaryWriter(logdir)
            except Exception as e:
                import warnings
                warnings.warn(f"tensorboard writer unavailable for {logdir}: {e}")
                self._writer = None
        self.history: dict[str, deque[tuple[int, float]]] = {}

    def scalar(self, name: str, value: float, step: int) -> None:
        tag = f"{self.role}/{name}"
        self.history.setdefault(tag, deque(maxlen=100_000)).append(
            (step, float(value)))
        if self._writer is not None:
            self._writer.add_scalar(tag, value, step)
        if self.verbose:
            print(f"[{tag}] step={step} {value:.6g}", flush=True)

    def scalars(self, values: dict[str, Any], step: int) -> None:
        for k, v in values.items():
            self.scalar(k, float(v), step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
