"""Profiler hooks + MFU accounting (SURVEY.md §5.1).

The reference's observability is wall-clock BPS prints
(``origin_repo/learner.py:171-175``).  TPU-side we add the two numbers that
actually locate a bottleneck:

* :func:`trace` — ``jax.profiler`` trace context; open the dump in
  TensorBoard/XProf to see per-op HBM + MXU utilization.
* :func:`flops_per_call` / :func:`mfu` — XLA's own cost analysis for a
  jitted callable, turned into model-FLOPs-utilization given the chip's
  peak.  This is the honest "how much of the MXU are we using" metric for
  the fused learner step (bench.py reports it).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Iterator

import jax

from apex_tpu.utils.metrics import percentile  # noqa: F401 (re-export)

# bf16 peak FLOPs/s per chip for common TPU generations (public specs);
# bench/callers can override explicitly.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
}
DEFAULT_PEAK = PEAK_FLOPS["v5e"]


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """``with trace("/tmp/prof"): run_steps()`` -> XProf dump in logdir."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def flops_per_call(jitted, *args, **kwargs) -> float | None:
    """XLA-estimated FLOPs of one call of a jitted function, or None when
    the backend exposes no cost analysis (e.g. some CPU builds)."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):      # one entry per device program
            analysis = analysis[0]
        return float(analysis["flops"])
    except Exception:
        return None


def mfu(flops: float | None, calls_per_sec: float,
        peak_flops: float = DEFAULT_PEAK) -> float | None:
    """Model-FLOPs-utilization in [0, 1]."""
    if flops is None or peak_flops <= 0:
        return None
    return flops * calls_per_sec / peak_flops


class PhaseTimer:
    """Named wall-time phase accounting for a host loop (the actor-plane
    counterpart of :class:`DispatchGapTimer`): callers wrap each phase of a
    step — policy-wait, env-step, chunk drain — and :meth:`window` reports
    what fraction of the elapsed wall each phase consumed since the last
    reset.  Fractions need not sum to 1; the remainder is unattributed
    host time (param polls, Python bookkeeping).

    Pure host timing — never touches the device, so it is safe on the hot
    loop.

    ``ring``: an optional :class:`apex_tpu.obs.trace.TraceRing` — when
    attached, every completed phase also lands in the per-role trace ring
    as one Chrome trace event (host clock reads only; apexlint J006/J010
    stay clean).
    """

    def __init__(self, ring=None, track: str | None = None):
        self._acc: dict[str, float] = {}
        self._t0 = time.perf_counter()
        self.ring = ring
        self.track = track

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t
            self.add(name, dur)
            if self.ring is not None:
                self.ring.complete(name, t, dur, track=self.track)

    def add(self, name: str, seconds: float) -> None:
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def window(self, reset: bool = True) -> dict:
        """``{"wall_s", "fracs": {name: frac}}`` over the window since
        construction or the last resetting call."""
        now = time.perf_counter()
        wall = max(now - self._t0, 1e-9)
        out = {"wall_s": wall,
               "fracs": {k: v / wall for k, v in self._acc.items()}}
        if reset:
            self._acc = {k: 0.0 for k in self._acc}
            self._t0 = now
        return out


class DispatchGapTimer:
    """Host-side dispatch-gap accounting for async-dispatch hot loops.

    The gap is the wall time between one device dispatch RETURNING (the
    jitted call handing back futures — not the computation finishing) and
    the next dispatch being ISSUED.  Under async dispatch that gap is
    exactly the host-side hole in the device's work feed: polling, chunk
    stacking, H2D staging, Python bookkeeping.  A saturated learner keeps
    it near zero; the ingest pipeline exists to move the gap's contents
    onto a staging thread (training/ingest_pipeline.py).

    Pure host timing — never touches the device, so it is safe on the hot
    loop (unlike ``block_until_ready`` fences, which apexlint J006 flags
    there).
    """

    def __init__(self, window: int = 512, ring=None,
                 track: str | None = None):
        self._last_return: float | None = None
        self._gaps: deque[float] = deque(maxlen=window)
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        # optional obs.trace ring: each measured gap becomes one
        # "host_gap" trace event (host timing only)
        self.ring = ring
        self.track = track

    def about_to_dispatch(self) -> None:
        """Call immediately before issuing a device dispatch."""
        if self._last_return is None:
            return
        t0 = self._last_return
        gap = time.perf_counter() - t0
        self._gaps.append(gap)
        self.count += 1
        self.total += gap
        if gap > self.max:
            self.max = gap
        self._last_return = None
        if self.ring is not None:
            self.ring.complete("host_gap", t0, gap, track=self.track)

    def dispatch_returned(self) -> None:
        """Call immediately after the dispatch call returns."""
        self._last_return = time.perf_counter()

    def snapshot(self) -> dict:
        """Non-mutating stats dict (ms units; nearest-rank percentiles
        over the last ``window`` gaps) — callers may sample it at any
        cadence."""
        gaps = sorted(self._gaps)
        return {
            "dispatch_gap_ms_mean":
                1000.0 * self.total / self.count if self.count else 0.0,
            "dispatch_gap_ms_p50": 1000.0 * percentile(gaps, 0.50),
            "dispatch_gap_ms_p90": 1000.0 * percentile(gaps, 0.90),
            "dispatch_gap_ms_p99": 1000.0 * percentile(gaps, 0.99),
            "dispatch_gap_ms_max": 1000.0 * self.max,
            "dispatches": self.count,
        }
