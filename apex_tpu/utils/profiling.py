"""Profiler hooks + MFU accounting (SURVEY.md §5.1).

The reference's observability is wall-clock BPS prints
(``origin_repo/learner.py:171-175``).  TPU-side we add the two numbers that
actually locate a bottleneck:

* :func:`trace` — ``jax.profiler`` trace context; open the dump in
  TensorBoard/XProf to see per-op HBM + MXU utilization.
* :func:`flops_per_call` / :func:`mfu` — XLA's own cost analysis for a
  jitted callable, turned into model-FLOPs-utilization given the chip's
  peak.  This is the honest "how much of the MXU are we using" metric for
  the fused learner step (bench.py reports it).
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax

# bf16 peak FLOPs/s per chip for common TPU generations (public specs);
# bench/callers can override explicitly.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
}
DEFAULT_PEAK = PEAK_FLOPS["v5e"]


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """``with trace("/tmp/prof"): run_steps()`` -> XProf dump in logdir."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def flops_per_call(jitted, *args, **kwargs) -> float | None:
    """XLA-estimated FLOPs of one call of a jitted function, or None when
    the backend exposes no cost analysis (e.g. some CPU builds)."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):      # one entry per device program
            analysis = analysis[0]
        return float(analysis["flops"])
    except Exception:
        return None


def mfu(flops: float | None, calls_per_sec: float,
        peak_flops: float = DEFAULT_PEAK) -> float | None:
    """Model-FLOPs-utilization in [0, 1]."""
    if flops is None or peak_flops <= 0:
        return None
    return flops * calls_per_sec / peak_flops
