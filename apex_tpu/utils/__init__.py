from apex_tpu.utils.seeding import set_global_seeds, split_key
from apex_tpu.utils.metrics import RateCounter, MetricLogger

__all__ = ["set_global_seeds", "split_key", "RateCounter", "MetricLogger"]
