"""Seeding discipline.

The reference seeds three implicit global RNGs (``utils.py:15-22``).  On TPU the
numeric path must use explicit ``jax.random`` keys threaded through every
stochastic op (epsilon-greedy, NoisyNet noise, proposal sampling); numpy/stdlib
seeding remains for host-side actors.
"""

from __future__ import annotations

import random

import jax
import numpy as np


def set_global_seeds(seed: int) -> jax.Array:
    """Seed host RNGs and return a root JAX key (reference: utils.py:15-22)."""
    np.random.seed(seed)
    random.seed(seed)
    return jax.random.key(seed)


def split_key(key: jax.Array, n: int = 2):
    """Thin wrapper so call sites read uniformly."""
    return jax.random.split(key, n)
