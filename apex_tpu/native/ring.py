"""Python bindings for the shared-memory MPSC ring (``shm_ring.cpp``).

Two layers:

* :class:`ShmRing` — thin ctypes wrapper over the C ABI (bytes in/out).
* :class:`ShmChunkQueue` — the mp.Queue-shaped facade
  :class:`apex_tpu.actors.pool.ActorPool` uses for its chunk plane: same
  ``put / get / get_nowait / close / cancel_join_thread`` surface, same
  blocking-when-full backpressure, but the payload crosses process
  boundaries through one shared-memory copy instead of pickle->pipe->
  feeder-thread.  Messages are pickled (protocol 5) like the wire format
  everywhere else in the runtime; the win is the transport, not the codec.

The facade pickles cleanly: children receive only the segment name and
re-open the ring lazily on first use (the C side maps the same physical
pages).  The CREATOR process (the learner) owns the segment and unlinks it
on close.
"""

from __future__ import annotations

import ctypes
import pickle
import queue as queue_lib
import time

from apex_tpu import native
from apex_tpu.runtime.wire import restricted_loads


class ShmRingError(RuntimeError):
    pass


class ShmRing:
    """One shared-memory ring: many producers, one consumer."""

    def __init__(self, name: str, slot_size: int = 0, n_slots: int = 0,
                 create: bool = False):
        lib = native._load()
        if lib is None:
            raise ShmRingError(f"native ring unavailable: "
                               f"{native.build_error()}")
        if not name.startswith("/"):
            name = "/" + name
        self.name = name
        self._lib = lib
        if create:
            if slot_size <= 8 or n_slots <= 0:
                raise ValueError("create needs slot_size > 8 and n_slots > 0")
            self._h = lib.apex_shm_create(name.encode(), slot_size, n_slots)
        else:
            self._h = lib.apex_shm_open(name.encode())
        if not self._h:
            raise ShmRingError(f"could not {'create' if create else 'open'} "
                               f"shm ring {name!r}")
        self.slot_size = int(lib.apex_shm_slot_size(self._h))
        self._buf = ctypes.create_string_buffer(self.slot_size)
        self.corrupt_drops = 0   # torn-length payloads disposed by pop

    # -- raw ops -----------------------------------------------------------

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        """False when not delivered — ring full (timeout) or the ticket
        was disposed by a consumer force-skip while this producer stalled
        (rc -3); either way a retry re-sends under a fresh ticket.  Raises
        when ``data`` can never fit a slot."""
        rc = self._lib.apex_shm_push(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise ShmRingError(
                f"message of {len(data)} bytes exceeds slot size "
                f"{self.slot_size} (raise ActorConfig.shm_slot_bytes)")
        return rc == 0

    def pop(self, timeout_ms: int = 0) -> bytes | None:
        """Next message, or None on timeout."""
        rc = self._lib.apex_shm_pop(self._h, self._buf,
                                    self.slot_size, timeout_ms)
        if rc == -2:  # cannot happen: _buf is slot-sized
            raise ShmRingError("pop buffer smaller than slot")
        if rc == -3:  # torn length prefix disposed in-place (C-side
            self.corrupt_drops += 1   # contract) — treat as one lost msg
            return None
        if rc < 0:
            return None
        return self._buf.raw[:rc]

    def pending(self) -> int:
        return int(self._lib.apex_shm_pending(self._h))

    def force_skip(self) -> bool:
        """Plant a tombstone over a claimed-but-never-published head ticket
        (producer died mid-write).  Call ONLY after a long starvation
        window — see the C-side contract in shm_ring.cpp."""
        return bool(self._lib.apex_shm_force_skip(self._h))

    def push_timeouts(self) -> int:
        """Cumulative push timeout returns — BACKPRESSURE events (a full
        ring made a producer wait out a slice), not lost messages; blocking
        callers retry and nothing is dropped."""
        return int(self._lib.apex_shm_dropped(self._h))

    def disposed(self) -> int:
        """Tickets force-skipped away from stalled producers — each was
        one undelivered message (the producer's push returned -3 and, in
        the facade, was resent under a fresh ticket)."""
        return int(self._lib.apex_shm_disposed(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.apex_shm_close(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real path
        try:
            self.close()
        except Exception:
            pass


class ShmChunkQueue:
    """mp.Queue facade over :class:`ShmRing` for the ActorPool chunk plane.

    The parent constructs it (``create=True`` — owns/unlinks the segment);
    worker processes get a pickled copy holding only the name and re-open
    lazily.  ``put`` blocks while the ring is full, in 200ms slices so a
    terminated consumer never wedges a worker harder than mp.Queue would.
    """

    _counter = 0

    @classmethod
    def next_id(cls) -> int:
        """Process-local id for unique segment names (one per pool)."""
        cls._counter += 1
        return cls._counter

    # a wedged head ticket (producer SIGKILLed inside its microsecond
    # claim->publish window) is force-skipped after this much continuous
    # starvation with pending messages — orders of magnitude beyond any
    # live producer's memcpy
    STUCK_SECONDS = 10.0

    def __init__(self, name: str, slot_bytes: int, depth: int):
        self.name = name
        self.slot_bytes = slot_bytes
        self.depth = depth
        self._ring: ShmRing | None = ShmRing(
            name, slot_size=slot_bytes, n_slots=depth, create=True)
        self._owner = True
        self._starved_since: float | None = None
        self.skipped = 0                # force-skipped wedged tickets

    # -- pickling into workers --------------------------------------------

    def __getstate__(self):
        return {"name": self.name, "slot_bytes": self.slot_bytes,
                "depth": self.depth}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._ring = None          # re-open lazily in the child
        self._owner = False
        self._starved_since = None
        self.skipped = 0

    def _open(self) -> ShmRing:
        if self._ring is None:
            self._ring = ShmRing(self.name)
        return self._ring

    # -- mp.Queue surface used by pool.py / roles adapters -----------------

    def put(self, item) -> None:
        data = pickle.dumps(item, protocol=5)
        ring = self._open()
        while not ring.push(data, timeout_ms=200):
            pass                   # full: keep blocking, like mp.Queue.put

    def get(self, timeout: float = 0.0):
        return self._get(max(1, int(timeout * 1000)))

    def get_nowait(self):
        return self._get(0)

    def _get(self, timeout_ms: int):
        ring = self._open()
        corrupt_before = ring.corrupt_drops
        got = ring.pop(timeout_ms=timeout_ms)
        if got is None and ring.corrupt_drops > corrupt_before:
            # a torn-length payload was disposed, not a timeout: count it
            # like an unpickle failure and don't start the starvation clock
            self.skipped += 1
            raise queue_lib.Empty
        if got is not None:
            self._starved_since = None
            try:
                # restricted wire even in-host: one unpickler discipline
                # for every process boundary (apexlint C005)
                return restricted_loads(got)
            except Exception:
                # a force-skipped producer's resurrected memcpy can corrupt
                # one payload (shm_ring.cpp force-skip contract): count and
                # drop it rather than crash the learner
                self.skipped += 1
                raise queue_lib.Empty
        # starving: if messages are pending but nothing publishes for
        # STUCK_SECONDS, the head ticket's producer died mid-write —
        # tombstone it so the ring advances (shm_ring.cpp force-skip
        # contract)
        if ring.pending() > 0:
            now = time.monotonic()
            if self._starved_since is None:
                self._starved_since = now
            elif now - self._starved_since > self.STUCK_SECONDS:
                if ring.force_skip():
                    self.skipped += 1
                self._starved_since = None
        else:
            self._starved_since = None
        raise queue_lib.Empty

    def pending(self) -> int:
        return self._open().pending()

    def cancel_join_thread(self) -> None:   # no feeder thread to detach
        pass

    def close(self) -> None:
        if self._ring is not None:
            self._ring.close()
            self._ring = None


def chunk_slot_bytes(frame_dim: int, frame_dtype_size: int, kf: int,
                     k: int, stack: int, margin: int = 65536) -> int:
    """Conservative slot size for a frame-chunk message: the frames array
    dominates; transition fields and pickle framing ride in the margin."""
    frames = kf * frame_dim * frame_dtype_size
    trans = k * (2 * stack + 3) * 4 + k * 4
    return frames + trans + margin
