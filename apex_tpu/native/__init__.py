"""First-party native runtime components (C++, ctypes-bound).

The TPU compute path is JAX/XLA; the host runtime around it is native where
the hot path justifies it.  Today that is the in-host actor->learner data
plane: :mod:`apex_tpu.native.ring` replaces ``multiprocessing.Queue``'s
pickle->pipe->feeder-thread hops with a shared-memory MPSC ring
(``shm_ring.cpp``).

The library builds on demand with the image's ``g++`` (no pybind11 — plain
C ABI + ctypes) into ``_build/``; anything that can fail (no compiler, no
/dev/shm) degrades gracefully: callers check :func:`shm_available` and fall
back to ``mp.Queue``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "shm_ring.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB = os.path.join(_BUILD_DIR, "libapexshm.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build() -> str | None:
    """Compile the ring if the .so is missing or older than the source.
    Returns an error string, or None on success."""
    try:
        if (os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return None
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = _LIB + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
               _SRC, "-lrt", "-lpthread"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-2000:]}"
        os.replace(tmp, _LIB)  # atomic: concurrent builders don't torn-read
        return None
    except Exception as e:  # missing g++, read-only tree, ...
        return f"{type(e).__name__}: {e}"


def _load() -> ctypes.CDLL | None:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _build_error = str(e)
            return None
        lib.apex_shm_create.restype = ctypes.c_void_p
        lib.apex_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint64]
        lib.apex_shm_open.restype = ctypes.c_void_p
        lib.apex_shm_open.argtypes = [ctypes.c_char_p]
        lib.apex_shm_close.argtypes = [ctypes.c_void_p]
        lib.apex_shm_push.restype = ctypes.c_int
        lib.apex_shm_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.apex_shm_pop.restype = ctypes.c_int64
        lib.apex_shm_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_int]
        for fn in ("apex_shm_dropped", "apex_shm_disposed",
                   "apex_shm_pending", "apex_shm_slot_size"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.apex_shm_force_skip.restype = ctypes.c_int
        lib.apex_shm_force_skip.argtypes = [ctypes.c_void_p]
        lib.apex_shm_test_claim.restype = None
        lib.apex_shm_test_claim.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def shm_available() -> bool:
    """True when the native ring compiled, loads, and /dev/shm works."""
    return _load() is not None and os.path.isdir("/dev/shm")


def build_error() -> str | None:
    """Why the native library is unavailable (None if it is, or untried)."""
    _load()
    return _build_error
