// Shared-memory MPSC ring: the native in-host actor->learner data plane.
//
// The reference's in-host transport is multiprocessing.Queue
// (batchrecorder.py:111-112): every chunk is pickled, pushed through an OS
// pipe by a feeder thread (two extra copies + syscalls per message, small
// pipe buffer), and reassembled on the learner side.  Here the fleet writes
// frame chunks into a POSIX shared-memory segment instead: one memcpy in,
// one memcpy out, zero syscalls on the hot path, and the bounded ring gives
// the same end-to-end backpressure semantics (a full ring blocks producers
// exactly like a full mp.Queue blocks put()).  In-host only — the
// multi-host plane stays on sockets (apex_tpu/runtime/transport.py).
//
// Layout: a Header page, a cacheline-padded sequence word per slot, then
// n_slots fixed-size slots.  Coordination is the bounded-queue sequence
// scheme (Vyukov MPMC), used many-producer/one-consumer:
//
//   producer: t = tail; if seq[t % n] == t, CAS tail -> t+1 claims the
//             slot (already free); write payload; seq = t + 1 publishes.
//             seq < t means the ring is full -> wait WITHOUT claiming, so
//             a timeout simply returns and nothing is left half-claimed.
//   consumer: h = head (single consumer, plain variable); seq[h % n] ==
//             h + 1 means published; read; seq = h + n frees the slot for
//             ticket h + n.
//
// Waits spin briefly then sleep-poll (50us); chunk rates are O(10^2)
// messages/s, so poll latency is irrelevant — copy count is what matters.
//
// Crash notes: a producer killed between CAS-claim and publish (a
// microsecond window) leaves one slot unpublished, starving the consumer
// at that ticket — the same class of loss as killing a process inside
// mp.Queue.put (corrupted pipe).  The consumer recovers via
// apex_shm_force_skip after a long starvation window (see the function's
// contract below; ShmChunkQueue applies it automatically).
// ActorPool.cleanup drains with timeouts and destroys the segment, so
// shutdown never depends on ring liveness.  The creator unlinks any stale
// same-named segment left by a crashed run.
//
// Exposed as a plain-C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x41504558534852ULL;  // "APEXSHR"

struct Header {
  uint64_t magic;
  uint64_t slot_size;   // bytes per slot, including the 8-byte length prefix
  uint64_t n_slots;
  alignas(64) std::atomic<uint64_t> tail;  // next producer ticket
  alignas(64) uint64_t head;               // consumer cursor (one consumer)
  alignas(64) std::atomic<uint64_t> dropped;  // push timeout returns
  // (backpressure events for blocking callers, NOT lost messages)
  alignas(64) std::atomic<uint64_t> disposed;  // tickets force-skipped away
  // from stalled producers (each is one undelivered message, resendable)
};

struct Seq {   // one per slot, padded: adjacent slots' producers don't
  alignas(64) std::atomic<uint64_t> v;      // false-share the sequence word
};

struct Ring {
  Header* hdr;
  Seq* seq;       // [n_slots]
  uint8_t* slots;
  size_t map_len;
  int owner;      // created (vs opened) — unlink on close
  char name[64];
};

inline void sleep_us(long us) {
  timespec ts{0, us * 1000L};
  nanosleep(&ts, nullptr);
}

inline double now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1e3 + ts.tv_nsec / 1e6;
}

inline void backoff(int* spins) {
  if (++*spins < 64) sched_yield();
  else sleep_us(50);
}

Ring* map_ring(const char* name, int create, uint64_t slot_size,
               uint64_t n_slots) {
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;

  size_t len = 0;
  if (create) {
    len = sizeof(Header) + sizeof(Seq) * n_slots + slot_size * n_slots;
    if (ftruncate(fd, (off_t)len) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    len = (size_t)st.st_size;
  }

  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);  // the mapping holds its own reference
  if (mem == MAP_FAILED) return nullptr;

  auto* hdr = (Header*)mem;
  auto* seq = (Seq*)((uint8_t*)mem + sizeof(Header));
  if (create) {
    hdr->magic = kMagic;
    hdr->slot_size = slot_size;
    hdr->n_slots = n_slots;
    hdr->tail.store(0, std::memory_order_relaxed);
    hdr->head = 0;
    hdr->dropped.store(0, std::memory_order_relaxed);
    hdr->disposed.store(0, std::memory_order_relaxed);
    for (uint64_t i = 0; i < n_slots; ++i)
      seq[i].v.store(i, std::memory_order_relaxed);
  } else if (hdr->magic != kMagic) {
    munmap(mem, len);
    return nullptr;
  }

  auto* r = new Ring;
  r->hdr = hdr;
  r->seq = seq;
  r->slots = (uint8_t*)mem + sizeof(Header) + sizeof(Seq) * hdr->n_slots;
  r->map_len = len;
  r->owner = create;
  strncpy(r->name, name, sizeof(r->name) - 1);
  r->name[sizeof(r->name) - 1] = '\0';
  return r;
}

}  // namespace

extern "C" {

void* apex_shm_create(const char* name, uint64_t slot_size,
                      uint64_t n_slots) {
  shm_unlink(name);  // stale segment from a crashed run
  return map_ring(name, 1, slot_size, n_slots);
}

void* apex_shm_open(const char* name) { return map_ring(name, 0, 0, 0); }

void apex_shm_close(void* handle) {
  if (!handle) return;
  auto* r = (Ring*)handle;
  if (r->owner) shm_unlink(r->name);
  munmap((void*)r->hdr, r->map_len);
  delete r;
}

// 0 = ok, -1 = timeout (ring full; nothing claimed), -2 = payload too
// large for a slot, -3 = ticket disposed by the consumer's force-skip
// while this producer was stalled (message NOT delivered; caller may
// simply push again under a fresh ticket).
int apex_shm_push(void* handle, const uint8_t* data, uint64_t len,
                  int timeout_ms) {
  auto* r = (Ring*)handle;
  Header* h = r->hdr;
  if (len + 8 > h->slot_size) return -2;

  double deadline = now_ms() + timeout_ms;
  int spins = 0;
  uint64_t t;
  for (;;) {
    t = h->tail.load(std::memory_order_relaxed);
    uint64_t s = t % h->n_slots;
    uint64_t sv = r->seq[s].v.load(std::memory_order_acquire);
    if (sv == t) {
      if (h->tail.compare_exchange_weak(t, t + 1,
                                        std::memory_order_relaxed))
        break;  // claimed a known-free slot
      // lost the race to another producer; retry immediately
    } else if (sv < t) {
      // ring full (slot not yet freed by the consumer): wait unclaimed
      if (timeout_ms >= 0 && now_ms() > deadline) {
        h->dropped.fetch_add(1, std::memory_order_relaxed);
        return -1;
      }
      backoff(&spins);
    }
    // sv > t: another producer published past us between the loads; retry
  }
  uint64_t s = t % h->n_slots;
  uint8_t* slot = r->slots + s * h->slot_size;
  memcpy(slot, &len, 8);
  memcpy(slot + 8, data, len);
  // Publish via CAS: if the consumer force-skipped this ticket while we
  // were stalled between claim and here, seq has already moved on — we
  // must NOT touch it (a blind store would deadlock the ring for every
  // later ticket on this slot).  The memcpy above may then have raced the
  // slot's next owner; the consumer tolerates that as one corrupt payload
  // (unpickle failure -> skipped), and we report -3 so the caller resends.
  uint64_t expect = t;
  if (!r->seq[s].v.compare_exchange_strong(expect, t + 1,
                                           std::memory_order_release,
                                           std::memory_order_relaxed)) {
    return -3;  // the skip itself was already counted in disposed
  }
  return 0;
}

// >=0 = payload length, -1 = timeout, -2 = out buffer too small,
// -3 = torn/corrupt length prefix (payload disposed, head advanced).
int64_t apex_shm_pop(void* handle, uint8_t* out, uint64_t cap,
                     int timeout_ms) {
  auto* r = (Ring*)handle;
  Header* h = r->hdr;
  uint64_t t = h->head;
  uint64_t s = t % h->n_slots;
  uint8_t* slot = r->slots + s * h->slot_size;

  double deadline = now_ms() + timeout_ms;
  int spins = 0;
  while (r->seq[s].v.load(std::memory_order_acquire) != t + 1) {
    if (timeout_ms >= 0 && now_ms() > deadline) return -1;
    backoff(&spins);
  }
  uint64_t len;
  memcpy(&len, slot, 8);
  if (len > h->slot_size - 8) {
    // Torn length prefix: a force-skipped producer's resurrected memcpy
    // raced this slot's reuse (see force-skip contract).  No valid push
    // can exceed slot_size - 8 (push rejects those with -2), so dispose
    // of the payload and keep the ring advancing instead of wedging.
    h->head = t + 1;
    r->seq[s].v.store(t + h->n_slots, std::memory_order_release);
    h->disposed.fetch_add(1, std::memory_order_relaxed);
    return -3;
  }
  if (len > cap) return -2;
  if (len) memcpy(out, slot + 8, len);
  h->head = t + 1;
  r->seq[s].v.store(t + h->n_slots, std::memory_order_release);
  return (int64_t)len;
}

uint64_t apex_shm_dropped(void* handle) {
  return ((Ring*)handle)->hdr->dropped.load(std::memory_order_relaxed);
}

uint64_t apex_shm_disposed(void* handle) {
  return ((Ring*)handle)->hdr->disposed.load(std::memory_order_relaxed);
}

// Consumer-side wedge recovery: if the head ticket was claimed (tail moved
// past it) but never published — its producer died (or stalled
// indefinitely) between CAS-claim and its publish — dispose of the ticket
// and free the slot in ONE CAS (t -> t + n_slots), advancing head past it.
// The CALLER supplies the liveness judgment (e.g. "pop has timed out for N
// seconds while pending() > 0").  If the claimant later resurrects, its
// own publish CAS fails cleanly (returns -3, see apex_shm_push); the only
// residual risk is its in-flight memcpy racing the slot's next owner —
// one corrupt payload, caught at unpickle, never a wedged ring.
// Returns 1 if skipped, 0 if the head is published/unclaimed.
int apex_shm_force_skip(void* handle) {
  auto* r = (Ring*)handle;
  Header* h = r->hdr;
  uint64_t t = h->head;
  if (h->tail.load(std::memory_order_relaxed) <= t) return 0;  // unclaimed
  uint64_t s = t % h->n_slots;
  uint64_t expect = t;  // claimed-but-unpublished state
  if (!r->seq[s].v.compare_exchange_strong(expect, t + h->n_slots,
                                           std::memory_order_release,
                                           std::memory_order_relaxed))
    return 0;           // published in the meantime: nothing to skip
  h->head = t + 1;
  h->disposed.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

// TEST ONLY: claim the next ticket and never publish it — simulates a
// producer killed mid-write so force_skip paths can be exercised.
void apex_shm_test_claim(void* handle) {
  ((Ring*)handle)->hdr->tail.fetch_add(1, std::memory_order_relaxed);
}

// Messages published-or-claimed and not yet consumed (approximate).
uint64_t apex_shm_pending(void* handle) {
  auto* r = (Ring*)handle;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t head = r->hdr->head;
  return tail > head ? tail - head : 0;
}

uint64_t apex_shm_slot_size(void* handle) {
  return ((Ring*)handle)->hdr->slot_size;
}

}  // extern "C"
