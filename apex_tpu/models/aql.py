"""AQL — proposal-action Q-learning for continuous action spaces.

Capability parity with the reference ``AQL``/``Q_Network``/``Proposal_Network``
(``model.py:169-390``): Q-learning over a per-state CANDIDATE SET of actions —
``uniform_sample`` draws from the action box plus ``propose_sample`` draws
from a learned Gaussian proposal (fixed diagonal covariance ``action_var``,
``model.py:365-369``) — scored by a Q head whose advantage MLP uses NoisyNet
layers for exploration (``model.py:268-270``).  Acting = argmax over the
candidate scores, epsilon-greedy over the candidate INDEX
(``model.py:330-335``); the candidate set ``a_mu`` is stored with the
transition so the learner re-scores the same set (``memory.py:364-391``).

TPU-first redesign (not a port):

* One flax module, one params tree; the proposal head lives under the
  ``proposal`` scope so the two-optimizer split (``AQL.py:41-42``) is a pure
  label function over the tree — no separate networks with copied trunks.
* All sampling is functional: candidate draws use a ``'sample'`` PRNG
  collection, NoisyDense noise a ``'noise'`` collection; there is no
  ``reset_noise`` side effect — every ``apply`` with a fresh key IS the
  reset (``AQL_dis.py:104-105`` semantics by construction).
* Candidate scoring is one batched einsum-friendly pass over ``[B, T]``
  pairs — the (state-embed, action-embed) tiling the reference does with
  ``repeat``/``reshape`` (``model.py:294-320``) is a broadcast, no data
  motion, and the ``[B*T, feat]`` matmuls land on the MXU.

Discrete action spaces (``discrete=True``): the reference routes discrete
envs through the same machinery with a Categorical proposal
(``model.py:370-376``) and feeds the candidate INDEX to the Q action-embed
as a float scalar (``model.py:321-323``).  Same here: candidates are
``[B, T, 1]`` float index values — the identical tensor contract as the
continuous ``[B, T, A]`` — so replay storage, the losses, and the actor
families are shared verbatim between the two families.  The uniform half
of the candidate set draws DISTINCT actions per row (the reference's
``np.random.choice(..., replace=False)``, ``model.py:371-373``, done here
as per-row permutations so batches > 1 are correct), and ``uniform_sample``
is clamped to the action count at spec build (``model.py:180-184``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.models.dueling import orthogonal_init
from apex_tpu.models.noisy import NoisyDense


class AQLNetwork(nn.Module):
    """Embedding trunk + proposal head + candidate-scoring Q head.

    Attributes:
      action_dim: dimensionality of the Box action space, or the action
        COUNT when ``discrete`` (the proposal head then emits logits).
      action_low/high: box bounds (uniform candidates are drawn here).
      propose_sample/uniform_sample: candidate-set split (``model.py:170``).
      action_var: fixed diagonal variance of the proposal Gaussian.
      discrete: Categorical proposal over ``action_dim`` actions; candidate
        tensors are ``[B, T, 1]`` float index values.
      noisy_deterministic: mu-only NoisyDense (eval mode).
    """

    action_dim: int
    action_low: float = -1.0
    action_high: float = 1.0
    discrete: bool = False
    propose_sample: int = 100
    uniform_sample: int = 400
    action_var: float = 0.25
    obs_is_image: bool = False
    compute_dtype: jnp.dtype = jnp.float32
    scale_uint8: bool = False
    noisy_deterministic: bool = False
    trunk_features: Sequence[int] = (32, 64, 64)

    @property
    def total_sample(self) -> int:
        return self.propose_sample + self.uniform_sample

    def setup(self):
        if self.discrete and self.uniform_sample > self.action_dim:
            # aql_model_spec clamps this (model.py:180-184); a directly
            # constructed model must fail HERE, not as an opaque shape
            # mismatch at ingest (total_sample would over-report)
            raise ValueError(
                f"discrete uniform_sample={self.uniform_sample} > "
                f"action count {self.action_dim}: distinct uniform draws "
                f"are impossible — clamp to the action count")
        dt = self.compute_dtype
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, dtype=dt, kernel_init=orthogonal_init(),
            bias_init=nn.initializers.zeros, name=name)
        # state embedding feeding the proposal (model.py:283-287)
        self.embed_hidden = dense(128, "embed_hidden")
        # proposal head: embed -> mu (model.py:356-360); the "proposal"/
        # "embed" scope prefixes are the two-optimizer split keys
        # (ops.losses.aql_param_labels)
        self.proposal_hidden = dense(128, "proposal_hidden")
        self.proposal_mu = dense(self.action_dim, "proposal_mu")
        # Q-side state feature (model.py:245-250: raw obs -> 64 -> 64)
        self.q_feature1 = dense(64, "q_feature1")
        self.q_feature2 = dense(64, "q_feature2")
        # action embedding (model.py:252-259: A -> 128 -> 64)
        self.action_embed1 = dense(128, "action_embed1")
        self.action_embed2 = dense(64, "action_embed2")
        # NoisyNet advantage scorer (model.py:268-270)
        self.advantage1 = NoisyDense(64, deterministic=self.noisy_deterministic,
                                     compute_dtype=dt, name="advantage1")
        self.advantage2 = NoisyDense(1, deterministic=self.noisy_deterministic,
                                     compute_dtype=dt, name="advantage2")

    # -- pieces ------------------------------------------------------------

    def _prep(self, obs: jax.Array) -> jax.Array:
        dt = self.compute_dtype
        if obs.dtype == jnp.uint8 and self.scale_uint8:
            obs = obs.astype(dt) / jnp.asarray(255.0, dt)
        else:
            obs = obs.astype(dt)
        if self.obs_is_image:
            obs = obs.reshape((obs.shape[0], -1))
        return obs

    def embed(self, obs: jax.Array) -> jax.Array:
        """128-d state embedding (``Q_Network.embedding_feature``)."""
        return nn.relu(self.embed_hidden(self._prep(obs)))

    def proposal_mean(self, obs: jax.Array) -> jax.Array:
        """Gaussian mean of the proposal distribution ``[B, A]`` —
        Categorical logits ``[B, n]`` when ``discrete``."""
        h = nn.relu(self.proposal_hidden(self.embed(obs)))
        return self.proposal_mu(h).astype(jnp.float32)

    def propose(self, obs: jax.Array) -> jax.Array:
        """Draw the candidate set — uniform samples first, proposal draws
        second (``model.py:361-376`` ordering).  ``[B, T, A]`` box points,
        or ``[B, T, 1]`` float index values when ``discrete`` (distinct
        uniform indices per row + Categorical draws).  Needs
        ``rngs={'sample': key}``."""
        b = obs.shape[0]
        mu = self.proposal_mean(obs)
        key = self.make_rng("sample")
        k_u, k_p = jax.random.split(key)
        if self.discrete:
            n = self.action_dim
            perm = jax.vmap(lambda k: jax.random.permutation(k, n))(
                jax.random.split(k_u, b))                    # [B, n]
            a_uniform = perm[:, :self.uniform_sample]        # distinct
            a_prop = jax.random.categorical(
                k_p, mu, axis=-1,
                shape=(self.propose_sample, b)).T            # [B, P]
            a_mu = jnp.concatenate([a_uniform, a_prop], axis=1)
            return a_mu.astype(jnp.float32)[..., None]       # [B, T, 1]
        a_uniform = jax.random.uniform(
            k_u, (b, self.uniform_sample, self.action_dim), jnp.float32,
            self.action_low, self.action_high)
        sigma = jnp.sqrt(jnp.float32(self.action_var))
        a_prop = mu[:, None, :] + sigma * jax.random.normal(
            k_p, (b, self.propose_sample, self.action_dim), jnp.float32)
        return jnp.concatenate([a_uniform, a_prop], axis=1)

    def score(self, obs: jax.Array, a_mu: jax.Array) -> jax.Array:
        """Q-values of every candidate, ``[B, T]`` (``Q_Network.act`` tiling,
        ``model.py:294-320``, as a broadcast).  Needs ``rngs={'noise': key}``
        unless ``noisy_deterministic``."""
        b, t, _ = a_mu.shape
        qf = nn.relu(self.q_feature2(nn.relu(
            self.q_feature1(self._prep(obs)))))              # [B, 64]
        af = nn.relu(self.action_embed2(nn.relu(
            self.action_embed1(a_mu.reshape(b * t, -1)))))   # [B*T, 64]
        x = jnp.concatenate(
            [af.reshape(b, t, -1),
             jnp.broadcast_to(qf[:, None, :], (b, t, qf.shape[-1]))], axis=-1)
        x = nn.relu(x).reshape(b * t, -1)
        adv = self.advantage2(nn.relu(self.advantage1(x)))
        return adv.reshape(b, t).astype(jnp.float32)

    def __call__(self, obs: jax.Array, a_mu: jax.Array) -> jax.Array:
        return self.score(obs, a_mu)

    def full_init(self, obs: jax.Array, a_mu: jax.Array) -> jax.Array:
        """Init entry touching every submodule (score alone would skip the
        embed/proposal params).  ``model.init({'params', 'noise', 'sample'},
        obs, a_mu, method=AQLNetwork.full_init)``."""
        _ = self.propose(obs)
        return self.score(obs, a_mu)

    # -- log-density of the proposal (for the proposal loss) ---------------

    def proposal_log_prob(self, obs: jax.Array,
                          actions: jax.Array) -> tuple[jax.Array, jax.Array]:
        """``(log N(actions | mu(obs), action_var*I), entropy)`` per row —
        Categorical log-pmf + entropy when ``discrete``
        (``AQL_dis.py:79-86``; ``model.py:386-388``).

        Continuous: with the covariance fixed (``model.py:364-365``) the
        entropy is a constant — kept for parity with the reference's
        ``-log_prob - lam*entropy`` objective."""
        mu = self.proposal_mean(obs)
        if self.discrete:
            logp = jax.nn.log_softmax(mu, axis=-1)            # [B, n]
            idx = actions.reshape(actions.shape[0]).astype(jnp.int32)
            log_prob = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
            entropy = -jnp.sum(jnp.exp(logp) * logp, axis=-1)
            return log_prob, entropy
        var = jnp.float32(self.action_var)
        d = self.action_dim
        log_prob = (-0.5 * jnp.sum((actions - mu) ** 2, axis=-1) / var
                    - 0.5 * d * jnp.log(2 * jnp.pi * var))
        entropy = 0.5 * d * (1.0 + jnp.log(2 * jnp.pi * var))
        return log_prob, jnp.broadcast_to(entropy, log_prob.shape)


def make_aql_policy_fn(model: AQLNetwork):
    """Jittable acting step (``AQL.act``, ``model.py:198-205``): propose
    candidates, score them, epsilon-greedy over the candidate index.
    Returns ``(env_actions [B, A], idx [B], a_mu [B, T, A], q [B, T])`` —
    the actor stores ``idx`` + ``a_mu`` so the learner re-scores the exact
    candidate set.  Discrete models return ``env_actions`` as ``int32 [B]``
    (the selected candidate's index value), steppable into a Discrete env
    unchanged."""

    def policy(params, obs: jax.Array, epsilon: jax.Array, key: jax.Array):
        k_sample, k_noise, k_eps, k_rand = jax.random.split(key, 4)
        a_mu = model.apply(params, obs, method=AQLNetwork.propose,
                           rngs={"sample": k_sample})
        q = model.apply(params, obs, a_mu, rngs={"noise": k_noise})
        greedy = q.argmax(axis=1)
        rand = jax.random.randint(k_rand, greedy.shape, 0, model.total_sample)
        explore = jax.random.uniform(k_eps, greedy.shape) < epsilon
        idx = jnp.where(explore, rand, greedy)
        actions = jnp.take_along_axis(
            a_mu, idx[:, None, None], axis=1)[:, 0, :]
        if model.discrete:
            actions = actions[:, 0].astype(jnp.int32)
        return actions, idx, a_mu, q

    return policy
