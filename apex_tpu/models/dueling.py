"""Dueling DQN in flax.linen, NHWC/TPU-native.

Capability parity with the reference ``DuelingDQN`` (``model.py:14-107``):
Nature-DQN conv trunk (32x8s4 / 64x4s2 / 64x3s1, ``model.py:32-39``) for 3-D
observations or a 128-unit MLP trunk for 1-D (``model.py:40-45``), dueling
value/advantage heads of width 128 (``model.py:48-58``), aggregation
``V + A - mean(A)`` (``model.py:68``), orthogonal init with ReLU gain and zero
bias (``model.py:97-107``).

TPU-first deltas (deliberate, not drift):

* **NHWC layout** — the reference is channel-first (``wrapper.py:301-313``);
  XLA:TPU's conv tiling is NHWC-native, so observations are stored and fed
  ``(H, W, stack)``.
* **uint8 in, scale in-model** — the reference scales frames on the host
  (``wrapper.py:207-215``); we keep replay/wire traffic uint8 (4x less HBM
  bandwidth) and fold ``/255`` into the first op of the compiled graph.
* **bfloat16 compute** — matmuls/convs run in bf16 on the MXU, params and the
  head output stay f32.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

_RELU_GAIN = 2.0 ** 0.5  # torch nn.init.calculate_gain('relu'); plain Python
# float so importing this module never touches a JAX backend (the driver's
# multi-chip dryrun must configure the platform before any device work).


def orthogonal_init(gain: float = _RELU_GAIN):
    return nn.initializers.orthogonal(scale=gain)


class DuelingDQN(nn.Module):
    """Q-network with dueling heads.

    Attributes:
      num_actions: size of the discrete action space.
      obs_is_image: 3-D pixel observations (conv trunk) vs 1-D (MLP trunk).
      compute_dtype: matmul/conv dtype (bf16 for the MXU); outputs f32.
      scale_uint8: divide image input by 255 inside the graph.
    """

    num_actions: int
    obs_is_image: bool = True
    compute_dtype: jnp.dtype = jnp.bfloat16
    scale_uint8: bool = True
    trunk_features: Sequence[int] = (32, 64, 64)
    head_width: int = 128

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dt = self.compute_dtype
        if x.dtype == jnp.uint8 and self.scale_uint8:
            x = x.astype(dt) / jnp.asarray(255.0, dt)
        else:
            x = x.astype(dt)

        if self.obs_is_image:
            f1, f2, f3 = self.trunk_features
            for feats, kernel, stride in (
                    (f1, (8, 8), (4, 4)),
                    (f2, (4, 4), (2, 2)),
                    (f3, (3, 3), (1, 1))):
                x = nn.Conv(feats, kernel, strides=stride, padding="VALID",
                            dtype=dt, kernel_init=orthogonal_init(),
                            bias_init=nn.initializers.zeros)(x)
                x = nn.relu(x)
            x = x.reshape((x.shape[0], -1))
        else:
            x = nn.Dense(128, dtype=dt, kernel_init=orthogonal_init(),
                         bias_init=nn.initializers.zeros)(x)
            x = nn.relu(x)

        def head(out_dim: int, name: str) -> jax.Array:
            h = nn.Dense(self.head_width, dtype=dt,
                         kernel_init=orthogonal_init(),
                         bias_init=nn.initializers.zeros,
                         name=f"{name}_hidden")(x)
            h = nn.relu(h)
            return nn.Dense(out_dim, dtype=dt,
                            kernel_init=orthogonal_init(),
                            bias_init=nn.initializers.zeros,
                            name=f"{name}_out")(h)

        advantage = head(self.num_actions, "advantage").astype(jnp.float32)
        value = head(1, "value").astype(jnp.float32)
        return value + advantage - advantage.mean(axis=1, keepdims=True)


def make_policy_fn(model: DuelingDQN):
    """Jittable epsilon-greedy policy (reference ``DuelingDQN.act``,
    ``model.py:74-86``): returns ``(actions, q_values)`` so actors can compute
    initial TD priorities without re-running the network (``memory.py:396``).

    Vectorized over a batch of states — one call serves a whole vectorized
    env fleet, unlike the reference's single-state ``act``.
    """

    def policy(params, obs: jax.Array, epsilon: jax.Array, key: jax.Array):
        q_values = model.apply(params, obs)
        explore_key, action_key = jax.random.split(key)
        greedy = q_values.argmax(axis=1)
        random_actions = jax.random.randint(
            action_key, greedy.shape, 0, model.num_actions)
        explore = jax.random.uniform(explore_key, greedy.shape) < epsilon
        return jnp.where(explore, random_actions, greedy), q_values

    return policy
