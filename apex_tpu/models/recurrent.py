"""Recurrent dueling DQN (R2D2-style) in flax.linen.

The reference lists "recurrent DQN" as an unimplemented TODO
(``README.md:5``); this module implements it TPU-first, following the
R2D2 recipe (Kapturowski et al. 2019: recurrent replay, stored recurrent
state, burn-in) on top of the same Nature trunk / dueling-head geometry as
:class:`apex_tpu.models.dueling.DuelingDQN` (``model.py:14-107``).

Design notes:

* The LSTM unroll is a ``flax.linen.scan`` over the time axis — one
  compiled ``lax.scan``, weights broadcast, no Python loop.  Trunk and
  heads run batched over ``B*L`` frames around the scan, so the convs
  stay one big MXU-friendly batch; only the cell itself is sequential.
* One ``__call__`` serves sequences AND single steps (actors pass
  ``L=1``), so there is exactly one parameter structure and no
  train/act weight-translation.
* The carry is explicit state threaded by the caller — actors store it
  per environment and ship the value at sequence start to the replay
  (the R2D2 "stored state" strategy), rather than hiding it in module
  state.
* With a recurrent core the frame-stack becomes redundant (the LSTM IS
  the memory); the family defaults to single frames, which also
  quarters the observation bytes per step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.models.dueling import orthogonal_init


class RecurrentDuelingDQN(nn.Module):
    """Dueling Q-network with an LSTM between the trunk and the heads.

    ``__call__(x_seq, carry)`` takes ``x_seq [B, L, *obs]`` and carry
    ``(c, h)`` each ``[B, lstm_features]``; returns ``(q_seq [B, L, A],
    new_carry)``.
    """

    num_actions: int
    obs_is_image: bool = True
    compute_dtype: jnp.dtype = jnp.bfloat16
    scale_uint8: bool = True
    trunk_features: Sequence[int] = (32, 64, 64)
    lstm_features: int = 128
    head_width: int = 128

    def initial_state(self, batch_size: int):
        """Zero carry ``(c, h)`` — parameter-free, callable pre-init.
        f32: the carry crosses step boundaries and accumulates."""
        z = jnp.zeros((batch_size, self.lstm_features), jnp.float32)
        return (z, z)

    @nn.compact
    def __call__(self, x_seq: jax.Array, carry):
        dt = self.compute_dtype
        b, length = x_seq.shape[0], x_seq.shape[1]
        x = x_seq.reshape((b * length,) + x_seq.shape[2:])
        if x.dtype == jnp.uint8 and self.scale_uint8:
            x = x.astype(dt) / jnp.asarray(255.0, dt)
        else:
            x = x.astype(dt)

        if self.obs_is_image:
            f1, f2, f3 = self.trunk_features
            for feats, kernel, stride in (
                    (f1, (8, 8), (4, 4)),
                    (f2, (4, 4), (2, 2)),
                    (f3, (3, 3), (1, 1))):
                x = nn.Conv(feats, kernel, strides=stride, padding="VALID",
                            dtype=dt, kernel_init=orthogonal_init(),
                            bias_init=nn.initializers.zeros)(x)
                x = nn.relu(x)
            x = x.reshape((b * length, -1))
        else:
            x = nn.Dense(128, dtype=dt, kernel_init=orthogonal_init(),
                         bias_init=nn.initializers.zeros)(x)
            x = nn.relu(x)

        feats = x.reshape(b, length, -1).astype(jnp.float32)
        # time-axis scan of one LSTM cell: params broadcast across steps.
        # Carry math stays f32 (bf16 carries drift over long unrolls).
        scan_cell = nn.scan(nn.OptimizedLSTMCell,
                            variable_broadcast="params",
                            split_rngs={"params": False},
                            in_axes=1, out_axes=1)
        carry, h_seq = scan_cell(self.lstm_features, name="lstm")(
            carry, feats)

        h = h_seq.reshape(b * length, -1).astype(dt)

        def head(out_dim: int, name: str) -> jax.Array:
            y = nn.Dense(self.head_width, dtype=dt,
                         kernel_init=orthogonal_init(),
                         bias_init=nn.initializers.zeros,
                         name=f"{name}_hidden")(h)
            y = nn.relu(y)
            return nn.Dense(out_dim, dtype=dt,
                            kernel_init=orthogonal_init(),
                            bias_init=nn.initializers.zeros,
                            name=f"{name}_out")(y)

        advantage = head(self.num_actions, "advantage").astype(jnp.float32)
        value = head(1, "value").astype(jnp.float32)
        q = value + advantage - advantage.mean(axis=1, keepdims=True)
        return q.reshape(b, length, self.num_actions), carry


def make_recurrent_policy_fn(model: RecurrentDuelingDQN):
    """Jittable stateful epsilon-greedy step: ``(params, obs [B, *obs],
    carry, epsilon, key) -> (actions [B], q [B, A], new_carry)``.  The
    caller owns the carry (one per env slot) and must reset it to
    ``model.initial_state`` on episode boundaries."""

    def policy(params, obs, carry, epsilon, key):
        q_seq, carry = model.apply(params, obs[:, None], carry)
        q = q_seq[:, 0]
        explore_key, action_key = jax.random.split(key)
        greedy = q.argmax(axis=1)
        random_actions = jax.random.randint(
            action_key, greedy.shape, 0, model.num_actions)
        explore = jax.random.uniform(explore_key, greedy.shape) < epsilon
        return jnp.where(explore, random_actions, greedy), q, carry

    return policy
