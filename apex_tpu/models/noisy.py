"""Factorized NoisyNet linear layer (Fortunato et al. 2017).

Parity with the reference ``NoisyLinear`` (``model.py:112-164``): mu + sigma
parameters with mu ~ U(-1/sqrt(fan_in), 1/sqrt(fan_in)) and sigma =
std_init/sqrt(fan_in); factorized noise eps_out (x) eps_in with
``sign(x)*sqrt(|x|)`` scaling; deterministic (mu-only) eval mode.

TPU-first delta: the reference keeps noise in mutable buffers refreshed by an
explicit ``reset_noise()`` side effect (``model.py:154-159``).  Here noise is
drawn functionally from a ``'noise'`` PRNG collection each application —
``apply(..., rngs={'noise': key})`` IS the noise reset, which jits cleanly and
makes per-step noise refresh (``AQL_dis.py:104-105``) the default behavior.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def _scale_noise(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class NoisyDense(nn.Module):
    features: int
    std_init: float = 0.4
    deterministic: bool = False
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        mu_range = 1.0 / jnp.sqrt(in_features)

        def mu_init(key, shape, dtype=jnp.float32):
            return jax.random.uniform(key, shape, dtype, -mu_range, mu_range)

        def sigma_init(key, shape, dtype=jnp.float32):
            del key
            return jnp.full(shape, self.std_init / jnp.sqrt(in_features), dtype)

        w_mu = self.param("w_mu", mu_init, (in_features, self.features))
        w_sigma = self.param("w_sigma", sigma_init, (in_features, self.features))
        b_mu = self.param("b_mu", mu_init, (self.features,))
        b_sigma = self.param("b_sigma", sigma_init, (self.features,))

        if self.deterministic:
            w, b = w_mu, b_mu
        else:
            key = self.make_rng("noise")
            k_in, k_out = jax.random.split(key)
            eps_in = _scale_noise(jax.random.normal(k_in, (in_features,)))
            eps_out = _scale_noise(jax.random.normal(k_out, (self.features,)))
            w = w_mu + w_sigma * jnp.outer(eps_in, eps_out)
            b = b_mu + b_sigma * eps_out

        dt = self.compute_dtype
        return (x.astype(dt) @ w.astype(dt) + b.astype(dt)).astype(jnp.float32)
