"""Centralized batched inference plane — a device-attached policy server
for the actor fleet.

Today every actor host runs its own policy copy on host CPU and pays B
tiny forward passes per vector step plus the full serialize→publish→
deserialize param cycle per refresh.  "Human-Level Control without
Server-Grade Hardware" (arxiv 2111.01264) shows the economics of batching
actor inference centrally; Stooke & Abbeel (arxiv 1803.02811 — the basis
of the actor plane's double buffering) covers the overlap scheduling that
hides the round-trip.  This package is that server for the apex-tpu
fleet:

* :mod:`~apex_tpu.infer_service.service` — the ``--role infer`` process:
  one ROUTER that coalesces policy requests ACROSS actor processes into
  scan-stacked device dispatches, with params kept fresh off the
  existing learner param channel (optionally device-resident).
* :mod:`~apex_tpu.infer_service.client` — the actor-side half:
  ``ActorConfig.remote_policy`` makes each half-group's
  ``_policy_group`` dispatch a wire request instead of a local jit call
  (riding the double-buffer split, so one group's round-trip overlaps
  the other group's env stepping), with local-policy fallback after
  ``comms.infer_wait_s`` and the dead-shard re-probe discipline so a
  wedged/dead server never stalls the fleet.

Bit-parity is the correctness anchor: for identical params and key
chains, remote-served actions/chunks/priorities are bit-identical to the
local-policy path (tests/test_infer.py pins it across even/odd B and
both half-groups), so the remote/local A/B measures pure plumbing cost
vs batching win.
"""

from apex_tpu.infer_service.client import InferClient
from apex_tpu.infer_service.service import (InferServer, quantize_pow2,
                                            run_infer_server)

__all__ = ["InferClient", "InferServer", "quantize_pow2",
           "run_infer_server"]
