"""The infer-server role: request coalescing, the scan-stacked dispatch,
params off the learner channel, heartbeats, chaos, lifecycle.

One ROUTER at ``comms.infer_port`` multiplexes every remote-policy
actor's requests:

* ``("infer", msg)`` from actors — ``msg`` carries one half-group's
  stacked observations, its epsilon ladder slice, the RAW per-step key
  (as uint32 key data), and the group id.  The server replies
  ``("act", {...})`` with that group's actions and acting-time Q-values,
  stamped with the param version and learner epoch they were computed
  under.  A request decoded while the server has no params yet gets
  ``("dry", {"rid": ...})`` so the client falls back immediately instead
  of waiting out ``infer_wait_s``.

Adaptive batching: the first decoded request opens a window; the server
keeps draining the socket until ``infer_batch_max`` requests are queued
or ``infer_window_ms`` elapsed, then groups same-shaped requests and
runs each group as ONE ``lax.scan`` over the stacked requests — the
scan-of-identical-bodies batching PR 2 pinned bit-identical for the
learner's fused steps, applied to acting.  The scan length pads to
pow2-quantized widths (repeating the last request; padded outputs are
discarded) so the compile count stays bounded no matter how request
counts fluctuate.

Bit-parity: each scan step computes exactly
``policy_fn(params, obs, eps, fold_in(key, group))`` — the same program
the actor's local ``_grouped_policy`` runs — so remote actions/Q are
bit-identical to local acting for the same params and key chain
(tests/test_infer.py pins it; it is what makes the local fallback a pure
scheduling event).

Params ride the EXISTING param channel: the server subscribes like any
actor (SUB + CONFLATE, latest-wins) — no new publish cycle — and with
``comms.infer_device_params`` keeps them device-placed on arrival (the
device-to-device path on a shared-device deployment; skipped on the CPU
backend like the ingest pipeline's staging ring).  Replies carry the
subscriber's ``learner_epoch`` so clients can discard a dead life's
stragglers (PR 8 fencing).

Membership: ordinary :class:`~apex_tpu.fleet.heartbeat.Heartbeat`\\ s
(role ``"infer"``) ship to the learner's chunk port, so the
:class:`~apex_tpu.fleet.registry.FleetRegistry`, ``--role status``, the
chaos drills, and the supervisor all work on this role for free; the
beats carry the serving gauges (queue depth, batch-size p50/p90,
coalesce latency) the status table and Prometheus exposition surface.

Chaos: ``CHAOS_SEED``/``CHAOS_SPEC`` gate a per-identity plan under
``infer-<server_id>`` — ``kill`` fires on the request index
(``os._exit(137)``), ``drop_frac`` drops requests unanswered (the client
times out and falls back — exactly what a dying server produces), and
``mute`` swallows outgoing replies while ingress stays up.
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import ApexConfig, CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.obs.spans import LatencyHistogram
from apex_tpu.runtime import wire
from apex_tpu.serving import fence
from apex_tpu.tenancy import namespace as tenancy_ns


def quantize_pow2(n: int, cap: int) -> int:
    """Scan length for ``n`` queued requests: the next power of two, capped
    (same discipline as the ingest pipeline's scan-shortfall widths — a
    bounded set of compiled lengths, never one per request count)."""
    n = max(1, min(int(n), int(cap)))
    p = 1
    while p < n:
        p *= 2
    return min(p, int(cap))


def make_batched_policy(policy_fn):
    """Jit ``policy_fn`` as a scan over stacked requests.  Each scan step
    re-wraps its request's raw key data and folds in its group id INSIDE
    the compiled program — element for element the actor-local
    ``_grouped_policy`` computation, so remote results are bit-identical
    to local acting (the scan-of-identical-bodies contract from the
    learner's scan_fused_steps)."""
    import jax

    # nb: the name must not collide with any host-side method in this
    # module — apexlint's jit-scope detection is name-based by design
    def _scan_requests(params, obs, eps, key_data, groups):
        def body(carry, xs):
            o, e, kd, g = xs
            key = jax.random.fold_in(jax.random.wrap_key_data(kd), g)
            return carry, policy_fn(params, o, e, key)

        _, (actions, q) = jax.lax.scan(body, 0, (obs, eps, key_data,
                                                 groups))
        return actions, q

    return jax.jit(_scan_requests)


class _RequestChaos:
    """The infer-server fault gate: one RNG draw per decoded request off
    the seeded per-identity stream (:mod:`apex_tpu.fleet.chaos`), so the
    server's kills and drops replay exactly, run after run."""

    def __init__(self, plan):
        self.plan = plan
        self._rng = plan.rng() if plan is not None else None
        self._n = 0
        self.dropped = 0

    def on_request(self) -> str:
        """"ok" | "drop"; a scheduled kill never returns."""
        if self.plan is None:
            return "ok"
        i = self._n
        self._n += 1
        if self.plan.kill_at is not None and i >= self.plan.kill_at:
            from apex_tpu.fleet.chaos import _die
            _die(self.plan.identity, i)
        if self._rng.random() < self.plan.drop_frac:
            self.dropped += 1
            return "drop"
        return "ok"


class InferServer:
    """Socket loop around one jitted policy (module docstring).
    Single-threaded on purpose: one thread owns the ROUTER, the param
    subscriber, and the dispatch order — the same thread-affinity
    contract the replay shards keep (and apexlint J013 now enforces)."""

    def __init__(self, comms: CommsConfig, policy_fn, server_id: int = 0,
                 bind_ip: str = "*", heartbeat: bool = True, sub=None,
                 port: int | None = None):
        import zmq

        from apex_tpu.fleet.chaos import chaos_from_env

        self._zmq = zmq
        self.comms = comms
        self.server_id = int(server_id)
        self.identity = f"infer-{server_id}"
        self.batched = make_batched_policy(policy_fn)
        self.port = int(port) if port is not None else comms.infer_port
        self.sock = zmq.Context.instance().socket(zmq.ROUTER)
        self.sock.bind(f"tcp://{bind_ip}:{self.port}")
        # params: latest-wins off the learner channel (``sub``), or
        # injected via set_params (tests/bench drive the server without a
        # learner).  Device placement is flag-gated and CPU-exempt.
        self.sub = sub
        self.params = None
        self.param_version = 0
        self.learner_epoch = 0
        self._place = bool(comms.infer_device_params)
        # serving-tier version gate (apex_tpu/serving/deploy drives it
        # over the ctl channel): while ``_pin`` holds a model fence,
        # installs BEYOND it are held (counted) and the shard keeps
        # serving what it has; ``_incumbent`` retains the pre-canary
        # params so a rollback restores them bit-identically.
        self._pin: tuple | None = None
        self._incumbent: tuple | None = None    # (version, params, epoch)
        self.held = 0                   # installs refused by the pin
        self.gate_rollbacks = 0         # incumbent restores taken
        self.ctl_cmds = 0
        # tenant entries (PR 13): each non-default tenant served here
        # gets its OWN params/version/epoch/compiled-policy/subscriber —
        # requests coalesce per (tenant, shape), so one tenant's batch
        # never runs under another's params.  The default tenant stays
        # on the attributes above, bit-identical to the single-tenant
        # server; the serve-ctl version gate also governs only the
        # default tenant (per-tenant canaries are a ROADMAP follow-up).
        self.tenants: dict[str, dict] = {}
        self.unknown_tenant = 0
        # serving counters / gauges (heartbeats + stats())
        self.requests = 0
        self.replies = 0
        self.dry_replies = 0            # requests answered before params
        self.rejected = 0               # payloads outside the allowlist
        self.dispatches = 0
        self.batch_hist = LatencyHistogram()      # requests per dispatch
        self.coalesce_hist = LatencyHistogram()   # recv -> dispatch, s
        self._queue_depth = 0
        chaos = chaos_from_env()
        plan = chaos.plan_for(self.identity) if chaos is not None else None
        self.chaos = _RequestChaos(plan)
        self._mute = bool(plan is not None and plan.mute_replies)
        self.chaos_muted = 0
        self._hb = None
        self._hb_sender = None
        if heartbeat:
            from apex_tpu.fleet.heartbeat import HeartbeatEmitter
            from apex_tpu.runtime.transport import ChunkSender
            self._hb_sender = ChunkSender(comms, self.identity)
            self._hb = HeartbeatEmitter(
                self.identity, role="infer",
                interval_s=comms.heartbeat_interval_s,
                counters_fn=lambda: {"chunks_sent": self.replies,
                                     "acks_received": self.requests},
                gauges_fn=self.gauges)

    # -- params --------------------------------------------------------------

    def set_params(self, version: int, params, epoch: int = 0) -> None:
        """Install params directly (tests, bench, co-located trainers);
        the serving path is identical to subscriber-fed params.  The
        epoch-fenced gate applies HERE — pinned shards hold (count)
        installs beyond the fence, so subscriber and direct installs
        obey one deployment discipline."""
        eff_epoch = int(epoch) if epoch else self.learner_epoch
        if self._pin is not None and fence.beyond(eff_epoch, version,
                                                  self._pin):
            self.held += 1
            return
        self.params = self._placed(params)
        self.param_version = int(version)
        if epoch:
            self.learner_epoch = int(epoch)

    # -- the serving-tier ctl channel (apex_tpu/serving/deploy) -------------

    def apply_ctl(self, body: dict) -> dict:
        """One deployment-controller command, applied on the socket
        thread (the gate and the dispatch order can never race).  All
        commands are idempotent — the controller RECONCILES every tick,
        so a respawned shard re-converges without special casing.

        * ``freeze``: stash current params (once) and pin at the
          shard's OWN current fence — the steady-state verb: the tier
          serves frozen, judged models, never the raw stream.
        * ``pin``: hold installs beyond an explicit (epoch, version)
          fence.
        * ``canary``: stash current params as the incumbent (once) and
          track the live stream.
        * ``rollback``: restore the stashed incumbent bit-identically
          and pin at ITS fence; a shard with no stash serving beyond
          the given fence (a respawn that picked up the candidate)
          drops to dry replies — clients fall back to local acting,
          never act on the rejected model.
        * ``promote``: clear pin + stash — the gate opens so the tier
          takes the newly judged version off the stream (the
          controller re-freezes next tick).
        * ``status`` (or anything else): report state only.
        """
        cmd = body.get("cmd")
        self.ctl_cmds += 1
        f = None
        if "epoch" in body or "version" in body:
            f = fence.fence_key(body.get("epoch"), body.get("version"))
        if cmd == "freeze":
            if self.params is not None and self._incumbent is None:
                self._incumbent = (self.param_version, self.params,
                                   self.learner_epoch)
            self._pin = fence.fence_key(self.learner_epoch,
                                        self.param_version)
        elif cmd == "pin" and f is not None:
            self._pin = f
        elif cmd == "canary":
            if self._incumbent is None and self.params is not None:
                self._incumbent = (self.param_version, self.params,
                                   self.learner_epoch)
            self._pin = None
        elif cmd == "rollback":
            if self._incumbent is not None:
                v, p, e = self._incumbent
                if fence.beyond(self.learner_epoch, self.param_version,
                                (e, v)):
                    self.gate_rollbacks += 1    # the restore changed
                self.params, self.param_version = p, int(v)  # something
                self.learner_epoch = int(e)
                self._incumbent = None
                self._pin = fence.fence_key(e, v)
            elif self._pin is not None and fence.at_or_before(
                    self.learner_epoch, self.param_version, self._pin):
                pass        # already rolled back / frozen pre-candidate
            elif f is not None and self.params is not None \
                    and fence.beyond(self.learner_epoch,
                                     self.param_version, f):
                # a respawned shard serving the candidate with no stash:
                # serving it would violate the rollback — serve dry
                # (clients act locally, bit-identically) until the next
                # promotion opens the gate
                self.params = None
                self._pin = f
            elif f is not None and self._pin is None:
                self._pin = f
        elif cmd == "promote":
            self._pin = None
            self._incumbent = None
        return self.ctl_state(rid=body.get("rid"))

    def ctl_state(self, rid=None) -> dict:
        """Gate state for ctl replies and stats(): plain builtins."""
        out = {"shard": self.server_id,
               "epoch": self.learner_epoch,
               "version": self.param_version,
               "pinned": self._pin is not None,
               "pin": list(self._pin) if self._pin is not None else None,
               "held": self.held,
               "rollbacks": self.gate_rollbacks,
               "has_incumbent": self._incumbent is not None,
               "has_params": self.params is not None}
        if rid is not None:
            out["rid"] = rid
        return out

    def _placed(self, params):
        if not self._place:
            return params
        import jax
        if jax.default_backend() == "cpu":
            return params           # host arrays ARE the device arrays
        return jax.device_put(params)

    # -- tenants (PR 13) -----------------------------------------------------

    def add_tenant(self, tenant: str, policy_fn, sub=None) -> None:
        """Serve one more tenant from this shard: its own compiled
        policy (its env's model — obs geometry and action count differ
        per tenant) and, optionally, a subscriber on ITS learner's
        param channel.  Direct installs come via
        :meth:`set_tenant_params` (tests, co-located trainers)."""
        if tenancy_ns.is_default(tenant):
            return                  # the default tenant IS the server
        self.tenants[tenant] = {
            "batched": make_batched_policy(policy_fn),
            "sub": sub, "params": None, "version": 0, "epoch": 0}

    def set_tenant_params(self, tenant: str, version: int, params,
                          epoch: int = 0) -> None:
        entry = self.tenants[tenant]
        entry["params"] = self._placed(params)
        entry["version"] = int(version)
        if epoch:
            entry["epoch"] = int(epoch)

    def _poll_params(self) -> None:
        if self.sub is not None:
            got = self.sub.poll(0)
            if got is not None:
                version, params = got
                self.set_params(version, params,
                                epoch=getattr(self.sub, "learner_epoch",
                                              0))
        for tenant, entry in self.tenants.items():
            sub = entry["sub"]
            if sub is None:
                continue
            got = sub.poll(0)
            if got is not None:
                version, params = got
                self.set_tenant_params(
                    tenant, version, params,
                    epoch=getattr(sub, "learner_epoch", 0))

    # -- serving -------------------------------------------------------------

    def step(self, timeout_ms: int = 100) -> int:
        """One poll/coalesce/dispatch round; returns requests served."""
        self._poll_params()
        if self._hb is not None:
            hb = self._hb.maybe_beat(self.param_version)
            if hb is not None:
                self._hb_sender.send_stat(hb)
        if not self.sock.poll(timeout_ms, self._zmq.POLLIN):
            return 0
        pending = self._coalesce()
        if not pending:
            return 0
        served = 0
        for group in self._group_by_shape(pending):
            served += self._dispatch(group)
        return served

    def _coalesce(self) -> list:
        """Drain decoded requests until ``infer_batch_max`` are queued or
        ``infer_window_ms`` elapsed since the first — the adaptive batch
        window.  Returns ``[(ident, msg, recv_monotonic), ...]``."""
        deadline = None
        out: list = []
        while len(out) < self.comms.infer_batch_max:
            wait_ms = 0
            if deadline is not None:
                wait_ms = max(0, int((deadline - time.monotonic()) * 1000))
            if not self.sock.poll(wait_ms, self._zmq.POLLIN):
                break
            ident, payload = self.sock.recv_multipart()
            try:
                got = wire.restricted_loads(payload)
            except wire.WireRejected:
                self.rejected += 1      # counted, dropped, NO reply: a
                continue                # hostile payload costs its sender
            #                             one fallback wait, nobody else's
            if not (isinstance(got, tuple) and len(got) == 2
                    and isinstance(got[1], dict)):
                self.rejected += 1      # well-pickled garbage included
                continue
            if got[0] == "ctl":
                # deployment-controller command (apex_tpu/serving):
                # applied here on the one socket thread, outside the
                # batch window and the chaos request stream
                self._reply(ident, ("ctl_ok", self.apply_ctl(got[1])))
                continue
            if got[0] != "infer":
                self.rejected += 1
                continue
            if self.chaos.on_request() == "drop":
                continue                # unanswered: the client falls back
            msg = got[1]
            self.requests += 1
            obs_spans.stamp(msg, "infer_batch")
            out.append((ident, msg, time.monotonic()))
            if deadline is None:
                deadline = (time.monotonic()
                            + self.comms.infer_window_ms / 1000.0)
        self._queue_depth = len(out)
        return out

    @staticmethod
    def _group_by_shape(pending: list) -> list[list]:
        """Same-tenant, same-shaped requests share one scan dispatch (a
        scan needs one stacked geometry AND one params pytree: the
        tenant key is what guarantees one tenant's batch never runs
        under another's params).  A like-configured single-tenant fleet
        produces at most the two half-group widths, exactly as
        before."""
        by_key: dict[tuple, list] = {}
        for item in pending:
            tenant = str(item[1].get("tenant")
                         or tenancy_ns.DEFAULT_TENANT)
            by_key.setdefault((tenant, item[1]["obs"].shape),
                              []).append(item)
        return list(by_key.values())

    def _dry_group(self, group: list) -> int:
        """No params for this group's tenant yet: tell its clients to
        act locally NOW rather than letting them wait out
        infer_wait_s."""
        for ident, msg, _ in group:
            self.dry_replies += 1
            self._reply(ident, ("dry", {"rid": msg["rid"]}))
        return len(group)

    def _dispatch(self, group: list) -> int:
        """One scan-stacked device dispatch over ``group`` (same tenant
        + obs shape), padded to a pow2-quantized length by repeating the
        last request — each scan step depends only on its own inputs, so
        the padding changes compile count, never results."""
        tenant = str(group[0][1].get("tenant")
                     or tenancy_ns.DEFAULT_TENANT)
        if tenancy_ns.is_default(tenant):
            params, batched = self.params, self.batched
            pv, epoch = self.param_version, self.learner_epoch
        else:
            entry = self.tenants.get(tenant)
            if entry is None:
                self.unknown_tenant += 1    # unadmitted tenant: its
                return self._dry_group(group)   # clients act locally
            params, batched = entry["params"], entry["batched"]
            pv, epoch = entry["version"], entry["epoch"]
        if params is None:
            return self._dry_group(group)
        n = len(group)
        width = quantize_pow2(n, self.comms.infer_batch_max)
        idx = list(range(n)) + [n - 1] * (width - n)
        obs = np.stack([group[i][1]["obs"] for i in idx])
        eps = np.stack([np.asarray(group[i][1]["eps"], np.float32)
                        for i in idx])
        keys = np.stack([np.asarray(group[i][1]["key"]) for i in idx])
        groups = np.asarray([int(group[i][1]["group"]) for i in idx],
                            np.int32)
        actions, q = batched(params, obs, eps, keys, groups)
        actions, q = np.asarray(actions), np.asarray(q)
        self.dispatches += 1
        self.batch_hist.record(float(n))
        now = time.monotonic()
        for r, (ident, msg, t_recv) in enumerate(group):
            self.coalesce_hist.record(max(0.0, now - t_recv))
            reply = {"rid": msg["rid"], "actions": actions[r], "q": q[r],
                     "pv": pv, "epoch": epoch}
            spans = msg.get(obs_spans.SPAN_KEY)
            if spans:
                obs_spans.stamp_spans(spans, "infer_reply")
                reply[obs_spans.SPAN_KEY] = spans
            self.replies += 1
            self._reply(ident, ("act", reply))
        return n

    def _reply(self, ident: bytes, msg) -> None:
        if self._mute:
            self.chaos_muted += 1       # the reply dies on the down link
            return
        try:
            self.sock.send_multipart([ident, wire.dumps(msg)],
                                     self._zmq.DONTWAIT)
        except self._zmq.Again:
            pass        # a gone client's reply is droppable by contract

    # -- lifecycle / observability -------------------------------------------

    def run(self, stop_event=None, max_seconds: float | None = None) -> dict:
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        while True:
            if stop_event is not None and stop_event.is_set():
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            self.step()
        return self.stats()

    def gauges(self) -> dict:
        """The serving gauges heartbeats carry to the registry (status
        table + Prometheus exposition)."""
        import jax
        b, c = self.batch_hist.snapshot(), self.coalesce_hist.snapshot()
        # serve_* rows: the registry's per-shard pinned-version view —
        # the deployment controller's reconcile target is auditable from
        # `--role status` without a ctl round-trip
        return {"tenants": 1 + len(self.tenants),
                "backend_accel": float(jax.default_backend() != "cpu"),
                "queue_depth": self._queue_depth,
                "batch_p50": b["p50_s"], "batch_p90": b["p90_s"],
                "coalesce_ms_p50": round(c["p50_s"] * 1000.0, 3),
                "requests": self.requests, "replies": self.replies,
                "dry_replies": self.dry_replies,
                "rejected": self.rejected,
                "serve_epoch": self.learner_epoch,
                "serve_version": self.param_version,
                "serve_pinned": int(self._pin is not None),
                "serve_held": self.held,
                "serve_rollbacks": self.gate_rollbacks}

    def stats(self) -> dict:
        return {"server": self.server_id,
                "param_version": self.param_version,
                "learner_epoch": self.learner_epoch,
                "dispatches": self.dispatches,
                "chaos_dropped": self.chaos.dropped,
                "chaos_muted": self.chaos_muted,
                "ctl_cmds": self.ctl_cmds,
                **self.gauges()}

    def close(self) -> None:
        self.sock.close(linger=0)
        if self._hb_sender is not None:
            self._hb_sender.close(drain_s=0.0)
        if self.sub is not None:
            self.sub.close()
        for entry in self.tenants.values():
            if entry["sub"] is not None:
                entry["sub"].close()


def dqn_policy_fn(cfg: ApexConfig):
    """The policy program the server serves — the SAME builder the actor
    families jit locally (one function, two call sites: that identity is
    the whole bit-parity argument)."""
    from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
    from apex_tpu.training.apex import dqn_model_spec
    return make_policy_fn(DuelingDQN(**dqn_model_spec(cfg)))


def run_infer_server(cfg: ApexConfig, family: str = "dqn",
                     server_id: int = 0, stop_event=None,
                     max_seconds: float | None = None,
                     bind_ip: str = "*") -> dict:
    """The ``--role infer`` entry point: build the jitted policy from the
    fleet config, subscribe the param channel, serve until stopped.
    Returns the final stats dict.  Skips the startup barrier like the
    replay shards — the server is useful the moment its ROUTER binds
    (actors fall back locally until it answers)."""
    from apex_tpu.obs.trace import get_ring, set_process_label
    from apex_tpu.runtime import transport
    from apex_tpu.serving.shard import shard_port

    if family != "dqn":
        raise NotImplementedError(
            f"the inference plane currently serves the dqn family only "
            f"(got {family!r}); aql/r2d2 actors stay on local policies — "
            f"see ROADMAP.md")
    n_shards = max(1, getattr(cfg.comms, "infer_shards", 1))
    if not 0 <= server_id < n_shards:
        raise ValueError(
            f"infer shard id {server_id} outside [0, {n_shards}) — set "
            f"--infer-shards/APEX_INFER_SHARDS fleet-wide")
    set_process_label(f"infer-{server_id}")
    get_ring()                      # arm the trace ring's dump triggers
    # explicit empty topic: the infer shard is SHARED-plane — its base
    # subscriber always serves the default tenant's channel, even if an
    # operator leaks APEX_TENANT into the server's environment
    sub = transport.ParamSubscriber(cfg.comms, topic=b"")
    server = InferServer(cfg.comms, dqn_policy_fn(cfg),
                         server_id=server_id, bind_ip=bind_ip, sub=sub,
                         port=shard_port(cfg.comms, server_id))
    # tenant entries (PR 13): one compiled policy + one param SUB per
    # roster tenant — the SUB connects that tenant's OWN learner
    # endpoint and subscribes its topic tag, so requests coalesced per
    # (tenant, group) always dispatch under the right tenant's params
    import dataclasses
    roster = tenancy_ns.load_roster()
    for tenant, spec in sorted(roster.items()):
        if spec.family != "dqn":
            print(f"infer-{server_id}: tenant {tenant!r} skipped "
                  f"(family {spec.family!r} unserved — ROADMAP.md)",
                  flush=True)
            continue
        tcfg = cfg.replace(env=dataclasses.replace(cfg.env,
                                                   env_id=spec.env_id))
        tsub = transport.ParamSubscriber(
            tenancy_ns.tenant_comms(cfg.comms, spec),
            topic=tenancy_ns.param_topic(tenant))
        server.add_tenant(tenant, dqn_policy_fn(tcfg), sub=tsub)
    print(f"infer-{server_id}: serving on port {server.port} "
          f"(shard {server_id}/{n_shards}, "
          f"tenants=1+{len(server.tenants)}, "
          f"batch_max={cfg.comms.infer_batch_max}, "
          f"window_ms={cfg.comms.infer_window_ms}, "
          f"device_params={cfg.comms.infer_device_params})", flush=True)
    try:
        return server.run(stop_event=stop_event, max_seconds=max_seconds)
    finally:
        server.close()
