"""Actor-side half of the inference plane.

One DEALER per worker, driven by EXACTLY the worker thread (submit and
collect both happen inside the vector step — the zmq single-thread
contract apexlint J013 enforces).  ``submit`` ships one half-group's
policy inputs and returns a :class:`PendingInfer` WITHOUT blocking, so
the double-buffered interleave dispatches both groups' requests before
materializing either — one group's round-trip overlaps the other group's
env stepping, and the two requests land in the same server batch window.

Fallback contract (the replay service's learner-direct fallback, applied
to inference): ``collect`` waits at most ``comms.infer_wait_s`` for the
reply, then computes the SAME program locally — bit-identical by the
parity pin, so a fallback is a scheduling event, never a trajectory
fork.  A timeout marks the server DOWN: subsequent submits skip the wire
entirely (local acting at full speed) and one real request re-probes the
server every ``comms.infer_reprobe_s``, so a supervised respawn gets its
traffic back without an actor restart (the PR 8 dead-shard re-probe
discipline — a stale down-marker must never wedge a recovered server
out).

Epoch fencing (PR 8): every reply carries the learner epoch the server
acted under; a reply stamped with an OLDER epoch than the newest this
client has seen is a dead learner life's straggler — discarded
(counted), never acted on.

Replies are decoded through the restricted wire unpickler: a compromised
or corrupt server costs counted drops and local fallbacks, never
execution.
"""

from __future__ import annotations

import time

import numpy as np

from apex_tpu.config import CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.obs.spans import LatencyHistogram
from apex_tpu.runtime import wire


class PendingInfer:
    """One in-flight half-group request; ``materialize()`` is the single
    blocking point, exactly where the local path's ``np.asarray`` sync
    sits."""

    __slots__ = ("client", "rid", "sent", "fallback", "t0")

    def __init__(self, client: "InferClient", rid: int, sent: bool,
                 fallback, t0: float):
        self.client = client
        self.rid = rid
        self.sent = sent
        self.fallback = fallback
        self.t0 = t0

    def materialize(self) -> tuple:
        return self.client.collect(self)


class InferClient:
    """Submit/collect pairs over one DEALER, with local fallback and the
    down-marker/re-probe machine."""

    def __init__(self, comms: CommsConfig, identity: str,
                 infer_ip: str | None = None, wait_s: float | None = None,
                 reprobe_s: float | None = None, clock=time.monotonic,
                 port: int | None = None):
        import zmq

        from apex_tpu.tenancy import namespace as tenancy_ns

        self._zmq = zmq
        self.comms = comms
        self.identity = identity
        # this worker's tenant (PR 13): stamped on every request so the
        # shared server coalesces per (tenant, group) and dispatches
        # under OUR learner's params; the default tenant stays
        # unstamped — the pre-tenancy request schema, byte for byte
        self.tenant = tenancy_ns.current_tenant()
        self._clock = clock
        # sharded serving tier (apex_tpu/serving/shard): the home-shard
        # index make_infer_client stamps after construction — 0 for the
        # PR 9 single-server topology, surfaced in gauges() so fallback/
        # stale counts attribute to the shard that caused them
        self.shard = 0
        self.sock = zmq.Context.instance().socket(zmq.DEALER)
        self.sock.setsockopt(zmq.IDENTITY, f"{identity}-infer".encode())
        # bounded send queue: requests to a dead server must fail fast
        # into the local fallback, not pile up in a kernel buffer
        self.sock.setsockopt(zmq.SNDHWM, 16)
        ip = infer_ip or comms.infer_ip
        self.sock.connect(f"tcp://{ip}:{port or comms.infer_port}")
        self.wait_s = (comms.infer_wait_s if wait_s is None
                       else float(wait_s))
        self.reprobe_s = (comms.infer_reprobe_s if reprobe_s is None
                          else float(reprobe_s))
        self._rid = 0
        self._replies: dict[int, dict | None] = {}
        self._outstanding: set[int] = set()
        self._down_since: float | None = None
        # counters (heartbeat gauges + bench part-1e)
        self.remote_steps = 0
        self.fallbacks = 0
        self.stale_epoch = 0
        self.rejected = 0
        self.reprobes = 0
        # Round-trip observations, INCLUDING censored ones: a request
        # that times out into the fallback records its elapsed wait (>=
        # wait_s) — the SRE discipline that timeouts count against the
        # latency SLO at the timeout value, or p99 goes blind exactly
        # when the server dies.  The window is deliberately smaller than
        # the default 4096 so the p99 gauge recovers within seconds of a
        # respawned server taking traffic back instead of dragging dead-
        # server samples around for the rest of the run.
        self.round_trip = LatencyHistogram(window=1024)
        self.epoch_seen = 0             # newest learner epoch in a reply
        self.last_version = 0           # newest param version in a reply
        from apex_tpu.obs.trace import get_ring
        self._ring = get_ring()

    # -- submit/collect ------------------------------------------------------

    def _remote_ok(self) -> bool:
        """False while the server is marked down — except one real probe
        per re-probe period (a respawned server has no memory of the
        timeouts that marked it down; the probe is how it gets its
        traffic back)."""
        if self._down_since is None:
            return True
        if self.reprobe_s > 0 and (self._clock() - self._down_since
                                   >= self.reprobe_s):
            self._down_since = self._clock()
            self.reprobes += 1
            return True
        return False

    def submit(self, obs, eps, key, group: int, fallback) -> PendingInfer:
        """Ship one half-group request (non-blocking) and hand back the
        pending handle; ``fallback`` is the zero-argument local policy
        call producing the bit-identical ``(actions, q)``."""
        import jax

        rid = self._rid
        self._rid += 1
        t0 = self._clock()
        sent = False
        if self._remote_ok():
            from apex_tpu.tenancy import namespace as tenancy_ns
            msg = {"rid": rid, "obs": np.asarray(obs),
                   "eps": np.asarray(eps, np.float32),
                   "key": np.asarray(jax.random.key_data(key)),
                   "group": int(group)}
            if not tenancy_ns.is_default(self.tenant):
                msg["tenant"] = self.tenant
            if obs_spans.enabled():
                msg[obs_spans.SPAN_KEY] = [
                    obs_spans.new_span(hop="infer_send")]
            try:
                self.sock.send(wire.dumps(("infer", msg)),
                               self._zmq.DONTWAIT)
                sent = True
                self._outstanding.add(rid)
            except self._zmq.Again:
                pass            # full send queue == down server: fall back
        return PendingInfer(self, rid, sent, fallback, t0)

    def collect(self, pending: PendingInfer) -> tuple:
        """The one blocking point: the reply within ``wait_s``, else the
        local fallback (and the down-marker so later steps skip the
        wait)."""
        rid = pending.rid
        if pending.sent:
            deadline = pending.t0 + self.wait_s
            while True:
                self._drain()
                if rid in self._replies:
                    rep = self._replies.pop(rid)
                    self._outstanding.discard(rid)
                    if rep is not None:
                        self._down_since = None
                        self.remote_steps += 1
                        rt = self._clock() - pending.t0
                        self.round_trip.record(rt)
                        self._ring.complete("infer_rt", pending.t0, rt,
                                            track="infer-client")
                        return (np.asarray(rep["actions"]),
                                np.asarray(rep["q"]))
                    break       # dry reply: the server has no params yet
                remaining = deadline - self._clock()
                if remaining <= 0:
                    self._outstanding.discard(rid)
                    # censored round-trip: the timeout IS the observed
                    # latency (see round_trip above) — the SLO engine's
                    # infer_rt_p99_ms objective breaches on a dead
                    # server through exactly these samples
                    self.round_trip.record(self._clock() - pending.t0)
                    if self._down_since is None:
                        self._down_since = self._clock()
                    break
                self.sock.poll(min(50.0, remaining * 1000.0),
                               self._zmq.POLLIN)
        self.fallbacks += 1
        out = pending.fallback()
        return tuple(np.asarray(x) for x in out)

    def _drain(self) -> None:
        """Decode every queued reply; file by rid.  Stale-epoch replies
        (an older learner life's stragglers) are counted and DISCARDED —
        acting on a dead life's policy output would smuggle pre-restart
        staleness past the fencing every other plane enforces."""
        while self.sock.poll(0, self._zmq.POLLIN):
            try:
                got = wire.restricted_loads(self.sock.recv())
            except wire.WireRejected:
                self.rejected += 1
                continue
            if not (isinstance(got, tuple) and len(got) == 2):
                self.rejected += 1
                continue
            kind, body = got
            if kind == "dry":
                rid = int(body.get("rid", -1))
                if rid in self._outstanding:
                    self._replies[rid] = None
                continue
            if kind != "act" or not isinstance(body, dict):
                self.rejected += 1
                continue
            epoch = int(body.get("epoch", 0))
            if epoch and epoch < self.epoch_seen:
                self.stale_epoch += 1
                continue
            if epoch:
                self.epoch_seen = epoch
            self.last_version = max(self.last_version,
                                    int(body.get("pv", 0)))
            rid = int(body.get("rid", -1))
            if rid not in self._outstanding:
                continue        # a timed-out request's late reply
            spans = body.get(obs_spans.SPAN_KEY)
            if spans:
                obs_spans.stamp_spans(spans, "infer_reply")
            self._replies[rid] = body

    # -- observability -------------------------------------------------------

    def gauges(self) -> dict:
        """Actor-heartbeat gauges: the registry/status/Prometheus view of
        this worker's remote-policy health."""
        rt = self.round_trip.snapshot()
        # infer_shard makes the per-shard story legible fleet-wide: the
        # status table groups each worker's fallback/stale counts under
        # its home shard, so a mis-pinned or dead shard is visible in
        # `--role status` instead of only in local counters
        return {"infer_shard": self.shard,
                "infer_remote": self.remote_steps,
                "infer_fallbacks": self.fallbacks,
                "infer_stale_epoch": self.stale_epoch,
                "infer_epoch_seen": self.epoch_seen,
                "infer_reprobes": self.reprobes,
                "infer_rt_ms_p50": round(rt["p50_s"] * 1000.0, 3),
                "infer_rt_ms_p90": round(rt["p90_s"] * 1000.0, 3),
                "infer_rt_ms_p99": round(rt["p99_s"] * 1000.0, 3)}

    def close(self) -> None:
        self.sock.close(linger=0)
