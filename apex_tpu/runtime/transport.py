"""Socket transport for the multi-host plane (L4/C13-C15 re-design).

The reference's inter-node fabric is ZeroMQ TCP with four patterns:
PUB/SUB + CONFLATE for params (``origin_repo/learner.py:57-68``,
``actor.py:40-49``), DEALER/ROUTER with bounded outstanding-send windows for
transition and priority streams (``actor.py:105-115``,
``learner.py:117-131``), REQ/ROUTER for the startup barrier
(``learner.py:30-54``, ``actor.py:28-37``), and three ``zmq.proxy`` devices
bridging into a standalone replay server (``replay.py:48-74``).

The default TPU topology DISSOLVES the replay server: replay lives in the
learner's HBM (SURVEY.md §7), so the remote-ingest role collapses to one
ROUTER on the learner that feeds the fused ingest+train step directly —
C15's capability (other hosts feeding the learner) with one fewer hop and
no shared-lock bottleneck (``origin_repo/README.md:11``).  With
``comms.replay_shards > 0`` the standalone replay role returns, sharded
(:mod:`apex_tpu.replay_service`), built from the same primitives below:
each shard's ROUTER speaks this module's chunk/ack protocol, and the
:class:`ChunkSender` credit window points at shard ports via the
``ip``/``port`` overrides.  What remains here:

* :class:`ParamPublisher` / :class:`ParamSubscriber` — version-stamped
  latest-wins broadcast (SUB sets ``CONFLATE=1``: exactly the reference's
  staleness bound).
* :class:`ChunkSender` / :class:`ChunkReceiver` — actor->learner transition
  chunks with an explicit ack-based credit window (the reference bounds
  un-acked sends at 3, ``actor.py:110-114``).  Stats ride the same pipe as
  a second message kind.
* :class:`barrier_wait` / :class:`barrier_release` — startup handshake; the
  learner publishes nothing until every expected peer has checked in.

Wire format is pickle over zmq frames, like the reference's cPickle
(``actor.py:1``, ``learner.py:6``) — but every RECEIVE routes through the
allowlisted :mod:`apex_tpu.runtime.wire` unpickler, so the
trusted-cluster assumption both systems share is now defense-in-depth
instead of load-bearing: a payload referencing anything outside the
message/stat/array allowlist is counted and dropped, never executed.
"""

from __future__ import annotations

import pickle
import queue as queue_lib
import threading
import time
from dataclasses import dataclass

import zmq

from apex_tpu.config import CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.runtime import codec as wire_codec
from apex_tpu.runtime import wire


def _ctx() -> zmq.Context:
    return zmq.Context.instance()


# -- param plane -----------------------------------------------------------

class ParamPublisher:
    """Learner-side PUB socket (``learner.py:57-68``): send-and-forget with
    a small HWM; slow subscribers see only the latest version.

    ``epoch`` (learner-epoch fencing, PR 8): when set nonzero, every
    publish carries the learner's monotonically-bumped epoch as a third
    tuple element so parked actors can distinguish a RESTARTED learner
    (epoch changed: the outstanding ack window died with it, reset) from
    a merely STALLED one (same epoch: the acks are still coming).  Zero
    keeps the legacy 2-tuple wire format.

    Tenant topics (PR 13): a non-default-tenant learner prefixes every
    frame with its :func:`apex_tpu.tenancy.namespace.param_topic` tag so
    a shared infer shard's per-tenant SUB sockets attribute each publish
    to the tenant whose learner sent it — and a subscriber pointed at
    the WRONG tenant's endpoint filters everything instead of silently
    serving another tenant's params.  ``topic=None`` derives this
    process's tenant from ``APEX_TENANT`` (the chaos-config env
    discipline); the default tenant's topic is empty, keeping the wire
    byte-identical to the pre-tenancy format."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*",
                 topic: bytes | None = None, delta: bool | None = None,
                 keyframe_every: int | None = None):
        from apex_tpu.tenancy import namespace as tenancy_ns
        self.sock = _ctx().socket(zmq.PUB)
        self.sock.setsockopt(zmq.SNDHWM, comms.param_hwm)
        self.sock.bind(f"tcp://{bind_ip}:{comms.param_port}")
        self.epoch = 0
        self.topic = (tenancy_ns.param_topic(tenancy_ns.current_tenant())
                      if topic is None else topic)
        # sparse-delta mode (runtime/codec.py): deltas carry only the
        # leaves changed since the last keyframe — CONFLATE-safe, any
        # missed intermediate delta is harmless.  Off (dense publishes,
        # legacy wire bit-untouched) unless configured.
        self.delta = (bool(getattr(comms, "param_delta", False))
                      if delta is None else bool(delta))
        self.keyframe_every = max(1, int(
            getattr(comms, "param_keyframe_every", 16)
            if keyframe_every is None else keyframe_every))
        self._key_bytes: dict | None = None   # leaf bytes @ last keyframe
        self._key_seq = -1
        self._seq = 0
        self._last_epoch: int | None = None
        self._want_key = False
        self.param_publishes = 0
        self.param_keyframes = 0
        self.param_deltas = 0
        self.param_bytes_out = 0      # actual PUB frame bytes
        self.param_bytes_raw = 0      # dense leaf bytes (the analogue)
        self.param_delta_bytes = 0    # cumulative delta-frame bytes
        self.keyframes_forced = 0

    def force_keyframe(self) -> None:
        """Make the next publish dense — the trainer calls this when a
        subscriber's :class:`~apex_tpu.runtime.codec.KeyframeRequest`
        arrives on the stat plane."""
        self.keyframes_forced += 1
        self._want_key = True

    def publish(self, version: int, params) -> None:
        self.param_publishes += 1
        if self.delta:
            self._publish_delta(int(version), params)
            return
        msg = ((version, params, self.epoch) if self.epoch
               else (version, params))
        self.sock.send(self.topic + pickle.dumps(msg, protocol=5))

    def _publish_delta(self, version: int, params) -> None:
        """Keyframe/delta frames (dicts tagged ``pdelta``) instead of the
        legacy tuples.  First publish and every epoch bump are ALWAYS
        keyframes, so learner-epoch fencing semantics are untouched."""
        epoch = self.epoch
        keyframe = (self._key_bytes is None or self._want_key
                    or epoch != self._last_epoch
                    or (self._seq - self._key_seq) >= self.keyframe_every)
        frame = {"pdelta": 1, "v": version, "epoch": epoch,
                 "seq": self._seq}
        if keyframe:
            _, self._key_bytes, raw_total = wire_codec.diff_tree(params, {})
            frame["key"] = True
            frame["crc"] = wire_codec.bytes_checksum(self._key_bytes)
            frame["params"] = params
            self._key_seq = self._seq
            self._want_key = False
            self.param_keyframes += 1
        else:
            updates, new_bytes, raw_total = wire_codec.diff_tree(
                params, self._key_bytes)
            frame["key"] = False
            frame["base"] = self._key_seq
            frame["crc"] = wire_codec.bytes_checksum(new_bytes)
            frame["updates"] = updates
            self.param_deltas += 1
        payload = self.topic + pickle.dumps(frame, protocol=5)
        self.sock.send(payload)
        self._last_epoch = epoch
        self._seq += 1
        self.param_bytes_out += len(payload)
        self.param_bytes_raw += raw_total
        if not keyframe:
            self.param_delta_bytes += len(payload)

    def close(self) -> None:
        self.sock.close(linger=0)


class ParamSubscriber:
    """Actor/evaluator-side SUB with CONFLATE=1 — the kernel keeps exactly
    the newest message (``actor.py:40-49`` semantics, no user-space drain
    loop needed).

    Tenant topics (PR 13): a non-default-tenant subscriber subscribes
    exactly its tenant's frame prefix and strips it before decoding —
    zmq's publisher-side prefix filter keeps other tenants' frames off
    the wire entirely, and CONFLATE then holds the newest frame OF THIS
    TENANT.  ``topic=None`` derives the tenant from ``APEX_TENANT``;
    the default tenant subscribes everything (empty prefix), exactly
    the pre-tenancy socket."""

    def __init__(self, comms: CommsConfig, learner_ip: str | None = None,
                 topic: bytes | None = None):
        from apex_tpu.tenancy import namespace as tenancy_ns
        self.topic = (tenancy_ns.param_topic(tenancy_ns.current_tenant())
                      if topic is None else topic)
        self.sock = _ctx().socket(zmq.SUB)
        self.sock.setsockopt(zmq.CONFLATE, 1)
        self.sock.setsockopt(zmq.SUBSCRIBE, self.topic)
        ip = learner_ip or comms.learner_ip
        self.sock.connect(f"tcp://{ip}:{comms.param_port}")
        self.rejected = 0           # payloads outside the wire allowlist
        # learner-epoch of the newest stamped publish (0 until one lands);
        # the ParkController reads this to tell restart from stall
        self.learner_epoch = 0
        # param-delta reassembly state (runtime/codec.py): the stored
        # keyframe tree every delta applies against.  A publisher in
        # dense mode never sends ``pdelta`` frames, so this stays inert.
        self._key_tree = None
        self._key_seq = -1
        self.keyframes_seen = 0
        self.deltas_applied = 0
        self.delta_mismatches = 0
        self.want_keyframe = False
        # roles wire this to a KeyframeRequest send on the stat plane;
        # called (best-effort) whenever a delta cannot be applied
        self.on_mismatch = None

    def poll(self, timeout_ms: int = 0):
        """Newest ``(version, params)`` or None.  Epoch-stamped publishes
        (3-tuples) update :attr:`learner_epoch` and still return the
        2-tuple every consumer expects; ``pdelta`` frames (sparse-delta
        publishers) reassemble to the same 2-tuple."""
        if self.sock.poll(timeout_ms, zmq.POLLIN):
            from apex_tpu.tenancy import namespace as tenancy_ns
            payload = tenancy_ns.strip_topic(self.topic, self.sock.recv())
            if payload is None:
                self.rejected += 1      # a frame outside our topic
                return None
            try:
                got = wire.restricted_loads(payload)
            except wire.WireRejected:
                self.rejected += 1      # one bad publish costs one poll
                return None
            if isinstance(got, dict) and got.get("pdelta") == 1:
                return self._apply_pdelta(got)
            if isinstance(got, tuple) and len(got) == 3:
                self.learner_epoch = int(got[2])
                return got[:2]
            return got
        return None

    def _apply_pdelta(self, frame: dict):
        """Keyframe: store + return.  Delta: apply against the stored
        keyframe and verify the tree checksum; anything that does not
        reassemble bit-exactly (missed keyframe, corrupt frame) is
        dropped, counted, and answered with the :attr:`on_mismatch`
        hook (a KeyframeRequest up the stat plane)."""
        version = -1
        try:
            version = int(frame["v"])
            epoch = frame.get("epoch")
            if epoch:
                self.learner_epoch = int(epoch)
            if frame.get("key"):
                params = frame["params"]
                if wire_codec.tree_checksum(params) != int(frame["crc"]):
                    raise wire_codec.CodecError("keyframe checksum")
                self._key_tree = params
                self._key_seq = int(frame["seq"])
                self.keyframes_seen += 1
                self.want_keyframe = False
                return (version, params)
            if self._key_tree is None or int(frame["base"]) != self._key_seq:
                raise wire_codec.CodecError("no keyframe base")
            tree = wire_codec.apply_delta(self._key_tree, frame["updates"])
            if wire_codec.tree_checksum(tree) != int(frame["crc"]):
                raise wire_codec.CodecError("delta checksum")
            self.deltas_applied += 1
            return (version, tree)
        except (wire_codec.CodecError, KeyError, TypeError, ValueError):
            self.delta_mismatches += 1
            self.want_keyframe = True
            cb = self.on_mismatch
            if cb is not None:
                try:
                    cb(version)
                except Exception:
                    pass            # telemetry must never kill the poll
            return None

    def wait_first(self, stop_event=None, timeout_ms: int = 500):
        """Block (interruptibly) for the first publish
        (``actor.py:72-74``)."""
        while stop_event is None or not stop_event.is_set():
            got = self.poll(timeout_ms)
            if got is not None:
                return got
        return None

    def close(self) -> None:
        self.sock.close(linger=0)


# -- chunk/stat plane ------------------------------------------------------

class ChunkSender:
    """Actor-side DEALER with an ack-credit window: at most
    ``max_outstanding`` chunks in flight (``actor.py:110-114``).  Stats are
    fire-and-forget on the same socket (no credit consumed)."""

    def __init__(self, comms: CommsConfig, identity: str,
                 learner_ip: str | None = None, ip: str | None = None,
                 port: int | None = None, codec: str | None = None):
        """``ip``/``port`` override the learner endpoint — the sharded
        replay sender (:mod:`apex_tpu.replay_service.sender`) points the
        same credit-windowed DEALER at a replay shard's ROUTER.

        ``codec`` picks the chunk wire codec (runtime/codec.py); None
        falls back to ``comms.wire_codec``, then the ``APEX_WIRE_CODEC``
        env twin, then ``raw`` — which leaves the wire bit-identical to
        the pre-codec format."""
        self.sock = _ctx().socket(zmq.DEALER)
        self.sock.setsockopt(zmq.IDENTITY, identity.encode())
        target = ip or learner_ip or comms.learner_ip
        self.sock.connect(f"tcp://{target}:{port or comms.batch_port}")
        self.max_outstanding = comms.max_outstanding_sends
        self._in_flight = 0
        self.codec = wire_codec.resolve_codec(
            codec or getattr(comms, "wire_codec", "") or None)
        # fleet observability: cumulative wire counters (shipped in
        # Heartbeats so the learner's registry can difference them).
        # ``resends`` counts bounded-wait send attempts that found no
        # credit and were retried by the caller — the visible trace of an
        # ack-withholding fault riding out without chunk loss.
        self.chunks_sent = 0
        self.acks_received = 0
        self.resends = 0
        # codec byte counters: what rode the wire vs what raw would have
        # cost (gauges on the actor Heartbeat via wire_gauges())
        self.wire_bytes_out = 0
        self.wire_bytes_raw = 0

    def wire_gauges(self) -> dict:
        """Heartbeat gauges (keys registered in obs.metrics): codec byte
        counters + the realized compression ratio."""
        out = self.wire_bytes_out
        return {"wire_bytes_out": out,
                "wire_bytes_raw": self.wire_bytes_raw,
                "codec_ratio": (self.wire_bytes_raw / out) if out else 1.0}

    def note_resend(self) -> None:
        """The caller's retry loop re-attempted a send that timed out on
        credit (the chunk was never on the wire, so nothing is lost)."""
        self.resends += 1

    def _drain_acks(self, timeout_ms: int) -> None:
        while self.sock.poll(timeout_ms, zmq.POLLIN):
            self.sock.recv()
            self._in_flight = max(0, self._in_flight - 1)
            self.acks_received += 1
            timeout_ms = 0

    def reset_credits(self) -> None:
        """Forget the outstanding window — the park/rejoin path calls this
        after a learner death: the dead learner took the pending acks with
        it, and a stale window would wedge the first post-rejoin send
        forever.  Late acks from a fast restart land on an empty window
        (the drain clamps at zero)."""
        self._in_flight = 0

    def send_chunk(self, msg: dict, stop_event=None,
                   max_wait_s: float | None = None) -> bool:
        """Blocks while the credit window is exhausted; False if stopped —
        or, with ``max_wait_s``, if no credit arrived in time (the park
        controller's wedge detection polls through this)."""
        self._drain_acks(0)
        deadline = (None if max_wait_s is None
                    else time.monotonic() + max_wait_s)
        while self._in_flight >= self.max_outstanding:
            if stop_event is not None and stop_event.is_set():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            self._drain_acks(100)
        payload, raw_n, wire_n = wire_codec.encode_chunk(msg, self.codec)
        self.sock.send(payload)
        self.wire_bytes_raw += raw_n
        self.wire_bytes_out += wire_n
        self._in_flight += 1
        self.chunks_sent += 1
        return True

    def send_stat(self, stat) -> None:
        """Best-effort, NEVER blocks: stats are droppable telemetry, and a
        blocking send would wedge the actor loop if the learner dies."""
        try:
            self.sock.send(pickle.dumps(("stat", stat), protocol=5),
                           zmq.DONTWAIT)
        except zmq.Again:
            pass

    def close(self, drain_s: float = 2.0) -> None:
        """Drain outstanding acks (up to ``drain_s``) before closing.

        ``linger=0`` discards queued-but-unflushed messages, and with a
        credit window of W up to W just-sent chunks can still sit in the
        zmq send buffer when the actor shuts down — they would vanish
        silently (observed as a flaky all-roles test under CPU load).  An
        ack is proof the learner has received AND filed the chunk, so
        waiting for the window to empty makes clean shutdown lossless;
        on timeout (learner already dead) the remaining chunks are
        dropped, which is also what the reference's teardown does
        (``actor.py:110-114`` has no flush protocol at all)."""
        deadline = time.monotonic() + drain_s
        while self._in_flight > 0 and time.monotonic() < deadline:
            self._drain_acks(50)
        self.sock.close(linger=0)


class ChunkReceiver:
    """Learner-side ROUTER + decode pipeline: the socket thread receives
    and acks, ``n_decoders`` worker threads unpickle and enqueue.

    Acks grant the sender's next credit, so the bounded local queues
    backpressure the whole fleet end-to-end (the reference got this from
    the replay server's recv windows, ``replay.py:104-146``).  The decoder
    pool is the reference's N ``recv_batch`` pullers
    (``learner.py:71-114``, count ``arguments.py:73-74``) re-shaped for
    one process: deserialization moves OFF the socket thread so ack
    latency — the credit grant pacing the whole actor fleet — never waits
    behind a large pixel chunk's unpickle.  (Threads, not processes: the
    win here is pipelining recv/ack with decode, not CPU parallelism —
    the GIL bounds the latter, and the fused learner step, not decode
    throughput, is the intended bottleneck.)

    Backpressure chain: full ``chunks`` queue blocks decoders -> bounded
    decode queue fills -> socket thread stops receiving and acking -> zmq
    buffers -> sender credit windows exhaust -> actors block.  Exactly the
    single-threaded behavior, with one queue more of slack."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*",
                 queue_depth: int = 64, n_decoders: int | None = None):
        self.sock = _ctx().socket(zmq.ROUTER)
        self.sock.bind(f"tcp://{bind_ip}:{comms.batch_port}")
        self.chunks: queue_lib.Queue = queue_lib.Queue(maxsize=queue_depth)
        self.stats: queue_lib.Queue = queue_lib.Queue(maxsize=1024)
        # liveness observability: last wall-clock a message arrived per
        # peer.  Membership = CHUNK senders only (actors): evaluators send
        # one stat per episode — sometimes minutes apart — and finite-
        # episode evaluators exit cleanly, both of which would be constant
        # false alarms under a silence threshold.
        self.last_seen: dict[str, float] = {}
        self._chunk_senders: set[str] = set()
        # guards the two structures above: receiver/decoder threads insert
        # while silent_peers() snapshots from the trainer thread
        self._peers_lock = threading.Lock()
        self._stop = threading.Event()
        self.n_decoders = (n_decoders if n_decoders is not None
                           else comms.n_recv_batch_procs)
        self._decode_q: queue_lib.Queue = queue_lib.Queue(
            maxsize=max(2 * self.n_decoders, 8))
        self._ack_q: queue_lib.Queue = queue_lib.Queue()
        # messages handed to decoders and not yet acked/filed: while any
        # are in flight the socket loop polls on a short timeout so a
        # just-enqueued ack (the sender's next credit) leaves within ~5ms
        # instead of waiting out a full idle poll
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.rejected = 0          # payloads outside the wire allowlist
        self.codec_chunks = 0      # compressed chunks decoded OK
        self.codec_rejected = 0    # hostile/garbage codec payloads dropped
        # learner-side ingress chaos (apex_tpu/fleet/chaos, identity
        # "learner"): ack withholding parks the acks of a scheduled chunk
        # window for hold_s before releasing them, exhausting sender
        # credit windows so their bounded-retry recovery is exercised —
        # acks are DELAYED, never dropped, so no chunk is ever lost
        from apex_tpu.fleet.chaos import chaos_from_env
        chaos = chaos_from_env()
        self._chaos = (chaos.plan_for("learner")
                       if chaos is not None else None)
        self._ack_count = 0            # chunks acked or withheld so far
        self._withheld: list = []      # (release_monotonic, ident)
        self._withhold_lock = threading.Lock()
        self.acks_withheld = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._decoders = [
            threading.Thread(target=self._decode_loop, daemon=True)
            for _ in range(self.n_decoders)]

    def start(self) -> None:
        self._thread.start()
        for d in self._decoders:
            d.start()

    def _send_pending_acks(self) -> None:
        if self._withheld:
            now = time.monotonic()
            with self._withhold_lock:
                due = [i for t, i in self._withheld if t <= now]
                self._withheld = [(t, i) for t, i in self._withheld
                                  if t > now]
            for ident in due:          # the fault DELAYS acks, never
                self.sock.send_multipart([ident, b"ack"])   # drops them
        try:
            while True:
                ident = self._ack_q.get_nowait()
                self.sock.send_multipart([ident, b"ack"])
        except queue_lib.Empty:
            pass

    def _enqueue_ack(self, ident: bytes) -> None:
        """Decoder-side ack routing: scheduled ack-withhold windows park
        the ack until its release time; everything else acks normally."""
        plan = self._chaos
        if plan is not None and plan.ack_withhold_at is not None:
            with self._withhold_lock:
                i = self._ack_count
                self._ack_count += 1
                if (plan.ack_withhold_at <= i
                        < plan.ack_withhold_at + plan.ack_withhold_n):
                    self.acks_withheld += 1
                    self._withheld.append(
                        (time.monotonic() + plan.ack_withhold_s, ident))
                    return
        self._ack_q.put(ident)

    def _run(self) -> None:
        """Socket thread: the only thread touching the ROUTER (zmq sockets
        are not thread-safe) — receives frames, forwards raw payloads to
        the decoders, sends the acks they enqueue."""
        while not self._stop.is_set():
            self._send_pending_acks()
            with self._inflight_lock:
                busy = self._inflight > 0
            if not self.sock.poll(5 if busy else 100, zmq.POLLIN):
                continue
            ident, payload = self.sock.recv_multipart()
            with self._peers_lock:
                self.last_seen[ident.decode(errors="replace")] = \
                    time.monotonic()
            while not self._stop.is_set():
                try:
                    self._decode_q.put((ident, payload), timeout=0.1)
                    with self._inflight_lock:
                        self._inflight += 1
                    break
                except queue_lib.Full:     # decoders backed up: keep acks
                    self._send_pending_acks()   # flowing for what's done

    def _decode_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ident, payload = self._decode_q.get(timeout=0.1)
            except queue_lib.Empty:
                continue
            try:
                try:
                    kind, body = wire.restricted_loads(payload)
                except wire.WireRejected:
                    # count + drop, and deliberately DON'T ack: garbage
                    # must not earn its sender another credit (a hostile
                    # or corrupt peer wedges its own window, nobody
                    # else's)
                    self.rejected += 1
                    continue
                if kind == "chunkc":
                    # compressed chunk: decode HERE, on the decoder pool
                    # (never the trainer hot loop).  Garbage earns the
                    # same treatment as a WireRejected payload — counted,
                    # dropped, and deliberately unacked.
                    try:
                        body = wire_codec.decode_chunk(body)
                    except wire_codec.CodecError:
                        self.codec_rejected += 1
                        continue
                    self.codec_chunks += 1
                    kind = "chunk"
                if kind == "chunk":
                    obs_spans.stamp(body, "recv")   # lineage: wire arrival
                    with self._peers_lock:
                        self._chunk_senders.add(
                            ident.decode(errors="replace"))
                    # enqueue BEFORE acking: the ack is the credit grant
                    while not self._stop.is_set():
                        try:
                            self.chunks.put(body, timeout=0.1)
                            self._enqueue_ack(ident)
                            break
                        except queue_lib.Full:
                            continue
                elif kind == "stat":
                    try:
                        self.stats.put_nowait(body)
                    except queue_lib.Full:
                        pass
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:   # tolerate never-started
            self._thread.join(timeout=5)
        for d in self._decoders:
            if d.ident is not None:
                d.join(timeout=5)
        self.sock.close(linger=0)


# -- startup barrier -------------------------------------------------------

def barrier_release(comms: CommsConfig, n_peers: int, bind_ip: str = "*",
                    stop_event=None, timeout_s: float = 120.0) -> int:
    """Learner side (``learner.py:30-54``): collect ``n_peers`` hellos on a
    ROUTER, then release them all.  Returns peers released."""
    sock = _ctx().socket(zmq.ROUTER)
    sock.bind(f"tcp://{bind_ip}:{comms.barrier_port}")
    try:
        idents = []
        deadline = time.monotonic() + timeout_s
        while len(idents) < n_peers and time.monotonic() < deadline:
            if stop_event is not None and stop_event.is_set():
                break
            if sock.poll(100, zmq.POLLIN):
                ident, _empty, _hello = sock.recv_multipart()
                if ident not in idents:
                    idents.append(ident)
        if len(idents) == n_peers:
            # all-or-nothing: releasing a partial fleet while the learner
            # aborts would strand the released peers in their work loops;
            # unreleased peers time out in barrier_wait and exit cleanly
            for ident in idents:
                sock.send_multipart([ident, b"", b"go"])
        return len(idents)
    finally:
        sock.close(linger=0)


def barrier_wait(comms: CommsConfig, identity: str,
                 learner_ip: str | None = None, stop_event=None,
                 timeout_s: float = 120.0, rejoin_sub=None) -> bool:
    """Actor/evaluator side (``actor.py:28-37``): REQ hello, block for go.

    ``rejoin_sub``: an already-connected :class:`ParamSubscriber` polled
    ALONGSIDE the barrier reply.  The barrier exists exactly once, at
    fleet start (``learner.py:30-54``); a peer respawned by the deploy
    supervisor (``deploy/actor.sh``) finds it long gone and would
    otherwise block out the whole timeout.  A running learner republishes
    params at least every ``10 * publish_min_seconds`` (ConcurrentTrainer),
    so a received publish proves liveness past the barrier — whichever
    signal arrives first wins, making post-crash rejoin a ~seconds event
    instead of a barrier-timeout blackout."""
    sock = _ctx().socket(zmq.REQ)
    sock.setsockopt(zmq.IDENTITY, identity.encode())
    ip = learner_ip or comms.learner_ip
    sock.connect(f"tcp://{ip}:{comms.barrier_port}")
    try:
        sock.send(b"hello")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if stop_event is not None and stop_event.is_set():
                return False
            if sock.poll(100, zmq.POLLIN):
                sock.recv()
                return True
            if rejoin_sub is not None and rejoin_sub.poll(0) is not None:
                return True
        return False
    finally:
        sock.close(linger=0)


class RejoinBarrier:
    """The startup barrier, RE-RUN as a standing service (PR 8 registry
    reactions): after the one-shot all-or-nothing release
    (:func:`barrier_release`), the learner keeps a ROUTER on the barrier
    port whose thread answers EVERY hello with an immediate ``go`` — so
    late capacity (a scale-up actor that missed fleet start) and
    supervisor-respawned peers re-admit in one round-trip instead of
    waiting out the barrier timeout for the param-stream fallback.
    ``admitted`` counts re-admissions (surfaced in fleet_summary.json)."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*"):
        self.sock = _ctx().socket(zmq.ROUTER)
        # the one-shot release just closed this port in-process; give the
        # rebind a breath instead of dying on a transient EADDRINUSE
        deadline = time.monotonic() + 2.0
        while True:
            try:
                self.sock.bind(f"tcp://{bind_ip}:{comms.barrier_port}")
                break
            except zmq.ZMQError:
                if time.monotonic() > deadline:
                    self.sock.close(linger=0)
                    raise
                time.sleep(0.05)
        self.admitted = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.sock.poll(200, zmq.POLLIN):
                continue
            ident, _empty, _hello = self.sock.recv_multipart()
            self.sock.send_multipart([ident, b"", b"go"])
            self.admitted += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=5)
        self.sock.close(linger=0)


@dataclass
class RemotePool:
    """Socket-backed drop-in for :class:`apex_tpu.actors.pool.ActorPool` —
    the :class:`~apex_tpu.training.apex.ConcurrentTrainer` loop drives
    either through the same five methods, so one learner implementation
    serves the in-host and multi-host topologies.

    ``n_peers`` is the barrier head-count (actors + evaluators,
    ``learner.py:48-49`` counts the evaluator as actor "+1").

    Thread affinity under the async ingest pipeline
    (:mod:`apex_tpu.training.ingest_pipeline`): ``poll_chunks`` and
    ``publish_params`` are both driven by the single STAGING thread —
    ``poll_chunks`` reads a plain queue the receiver thread feeds (safe
    from any one consumer), and the zmq PUB socket sees a clean
    sequential handoff: built in :meth:`start` (caller thread), then used
    only by the staging thread (every publish routes through the
    pipeline, initial publish included), then closed in :meth:`cleanup`
    after the trainer joins that thread.  zmq sockets tolerate exactly
    this migrate-then-use-single-threaded pattern; what they cannot
    tolerate — and what the routing above rules out — is concurrent use
    from two threads.
    """

    comms: CommsConfig
    n_peers: int
    queue_depth: int = 64
    barrier_timeout_s: float = 120.0

    # pre-first-step republish keeps late-joining SUB sockets alive
    # (ConcurrentTrainer checks this attribute; mp pools don't need it)
    needs_warmup_republish = True

    def __post_init__(self):
        self.receiver = ChunkReceiver(self.comms,
                                      queue_depth=self.queue_depth)
        self.publisher: ParamPublisher | None = None
        self.rejoin_barrier: RejoinBarrier | None = None
        self.procs: list = []           # interface parity (nothing local)

    def start(self) -> None:
        self.receiver.start()
        # chaos harness (env-gated, identity "learner"): deterministic
        # publish stalls / kills inject here, on the real publisher
        from apex_tpu.fleet.chaos import maybe_wrap_publisher
        self.publisher = maybe_wrap_publisher(ParamPublisher(self.comms))
        released = barrier_release(self.comms, self.n_peers,
                                   timeout_s=self.barrier_timeout_s)
        if released < self.n_peers:
            # unwind: leave no bound ports / live threads behind a failed
            # start, or a same-process retry dies with EADDRINUSE
            self.cleanup()
            raise TimeoutError(
                f"startup barrier: {released}/{self.n_peers} peers")
        try:
            # the barrier re-runs as a standing service from here on:
            # respawned/late peers admit in one round-trip (losing it is
            # a degradation — the param-stream rejoin race still works —
            # never a dead learner)
            self.rejoin_barrier = RejoinBarrier(self.comms)
            self.rejoin_barrier.start()
        except Exception:
            self.rejoin_barrier = None

    def set_learner_epoch(self, epoch: int) -> None:
        """Stamp every subsequent publish with the learner's epoch
        (learner-epoch fencing; tolerates the chaos publisher wrapper)."""
        pub = self.publisher
        if pub is None:
            return
        getattr(pub, "inner", pub).epoch = int(epoch)

    def rejoin_admitted(self) -> int:
        rb = self.rejoin_barrier
        return rb.admitted if rb is not None else 0

    def acks_withheld(self) -> int:
        """Chaos-withheld acks since start (ack-withholding drills)."""
        return self.receiver.acks_withheld

    def cleanup(self) -> None:
        self.receiver.stop()
        if self.rejoin_barrier is not None:
            self.rejoin_barrier.stop()
            self.rejoin_barrier = None
        if self.publisher is not None:
            self.publisher.close()

    def publish_params(self, version: int, params) -> None:
        if self.publisher is None:
            raise RuntimeError("RemotePool.publish_params before start(): "
                               "the PUB socket binds in start()")
        self.publisher.publish(version, params)

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        out = []
        for _ in range(max_chunks):
            try:
                msg = (self.receiver.chunks.get(timeout=timeout) if timeout
                       else self.receiver.chunks.get_nowait())
            except queue_lib.Empty:
                break
            out.append(msg)
        return out

    def poll_stats(self) -> list:
        out = []
        try:
            while True:
                out.append(self.receiver.stats.get_nowait())
        except queue_lib.Empty:
            pass
        return out

    def peer_seen(self) -> dict[str, float]:
        """Locked snapshot of last message-arrival time per wire identity
        (monotonic clock) — the FleetRegistry merges this so a
        backpressured actor whose stat puts drop stays ALIVE as long as
        its chunks keep landing."""
        with self.receiver._peers_lock:
            return dict(self.receiver.last_seen)

    def wire_rejected(self) -> int:
        """Payloads dropped by the restricted unpickler since start."""
        return self.receiver.rejected

    def force_keyframe(self) -> None:
        """Relay a subscriber's KeyframeRequest to the publisher (the
        next delta-mode publish goes dense); tolerates the chaos
        publisher wrapper and dense-mode publishers."""
        pub = self.publisher
        if pub is None:
            return
        fk = getattr(getattr(pub, "inner", pub), "force_keyframe", None)
        if callable(fk):
            fk()

    def wire_summary(self) -> dict:
        """Codec-plane counters for fleet_summary.json / the metrics
        surface: receiver decode counts + publisher param-delta bytes."""
        out = {"codec_chunks": self.receiver.codec_chunks,
               "codec_rejected": self.receiver.codec_rejected}
        pub = self.publisher
        if pub is not None:
            inner = getattr(pub, "inner", pub)
            for key in ("param_publishes", "param_keyframes",
                        "param_deltas", "param_delta_bytes",
                        "param_bytes_out", "param_bytes_raw",
                        "keyframes_forced"):
                val = getattr(inner, key, None)
                if val is not None:
                    out[key] = int(val)
        return out

    def silent_peers(self, threshold_s: float = 60.0) -> list[str]:
        """CHUNK-sending peers (actors) that have sent nothing at all for
        ``threshold_s`` — a remote actor death shows up here (the learner
        cannot respawn a remote process, but it can SAY so; the reference
        topology loses actors silently forever, SURVEY.md §5.3).  Sustained
        credit-window backpressure can also trip this — the signal means
        "look at this actor", not strictly "dead"."""
        now = time.monotonic()
        # locked snapshot: the receiver thread inserts concurrently, and
        # an unguarded iteration can raise "dictionary changed size"
        with self.receiver._peers_lock:
            senders = set(self.receiver._chunk_senders)
            seen = list(self.receiver.last_seen.items())
        return sorted(ident for ident, t in seen
                      if ident in senders and now - t > threshold_s)
