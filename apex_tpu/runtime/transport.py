"""Socket transport for the multi-host plane (L4/C13-C15 re-design).

The reference's inter-node fabric is ZeroMQ TCP with four patterns:
PUB/SUB + CONFLATE for params (``origin_repo/learner.py:57-68``,
``actor.py:40-49``), DEALER/ROUTER with bounded outstanding-send windows for
transition and priority streams (``actor.py:105-115``,
``learner.py:117-131``), REQ/ROUTER for the startup barrier
(``learner.py:30-54``, ``actor.py:28-37``), and three ``zmq.proxy`` devices
bridging into a standalone replay server (``replay.py:48-74``).

The default TPU topology DISSOLVES the replay server: replay lives in the
learner's HBM (SURVEY.md §7), so the remote-ingest role collapses to one
ROUTER on the learner that feeds the fused ingest+train step directly —
C15's capability (other hosts feeding the learner) with one fewer hop and
no shared-lock bottleneck (``origin_repo/README.md:11``).  With
``comms.replay_shards > 0`` the standalone replay role returns, sharded
(:mod:`apex_tpu.replay_service`), built from the same primitives below:
each shard's ROUTER speaks this module's chunk/ack protocol, and the
:class:`ChunkSender` credit window points at shard ports via the
``ip``/``port`` overrides.  What remains here:

* :class:`ParamPublisher` / :class:`ParamSubscriber` — version-stamped
  latest-wins broadcast (SUB sets ``CONFLATE=1``: exactly the reference's
  staleness bound).
* :class:`ChunkSender` / :class:`ChunkReceiver` — actor->learner transition
  chunks with an explicit ack-based credit window (the reference bounds
  un-acked sends at 3, ``actor.py:110-114``).  Stats ride the same pipe as
  a second message kind.
* :class:`barrier_wait` / :class:`barrier_release` — startup handshake; the
  learner publishes nothing until every expected peer has checked in.

Wire format is pickle over zmq frames, like the reference's cPickle
(``actor.py:1``, ``learner.py:6``) — but every RECEIVE routes through the
allowlisted :mod:`apex_tpu.runtime.wire` unpickler, so the
trusted-cluster assumption both systems share is now defense-in-depth
instead of load-bearing: a payload referencing anything outside the
message/stat/array allowlist is counted and dropped, never executed.
"""

from __future__ import annotations

import pickle
import queue as queue_lib
import threading
import time
from dataclasses import dataclass

import zmq

from apex_tpu.config import CommsConfig
from apex_tpu.obs import spans as obs_spans
from apex_tpu.runtime import wire


def _ctx() -> zmq.Context:
    return zmq.Context.instance()


# -- param plane -----------------------------------------------------------

class ParamPublisher:
    """Learner-side PUB socket (``learner.py:57-68``): send-and-forget with
    a small HWM; slow subscribers see only the latest version.

    ``epoch`` (learner-epoch fencing, PR 8): when set nonzero, every
    publish carries the learner's monotonically-bumped epoch as a third
    tuple element so parked actors can distinguish a RESTARTED learner
    (epoch changed: the outstanding ack window died with it, reset) from
    a merely STALLED one (same epoch: the acks are still coming).  Zero
    keeps the legacy 2-tuple wire format.

    Tenant topics (PR 13): a non-default-tenant learner prefixes every
    frame with its :func:`apex_tpu.tenancy.namespace.param_topic` tag so
    a shared infer shard's per-tenant SUB sockets attribute each publish
    to the tenant whose learner sent it — and a subscriber pointed at
    the WRONG tenant's endpoint filters everything instead of silently
    serving another tenant's params.  ``topic=None`` derives this
    process's tenant from ``APEX_TENANT`` (the chaos-config env
    discipline); the default tenant's topic is empty, keeping the wire
    byte-identical to the pre-tenancy format."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*",
                 topic: bytes | None = None):
        from apex_tpu.tenancy import namespace as tenancy_ns
        self.sock = _ctx().socket(zmq.PUB)
        self.sock.setsockopt(zmq.SNDHWM, comms.param_hwm)
        self.sock.bind(f"tcp://{bind_ip}:{comms.param_port}")
        self.epoch = 0
        self.topic = (tenancy_ns.param_topic(tenancy_ns.current_tenant())
                      if topic is None else topic)

    def publish(self, version: int, params) -> None:
        msg = ((version, params, self.epoch) if self.epoch
               else (version, params))
        self.sock.send(self.topic + pickle.dumps(msg, protocol=5))

    def close(self) -> None:
        self.sock.close(linger=0)


class ParamSubscriber:
    """Actor/evaluator-side SUB with CONFLATE=1 — the kernel keeps exactly
    the newest message (``actor.py:40-49`` semantics, no user-space drain
    loop needed).

    Tenant topics (PR 13): a non-default-tenant subscriber subscribes
    exactly its tenant's frame prefix and strips it before decoding —
    zmq's publisher-side prefix filter keeps other tenants' frames off
    the wire entirely, and CONFLATE then holds the newest frame OF THIS
    TENANT.  ``topic=None`` derives the tenant from ``APEX_TENANT``;
    the default tenant subscribes everything (empty prefix), exactly
    the pre-tenancy socket."""

    def __init__(self, comms: CommsConfig, learner_ip: str | None = None,
                 topic: bytes | None = None):
        from apex_tpu.tenancy import namespace as tenancy_ns
        self.topic = (tenancy_ns.param_topic(tenancy_ns.current_tenant())
                      if topic is None else topic)
        self.sock = _ctx().socket(zmq.SUB)
        self.sock.setsockopt(zmq.CONFLATE, 1)
        self.sock.setsockopt(zmq.SUBSCRIBE, self.topic)
        ip = learner_ip or comms.learner_ip
        self.sock.connect(f"tcp://{ip}:{comms.param_port}")
        self.rejected = 0           # payloads outside the wire allowlist
        # learner-epoch of the newest stamped publish (0 until one lands);
        # the ParkController reads this to tell restart from stall
        self.learner_epoch = 0

    def poll(self, timeout_ms: int = 0):
        """Newest ``(version, params)`` or None.  Epoch-stamped publishes
        (3-tuples) update :attr:`learner_epoch` and still return the
        2-tuple every consumer expects."""
        if self.sock.poll(timeout_ms, zmq.POLLIN):
            from apex_tpu.tenancy import namespace as tenancy_ns
            payload = tenancy_ns.strip_topic(self.topic, self.sock.recv())
            if payload is None:
                self.rejected += 1      # a frame outside our topic
                return None
            try:
                got = wire.restricted_loads(payload)
            except wire.WireRejected:
                self.rejected += 1      # one bad publish costs one poll
                return None
            if isinstance(got, tuple) and len(got) == 3:
                self.learner_epoch = int(got[2])
                return got[:2]
            return got
        return None

    def wait_first(self, stop_event=None, timeout_ms: int = 500):
        """Block (interruptibly) for the first publish
        (``actor.py:72-74``)."""
        while stop_event is None or not stop_event.is_set():
            got = self.poll(timeout_ms)
            if got is not None:
                return got
        return None

    def close(self) -> None:
        self.sock.close(linger=0)


# -- chunk/stat plane ------------------------------------------------------

class ChunkSender:
    """Actor-side DEALER with an ack-credit window: at most
    ``max_outstanding`` chunks in flight (``actor.py:110-114``).  Stats are
    fire-and-forget on the same socket (no credit consumed)."""

    def __init__(self, comms: CommsConfig, identity: str,
                 learner_ip: str | None = None, ip: str | None = None,
                 port: int | None = None):
        """``ip``/``port`` override the learner endpoint — the sharded
        replay sender (:mod:`apex_tpu.replay_service.sender`) points the
        same credit-windowed DEALER at a replay shard's ROUTER."""
        self.sock = _ctx().socket(zmq.DEALER)
        self.sock.setsockopt(zmq.IDENTITY, identity.encode())
        target = ip or learner_ip or comms.learner_ip
        self.sock.connect(f"tcp://{target}:{port or comms.batch_port}")
        self.max_outstanding = comms.max_outstanding_sends
        self._in_flight = 0
        # fleet observability: cumulative wire counters (shipped in
        # Heartbeats so the learner's registry can difference them).
        # ``resends`` counts bounded-wait send attempts that found no
        # credit and were retried by the caller — the visible trace of an
        # ack-withholding fault riding out without chunk loss.
        self.chunks_sent = 0
        self.acks_received = 0
        self.resends = 0

    def note_resend(self) -> None:
        """The caller's retry loop re-attempted a send that timed out on
        credit (the chunk was never on the wire, so nothing is lost)."""
        self.resends += 1

    def _drain_acks(self, timeout_ms: int) -> None:
        while self.sock.poll(timeout_ms, zmq.POLLIN):
            self.sock.recv()
            self._in_flight = max(0, self._in_flight - 1)
            self.acks_received += 1
            timeout_ms = 0

    def reset_credits(self) -> None:
        """Forget the outstanding window — the park/rejoin path calls this
        after a learner death: the dead learner took the pending acks with
        it, and a stale window would wedge the first post-rejoin send
        forever.  Late acks from a fast restart land on an empty window
        (the drain clamps at zero)."""
        self._in_flight = 0

    def send_chunk(self, msg: dict, stop_event=None,
                   max_wait_s: float | None = None) -> bool:
        """Blocks while the credit window is exhausted; False if stopped —
        or, with ``max_wait_s``, if no credit arrived in time (the park
        controller's wedge detection polls through this)."""
        self._drain_acks(0)
        deadline = (None if max_wait_s is None
                    else time.monotonic() + max_wait_s)
        while self._in_flight >= self.max_outstanding:
            if stop_event is not None and stop_event.is_set():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            self._drain_acks(100)
        self.sock.send(pickle.dumps(("chunk", msg), protocol=5))
        self._in_flight += 1
        self.chunks_sent += 1
        return True

    def send_stat(self, stat) -> None:
        """Best-effort, NEVER blocks: stats are droppable telemetry, and a
        blocking send would wedge the actor loop if the learner dies."""
        try:
            self.sock.send(pickle.dumps(("stat", stat), protocol=5),
                           zmq.DONTWAIT)
        except zmq.Again:
            pass

    def close(self, drain_s: float = 2.0) -> None:
        """Drain outstanding acks (up to ``drain_s``) before closing.

        ``linger=0`` discards queued-but-unflushed messages, and with a
        credit window of W up to W just-sent chunks can still sit in the
        zmq send buffer when the actor shuts down — they would vanish
        silently (observed as a flaky all-roles test under CPU load).  An
        ack is proof the learner has received AND filed the chunk, so
        waiting for the window to empty makes clean shutdown lossless;
        on timeout (learner already dead) the remaining chunks are
        dropped, which is also what the reference's teardown does
        (``actor.py:110-114`` has no flush protocol at all)."""
        deadline = time.monotonic() + drain_s
        while self._in_flight > 0 and time.monotonic() < deadline:
            self._drain_acks(50)
        self.sock.close(linger=0)


class ChunkReceiver:
    """Learner-side ROUTER + decode pipeline: the socket thread receives
    and acks, ``n_decoders`` worker threads unpickle and enqueue.

    Acks grant the sender's next credit, so the bounded local queues
    backpressure the whole fleet end-to-end (the reference got this from
    the replay server's recv windows, ``replay.py:104-146``).  The decoder
    pool is the reference's N ``recv_batch`` pullers
    (``learner.py:71-114``, count ``arguments.py:73-74``) re-shaped for
    one process: deserialization moves OFF the socket thread so ack
    latency — the credit grant pacing the whole actor fleet — never waits
    behind a large pixel chunk's unpickle.  (Threads, not processes: the
    win here is pipelining recv/ack with decode, not CPU parallelism —
    the GIL bounds the latter, and the fused learner step, not decode
    throughput, is the intended bottleneck.)

    Backpressure chain: full ``chunks`` queue blocks decoders -> bounded
    decode queue fills -> socket thread stops receiving and acking -> zmq
    buffers -> sender credit windows exhaust -> actors block.  Exactly the
    single-threaded behavior, with one queue more of slack."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*",
                 queue_depth: int = 64, n_decoders: int | None = None):
        self.sock = _ctx().socket(zmq.ROUTER)
        self.sock.bind(f"tcp://{bind_ip}:{comms.batch_port}")
        self.chunks: queue_lib.Queue = queue_lib.Queue(maxsize=queue_depth)
        self.stats: queue_lib.Queue = queue_lib.Queue(maxsize=1024)
        # liveness observability: last wall-clock a message arrived per
        # peer.  Membership = CHUNK senders only (actors): evaluators send
        # one stat per episode — sometimes minutes apart — and finite-
        # episode evaluators exit cleanly, both of which would be constant
        # false alarms under a silence threshold.
        self.last_seen: dict[str, float] = {}
        self._chunk_senders: set[str] = set()
        # guards the two structures above: receiver/decoder threads insert
        # while silent_peers() snapshots from the trainer thread
        self._peers_lock = threading.Lock()
        self._stop = threading.Event()
        self.n_decoders = (n_decoders if n_decoders is not None
                           else comms.n_recv_batch_procs)
        self._decode_q: queue_lib.Queue = queue_lib.Queue(
            maxsize=max(2 * self.n_decoders, 8))
        self._ack_q: queue_lib.Queue = queue_lib.Queue()
        # messages handed to decoders and not yet acked/filed: while any
        # are in flight the socket loop polls on a short timeout so a
        # just-enqueued ack (the sender's next credit) leaves within ~5ms
        # instead of waiting out a full idle poll
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.rejected = 0          # payloads outside the wire allowlist
        # learner-side ingress chaos (apex_tpu/fleet/chaos, identity
        # "learner"): ack withholding parks the acks of a scheduled chunk
        # window for hold_s before releasing them, exhausting sender
        # credit windows so their bounded-retry recovery is exercised —
        # acks are DELAYED, never dropped, so no chunk is ever lost
        from apex_tpu.fleet.chaos import chaos_from_env
        chaos = chaos_from_env()
        self._chaos = (chaos.plan_for("learner")
                       if chaos is not None else None)
        self._ack_count = 0            # chunks acked or withheld so far
        self._withheld: list = []      # (release_monotonic, ident)
        self._withhold_lock = threading.Lock()
        self.acks_withheld = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._decoders = [
            threading.Thread(target=self._decode_loop, daemon=True)
            for _ in range(self.n_decoders)]

    def start(self) -> None:
        self._thread.start()
        for d in self._decoders:
            d.start()

    def _send_pending_acks(self) -> None:
        if self._withheld:
            now = time.monotonic()
            with self._withhold_lock:
                due = [i for t, i in self._withheld if t <= now]
                self._withheld = [(t, i) for t, i in self._withheld
                                  if t > now]
            for ident in due:          # the fault DELAYS acks, never
                self.sock.send_multipart([ident, b"ack"])   # drops them
        try:
            while True:
                ident = self._ack_q.get_nowait()
                self.sock.send_multipart([ident, b"ack"])
        except queue_lib.Empty:
            pass

    def _enqueue_ack(self, ident: bytes) -> None:
        """Decoder-side ack routing: scheduled ack-withhold windows park
        the ack until its release time; everything else acks normally."""
        plan = self._chaos
        if plan is not None and plan.ack_withhold_at is not None:
            with self._withhold_lock:
                i = self._ack_count
                self._ack_count += 1
                if (plan.ack_withhold_at <= i
                        < plan.ack_withhold_at + plan.ack_withhold_n):
                    self.acks_withheld += 1
                    self._withheld.append(
                        (time.monotonic() + plan.ack_withhold_s, ident))
                    return
        self._ack_q.put(ident)

    def _run(self) -> None:
        """Socket thread: the only thread touching the ROUTER (zmq sockets
        are not thread-safe) — receives frames, forwards raw payloads to
        the decoders, sends the acks they enqueue."""
        while not self._stop.is_set():
            self._send_pending_acks()
            with self._inflight_lock:
                busy = self._inflight > 0
            if not self.sock.poll(5 if busy else 100, zmq.POLLIN):
                continue
            ident, payload = self.sock.recv_multipart()
            with self._peers_lock:
                self.last_seen[ident.decode(errors="replace")] = \
                    time.monotonic()
            while not self._stop.is_set():
                try:
                    self._decode_q.put((ident, payload), timeout=0.1)
                    with self._inflight_lock:
                        self._inflight += 1
                    break
                except queue_lib.Full:     # decoders backed up: keep acks
                    self._send_pending_acks()   # flowing for what's done

    def _decode_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ident, payload = self._decode_q.get(timeout=0.1)
            except queue_lib.Empty:
                continue
            try:
                try:
                    kind, body = wire.restricted_loads(payload)
                except wire.WireRejected:
                    # count + drop, and deliberately DON'T ack: garbage
                    # must not earn its sender another credit (a hostile
                    # or corrupt peer wedges its own window, nobody
                    # else's)
                    self.rejected += 1
                    continue
                if kind == "chunk":
                    obs_spans.stamp(body, "recv")   # lineage: wire arrival
                    with self._peers_lock:
                        self._chunk_senders.add(
                            ident.decode(errors="replace"))
                    # enqueue BEFORE acking: the ack is the credit grant
                    while not self._stop.is_set():
                        try:
                            self.chunks.put(body, timeout=0.1)
                            self._enqueue_ack(ident)
                            break
                        except queue_lib.Full:
                            continue
                elif kind == "stat":
                    try:
                        self.stats.put_nowait(body)
                    except queue_lib.Full:
                        pass
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:   # tolerate never-started
            self._thread.join(timeout=5)
        for d in self._decoders:
            if d.ident is not None:
                d.join(timeout=5)
        self.sock.close(linger=0)


# -- startup barrier -------------------------------------------------------

def barrier_release(comms: CommsConfig, n_peers: int, bind_ip: str = "*",
                    stop_event=None, timeout_s: float = 120.0) -> int:
    """Learner side (``learner.py:30-54``): collect ``n_peers`` hellos on a
    ROUTER, then release them all.  Returns peers released."""
    sock = _ctx().socket(zmq.ROUTER)
    sock.bind(f"tcp://{bind_ip}:{comms.barrier_port}")
    try:
        idents = []
        deadline = time.monotonic() + timeout_s
        while len(idents) < n_peers and time.monotonic() < deadline:
            if stop_event is not None and stop_event.is_set():
                break
            if sock.poll(100, zmq.POLLIN):
                ident, _empty, _hello = sock.recv_multipart()
                if ident not in idents:
                    idents.append(ident)
        if len(idents) == n_peers:
            # all-or-nothing: releasing a partial fleet while the learner
            # aborts would strand the released peers in their work loops;
            # unreleased peers time out in barrier_wait and exit cleanly
            for ident in idents:
                sock.send_multipart([ident, b"", b"go"])
        return len(idents)
    finally:
        sock.close(linger=0)


def barrier_wait(comms: CommsConfig, identity: str,
                 learner_ip: str | None = None, stop_event=None,
                 timeout_s: float = 120.0, rejoin_sub=None) -> bool:
    """Actor/evaluator side (``actor.py:28-37``): REQ hello, block for go.

    ``rejoin_sub``: an already-connected :class:`ParamSubscriber` polled
    ALONGSIDE the barrier reply.  The barrier exists exactly once, at
    fleet start (``learner.py:30-54``); a peer respawned by the deploy
    supervisor (``deploy/actor.sh``) finds it long gone and would
    otherwise block out the whole timeout.  A running learner republishes
    params at least every ``10 * publish_min_seconds`` (ConcurrentTrainer),
    so a received publish proves liveness past the barrier — whichever
    signal arrives first wins, making post-crash rejoin a ~seconds event
    instead of a barrier-timeout blackout."""
    sock = _ctx().socket(zmq.REQ)
    sock.setsockopt(zmq.IDENTITY, identity.encode())
    ip = learner_ip or comms.learner_ip
    sock.connect(f"tcp://{ip}:{comms.barrier_port}")
    try:
        sock.send(b"hello")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if stop_event is not None and stop_event.is_set():
                return False
            if sock.poll(100, zmq.POLLIN):
                sock.recv()
                return True
            if rejoin_sub is not None and rejoin_sub.poll(0) is not None:
                return True
        return False
    finally:
        sock.close(linger=0)


class RejoinBarrier:
    """The startup barrier, RE-RUN as a standing service (PR 8 registry
    reactions): after the one-shot all-or-nothing release
    (:func:`barrier_release`), the learner keeps a ROUTER on the barrier
    port whose thread answers EVERY hello with an immediate ``go`` — so
    late capacity (a scale-up actor that missed fleet start) and
    supervisor-respawned peers re-admit in one round-trip instead of
    waiting out the barrier timeout for the param-stream fallback.
    ``admitted`` counts re-admissions (surfaced in fleet_summary.json)."""

    def __init__(self, comms: CommsConfig, bind_ip: str = "*"):
        self.sock = _ctx().socket(zmq.ROUTER)
        # the one-shot release just closed this port in-process; give the
        # rebind a breath instead of dying on a transient EADDRINUSE
        deadline = time.monotonic() + 2.0
        while True:
            try:
                self.sock.bind(f"tcp://{bind_ip}:{comms.barrier_port}")
                break
            except zmq.ZMQError:
                if time.monotonic() > deadline:
                    self.sock.close(linger=0)
                    raise
                time.sleep(0.05)
        self.admitted = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.sock.poll(200, zmq.POLLIN):
                continue
            ident, _empty, _hello = self.sock.recv_multipart()
            self.sock.send_multipart([ident, b"", b"go"])
            self.admitted += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=5)
        self.sock.close(linger=0)


@dataclass
class RemotePool:
    """Socket-backed drop-in for :class:`apex_tpu.actors.pool.ActorPool` —
    the :class:`~apex_tpu.training.apex.ConcurrentTrainer` loop drives
    either through the same five methods, so one learner implementation
    serves the in-host and multi-host topologies.

    ``n_peers`` is the barrier head-count (actors + evaluators,
    ``learner.py:48-49`` counts the evaluator as actor "+1").

    Thread affinity under the async ingest pipeline
    (:mod:`apex_tpu.training.ingest_pipeline`): ``poll_chunks`` and
    ``publish_params`` are both driven by the single STAGING thread —
    ``poll_chunks`` reads a plain queue the receiver thread feeds (safe
    from any one consumer), and the zmq PUB socket sees a clean
    sequential handoff: built in :meth:`start` (caller thread), then used
    only by the staging thread (every publish routes through the
    pipeline, initial publish included), then closed in :meth:`cleanup`
    after the trainer joins that thread.  zmq sockets tolerate exactly
    this migrate-then-use-single-threaded pattern; what they cannot
    tolerate — and what the routing above rules out — is concurrent use
    from two threads.
    """

    comms: CommsConfig
    n_peers: int
    queue_depth: int = 64
    barrier_timeout_s: float = 120.0

    # pre-first-step republish keeps late-joining SUB sockets alive
    # (ConcurrentTrainer checks this attribute; mp pools don't need it)
    needs_warmup_republish = True

    def __post_init__(self):
        self.receiver = ChunkReceiver(self.comms,
                                      queue_depth=self.queue_depth)
        self.publisher: ParamPublisher | None = None
        self.rejoin_barrier: RejoinBarrier | None = None
        self.procs: list = []           # interface parity (nothing local)

    def start(self) -> None:
        self.receiver.start()
        # chaos harness (env-gated, identity "learner"): deterministic
        # publish stalls / kills inject here, on the real publisher
        from apex_tpu.fleet.chaos import maybe_wrap_publisher
        self.publisher = maybe_wrap_publisher(ParamPublisher(self.comms))
        released = barrier_release(self.comms, self.n_peers,
                                   timeout_s=self.barrier_timeout_s)
        if released < self.n_peers:
            # unwind: leave no bound ports / live threads behind a failed
            # start, or a same-process retry dies with EADDRINUSE
            self.cleanup()
            raise TimeoutError(
                f"startup barrier: {released}/{self.n_peers} peers")
        try:
            # the barrier re-runs as a standing service from here on:
            # respawned/late peers admit in one round-trip (losing it is
            # a degradation — the param-stream rejoin race still works —
            # never a dead learner)
            self.rejoin_barrier = RejoinBarrier(self.comms)
            self.rejoin_barrier.start()
        except Exception:
            self.rejoin_barrier = None

    def set_learner_epoch(self, epoch: int) -> None:
        """Stamp every subsequent publish with the learner's epoch
        (learner-epoch fencing; tolerates the chaos publisher wrapper)."""
        pub = self.publisher
        if pub is None:
            return
        getattr(pub, "inner", pub).epoch = int(epoch)

    def rejoin_admitted(self) -> int:
        rb = self.rejoin_barrier
        return rb.admitted if rb is not None else 0

    def acks_withheld(self) -> int:
        """Chaos-withheld acks since start (ack-withholding drills)."""
        return self.receiver.acks_withheld

    def cleanup(self) -> None:
        self.receiver.stop()
        if self.rejoin_barrier is not None:
            self.rejoin_barrier.stop()
            self.rejoin_barrier = None
        if self.publisher is not None:
            self.publisher.close()

    def publish_params(self, version: int, params) -> None:
        if self.publisher is None:
            raise RuntimeError("RemotePool.publish_params before start(): "
                               "the PUB socket binds in start()")
        self.publisher.publish(version, params)

    def poll_chunks(self, max_chunks: int, timeout: float = 0.0) -> list:
        out = []
        for _ in range(max_chunks):
            try:
                msg = (self.receiver.chunks.get(timeout=timeout) if timeout
                       else self.receiver.chunks.get_nowait())
            except queue_lib.Empty:
                break
            out.append(msg)
        return out

    def poll_stats(self) -> list:
        out = []
        try:
            while True:
                out.append(self.receiver.stats.get_nowait())
        except queue_lib.Empty:
            pass
        return out

    def peer_seen(self) -> dict[str, float]:
        """Locked snapshot of last message-arrival time per wire identity
        (monotonic clock) — the FleetRegistry merges this so a
        backpressured actor whose stat puts drop stays ALIVE as long as
        its chunks keep landing."""
        with self.receiver._peers_lock:
            return dict(self.receiver.last_seen)

    def wire_rejected(self) -> int:
        """Payloads dropped by the restricted unpickler since start."""
        return self.receiver.rejected

    def silent_peers(self, threshold_s: float = 60.0) -> list[str]:
        """CHUNK-sending peers (actors) that have sent nothing at all for
        ``threshold_s`` — a remote actor death shows up here (the learner
        cannot respawn a remote process, but it can SAY so; the reference
        topology loses actors silently forever, SURVEY.md §5.3).  Sustained
        credit-window backpressure can also trip this — the signal means
        "look at this actor", not strictly "dead"."""
        now = time.monotonic()
        # locked snapshot: the receiver thread inserts concurrently, and
        # an unguarded iteration can raise "dictionary changed size"
        with self.receiver._peers_lock:
            senders = set(self.receiver._chunk_senders)
            seen = list(self.receiver.last_seen.items())
        return sorted(ident for ident, t in seen
                      if ident in senders and now - t > threshold_s)
