"""Process roles for the multi-host topology (reference L6 role scripts).

The reference runs four role scripts — ``origin_repo/{learner,actor,replay,
eval}.py`` — wired by env vars (``actor.py:18-25``).  By default the replay
role is dissolved into the learner (HBM-resident replay, see
:mod:`apex_tpu.runtime.transport`); ``comms.replay_shards > 0`` restores it
as a sharded standalone plane (:mod:`apex_tpu.replay_service` — its role
entry point lives there as ``run_replay_shard``, dispatched by the CLI).
The three roles here:

* :func:`run_learner` — the standard :class:`ApexTrainer` driving a
  socket-backed :class:`RemotePool`: identical fused learner, chunks arrive
  over TCP instead of mp.Queue.
* :func:`run_actor` — the SAME exploration body as the in-host pool workers
  (``apex_tpu.actors.pool._worker_main``), with the mp queues swapped for
  socket adapters: SUB(CONFLATE) params, DEALER chunks with the credit
  window, stats piggybacked.  One body, two transports — the reference
  maintains two near-copies (``batchrecorder.py`` vs ``actor.py``).
* :func:`run_evaluator` — continuous greedy evaluation on the UNCLIPPED env,
  streaming params without ever pausing the learner
  (``origin_repo/eval.py:49-87``); scores are shipped to the learner's
  metric log as stats with negative ``actor_id``s.

Every role takes the shared :class:`~apex_tpu.config.ApexConfig` plus its
role identity — exactly the reference's single-argparse + env-var scheme
(``arguments.py:5-83``; :meth:`RoleIdentity.from_env`).
"""

from __future__ import annotations

import dataclasses
import queue as queue_lib
import threading

import numpy as np

from apex_tpu.config import ApexConfig, CommsConfig, RoleIdentity
from apex_tpu.runtime import codec as wire_codec
from apex_tpu.runtime import transport


# -- socket adapters with the mp.Queue interface ---------------------------

class _ParamQueueAdapter:
    """ParamSubscriber presented as the worker body's param queue.  The
    CONFLATE socket holds at most one (newest) message, so the body's
    drain-to-latest loop terminates after one hit.

    With a :class:`~apex_tpu.fleet.park.ParkController` attached, a stale
    param stream PARKS the worker right here — the loop is blocked inside
    its routine poll, env and chunk-builder state intact — until the
    rejoin race (barrier vs param stream) reattaches it."""

    def __init__(self, sub: transport.ParamSubscriber, park=None):
        self.sub = sub
        self.park = park

    def _got(self, got):
        if got is None:
            if self.park is not None and self.park.stale():
                got = self.park.park_and_rejoin(self.sub)
                if got is not None:
                    self.park.take_pending()    # consumed here, not twice
            if got is None:
                raise queue_lib.Empty
        elif self.park is not None:
            self.park.note_params()
        return got

    def get(self, timeout: float = 0.5):
        if self.park is not None:
            pending = self.park.take_pending()
            if pending is not None:
                return pending
        return self._got(self.sub.poll(int(timeout * 1000)))

    def get_nowait(self):
        if self.park is not None:
            pending = self.park.take_pending()
            if pending is not None:
                return pending
        return self._got(self.sub.poll(0))

    def park_state(self):
        """HeartbeatEmitter ``park_fn`` hook: (parked, rejoins)."""
        if self.park is None:
            return (False, 0)
        return self.park.park_state()


class _ChunkQueueAdapter:
    """ChunkSender presented as the worker body's chunk queue; ``put``
    blocks on the ack-credit window like a bounded mp.Queue blocks on
    depth.

    With a park controller attached, a WEDGED send (credit window
    exhausted with nothing draining) checks the param stream: a healthy
    backpressuring learner keeps publishing and the send just keeps
    waiting; a dead one parks the worker here, and the rejoin resets the
    credit window before this chunk re-sends."""

    def __init__(self, sender: transport.ChunkSender, stop_event,
                 park=None):
        self.sender = sender
        self.stop_event = stop_event
        self.park = park

    def put(self, item) -> None:
        _kind, _actor_id, msg = item
        if self.park is None:
            self.sender.send_chunk(msg, self.stop_event)
            return
        while not self.stop_event.is_set():
            if self.sender.send_chunk(msg, self.stop_event, max_wait_s=1.0):
                return
            # no credit for a full second: dead learner, withheld acks,
            # or just slow?  Count the retry (the chunk never hit the
            # wire — retrying is lossless), then park_and_rejoin probes
            # the param stream and only parks when it is stale too (the
            # rejoin stashes fresh params for the param adapter's next
            # poll and resets the credit window so this chunk can
            # re-send)
            note = getattr(self.sender, "note_resend", None)
            if note is not None:
                note()
            self.park.park_and_rejoin()

    def wire_counters(self) -> dict:
        """HeartbeatEmitter ``counters_fn`` hook."""
        return {"chunks_sent": self.sender.chunks_sent,
                "acks_received": self.sender.acks_received,
                "resends": getattr(self.sender, "resends", 0),
                "rerouted": getattr(self.sender, "rerouted", 0)}

    def wire_gauges(self) -> dict:
        """HeartbeatEmitter ``gauges_fn`` hook: the sender's codec byte
        counters + realized compression ratio (runtime/codec.py)."""
        fn = getattr(self.sender, "wire_gauges", None)
        return fn() if callable(fn) else {}


class _StatQueueAdapter:
    def __init__(self, sender: transport.ChunkSender):
        self.sender = sender

    def put_nowait(self, stat) -> None:
        self.sender.send_stat(stat)


# -- roles -----------------------------------------------------------------

def run_learner(cfg: ApexConfig, n_peers: int, total_steps: int,
                max_seconds: float = 3600.0, family: str = "dqn",
                logdir: str | None = None, verbose: bool = False,
                checkpoint_dir: str | None = None, train_ratio=None,
                min_train_ratio=None, queue_depth: int = 64,
                barrier_timeout_s: float = 120.0, restore: bool = False,
                rollout: str = "host", rollout_len: int | None = None,
                steps_per_dispatch: int = 4):
    """Learner role: barrier -> publish -> fused ingest+train loop.

    ``n_peers`` = actors + evaluators expected at the startup barrier
    (``learner.py:48-49``).  Returns the trainer (params, metrics history).

    ``rollout="ondevice"`` co-locates an Anakin rollout engine with the
    learner (:mod:`apex_tpu.training.anakin`): the socket pool keeps
    serving any host actors/evaluators while sealed chunks ALSO stream
    from the fused on-device scan — params hand to the engine as device
    arrays, never leaving the accelerator.

    ``rollout="fused"`` goes further (:mod:`apex_tpu.ondevice`): the
    whole rollout -> ingest -> sample -> train -> write-back cycle runs
    as ONE jitted program per dispatch; the socket pool keeps serving
    evaluators/status, host-actor chunks absorb between dispatches, and
    the host wakes once per ``steps_per_dispatch`` macro steps.
    """
    pool = transport.RemotePool(cfg.comms, n_peers, queue_depth=queue_depth,
                                barrier_timeout_s=barrier_timeout_s)
    if rollout == "fused":
        if family != "dqn":
            pool.cleanup()
            raise NotImplementedError(
                f"--rollout fused currently serves the dqn family only "
                f"(got {family!r}) — aql/r2d2 slot in behind the same "
                f"scan hooks (ROADMAP.md)")
        if cfg.comms.replay_shards > 0:
            pool.cleanup()
            raise ValueError(
                "--rollout fused owns replay on-device — run with "
                "--replay-shards 0 (APEX_REPLAY_SHARDS=0); the shard "
                "fleet serves the host topologies")
        from apex_tpu.ondevice.fused import FusedApexTrainer
        try:
            # make_jax_env's ValueError names non-jittable env ids and
            # the dp divisibility guards name --n-envs-per-actor /
            # --batch-size vs --mesh-dp, all before train()
            trainer = FusedApexTrainer(
                cfg, logdir=logdir, verbose=verbose,
                checkpoint_dir=checkpoint_dir, train_ratio=train_ratio,
                min_train_ratio=min_train_ratio, pool=pool,
                rollout_len=rollout_len,
                steps_per_dispatch=steps_per_dispatch)
            if restore:
                trainer.restore()
        except BaseException:
            pool.cleanup()
            raise
        return trainer.train(total_steps=total_steps,
                             max_seconds=max_seconds)
    if rollout == "ondevice":
        if family != "dqn":
            pool.cleanup()
            raise NotImplementedError(
                f"--rollout ondevice currently serves the dqn family "
                f"only (got {family!r}) — aql/r2d2 stay on the host "
                f"pipeline (ROADMAP.md)")
        from apex_tpu.training.anakin import AnakinPool, make_anakin_engine
        try:
            # make_jax_env raises a ValueError naming non-jittable env ids
            engine = make_anakin_engine(cfg, rollout_len=rollout_len)
        except BaseException:
            pool.cleanup()
            raise
        pool = AnakinPool(cfg, engine, inner=pool)
    client = None
    if cfg.comms.replay_shards > 0:
        # sharded replay service: sampling lives in the shard fleet; the
        # learner pulls pre-sampled batches and ships write-backs.  The
        # chunk ROUTER above stays bound — it still carries stats,
        # heartbeats, and the actors' direct-ingest fallback chunks.
        if family != "dqn":
            pool.cleanup()
            raise NotImplementedError(
                f"--replay-shards currently serves the dqn family only "
                f"(got {family!r}) — aql/r2d2 stay on in-learner replay")
        from apex_tpu.replay_service.client import ReplayServiceClient
        client = ReplayServiceClient(cfg.comms)
    try:
        if family == "dqn":
            from apex_tpu.training.apex import ApexTrainer
            trainer = ApexTrainer(cfg, logdir=logdir, verbose=verbose,
                                  checkpoint_dir=checkpoint_dir,
                                  train_ratio=train_ratio,
                                  min_train_ratio=min_train_ratio,
                                  pool=pool)
        elif family == "aql":
            from apex_tpu.training.aql import AQLApexTrainer
            trainer = AQLApexTrainer(cfg, logdir=logdir, verbose=verbose,
                                     checkpoint_dir=checkpoint_dir,
                                     train_ratio=train_ratio,
                                     min_train_ratio=min_train_ratio,
                                     pool=pool)
        elif family == "r2d2":
            from apex_tpu.training.r2d2 import R2D2ApexTrainer
            trainer = R2D2ApexTrainer(cfg, logdir=logdir, verbose=verbose,
                                      checkpoint_dir=checkpoint_dir,
                                      train_ratio=train_ratio,
                                      min_train_ratio=min_train_ratio,
                                      pool=pool)
        else:
            raise ValueError(f"unknown family {family!r}")
        if restore:
            trainer.restore()        # newest checkpoint in checkpoint_dir
        trainer.replay_client = client
    except BaseException:
        # the pool binds its ROUTER at construction — unwind it if the
        # trainer never gets far enough for train()'s finally to run
        pool.cleanup()
        if client is not None:
            client.close()
        raise
    try:
        return trainer.train(total_steps=total_steps,
                             max_seconds=max_seconds)
    finally:
        if client is not None:
            client.close()


def _join_fleet(comms, name: str, stop_event,
                timeout_s: float) -> "transport.ParamSubscriber":
    """Shared actor/evaluator fleet-join: connect the param SUB first, then
    race the one-shot startup barrier against the param stream
    (``transport.barrier_wait`` rejoin contract) — a fresh fleet releases
    via the barrier, a supervisor-respawned peer rejoins within seconds on
    the first republish, and the learner's ``silent_peers`` report clears
    on its first chunk.  Returns the connected subscriber; raises (and
    closes it) when neither signal arrives."""
    sub = transport.ParamSubscriber(comms)
    if not transport.barrier_wait(comms, name, stop_event=stop_event,
                                  timeout_s=timeout_s, rejoin_sub=sub):
        sub.close()
        raise TimeoutError(f"{name}: startup barrier timed out and no "
                           f"params flowing (learner not running)")
    return sub


def run_actor(cfg: ApexConfig, identity: RoleIdentity,
              family: str = "dqn", stop_event=None,
              barrier_timeout_s: float = 120.0) -> None:
    """Actor role: barrier -> SUB params -> explore -> DEALER chunks.

    Epsilon comes from the fleet-wide ladder position
    (``actor.py:69``): ``eps_base ** (1 + id/(N-1) * eps_alpha)``.
    """
    from apex_tpu.actors.pool import _worker_main, actor_epsilons

    from apex_tpu.fleet.chaos import maybe_wrap_sender
    from apex_tpu.fleet.park import ParkController

    if getattr(cfg.actor, "remote_policy", False) and family != "dqn":
        # guard BEFORE the fleet join: failing loud beats a fleet
        # silently acting on local policies while the operator believes
        # inference is centralized — and beats burning the barrier
        # timeout to say so
        raise NotImplementedError(
            f"--remote-policy currently serves the dqn family only "
            f"(got {family!r}) — aql/r2d2 actors stay on local "
            f"policies (ROADMAP.md)")
    stop_event = stop_event or threading.Event()
    # tenant-qualified wire identity (PR 13): two tenants' actor-0
    # processes sharing one replay/infer plane must never collide on a
    # ROUTER identity, and the tenant prefix is what partitions their
    # chunk ids; the default tenant qualifies to the bare name
    from apex_tpu.tenancy import namespace as tenancy_ns
    name = tenancy_ns.qualify(tenancy_ns.current_tenant(),
                              f"actor-{identity.actor_id}")
    comms = _with_ips(cfg.comms, identity)
    sub = _join_fleet(comms, name, stop_event, barrier_timeout_s)
    eps = actor_epsilons(identity.n_actors, cfg.actor.eps_base,
                         cfg.actor.eps_alpha)[identity.actor_id]

    sender = transport.ChunkSender(comms, name)
    if comms.replay_shards > 0:
        # sharded replay service: chunks hash to shard sockets; the
        # learner channel just built stays the stat/heartbeat pipe, the
        # park-liveness probe, and the direct-ingest fallback
        from apex_tpu.replay_service.sender import ShardedChunkSender
        sender = ShardedChunkSender(comms, name, direct=sender)
    sender = maybe_wrap_sender(sender, name)
    park = ParkController(comms, name, stop_event, sub=sub, sender=sender)
    # param-delta recovery: a delta this subscriber cannot apply (missed
    # keyframe under CONFLATE, checksum mismatch) asks the trainer for a
    # dense publish over the stat plane (best-effort, like any stat)
    sub.on_mismatch = lambda v: sender.send_stat(
        wire_codec.KeyframeRequest(name, int(v)))
    chunk_arg = cfg.actor.send_interval
    if family == "dqn":
        from apex_tpu.training.apex import dqn_model_spec
        worker_fn, model_spec = _worker_main, dqn_model_spec(cfg)
        if cfg.actor.n_envs_per_actor > 1 or cfg.actor.remote_policy:
            # remote policy lives on the vector family's half-group
            # hooks, so it forces the vector body even at B=1 (one
            # group, serial interleave — still one request per step)
            from apex_tpu.actors.vector import vector_worker_main
            worker_fn = vector_worker_main
            # the vector family re-derives its slots' epsilons from the
            # ladder over cfg.actor.n_actors * n_envs_per_actor — align the
            # config with the FLEET size the deploy scripts put in the
            # identity (actor.py:18-25)
            cfg = cfg.replace(actor=dataclasses.replace(
                cfg.actor, n_actors=identity.n_actors))
    elif family == "aql":
        from apex_tpu.actors.aql import aql_worker_main
        from apex_tpu.envs.registry import make_env
        from apex_tpu.training.aql import aql_model_spec
        probe = make_env(cfg.env.env_id, cfg.env, seed=0)
        worker_fn, model_spec = aql_worker_main, aql_model_spec(cfg, probe)
        probe.close()
        if cfg.actor.n_envs_per_actor > 1:
            from apex_tpu.actors.aql import vector_aql_worker_main
            worker_fn = vector_aql_worker_main
            cfg = cfg.replace(actor=dataclasses.replace(
                cfg.actor, n_actors=identity.n_actors))
    elif family == "r2d2":
        from apex_tpu.actors.r2d2 import r2d2_worker_main
        from apex_tpu.training.r2d2 import r2d2_model_spec
        model_spec = r2d2_model_spec(cfg)
        # single frames (the LSTM is the memory); the sequence group per
        # message is the one shared cfg.r2d2 constant, so actor messages
        # and the learner's expected shapes can't drift
        cfg = cfg.replace(env=dataclasses.replace(cfg.env, frame_stack=1))
        worker_fn, chunk_arg = r2d2_worker_main, cfg.r2d2.sequence_group
        if cfg.actor.n_envs_per_actor > 1:
            from apex_tpu.actors.r2d2 import vector_r2d2_worker_main
            worker_fn = vector_r2d2_worker_main
            cfg = cfg.replace(actor=dataclasses.replace(
                cfg.actor, n_actors=identity.n_actors))
    else:
        raise ValueError(f"unknown family {family!r}")
    try:
        worker_fn(identity.actor_id, cfg, model_spec,
                  _ChunkQueueAdapter(sender, stop_event, park=park),
                  _ParamQueueAdapter(sub, park=park),
                  _StatQueueAdapter(sender),
                  stop_event, float(eps), chunk_arg)
    finally:
        sender.close()
        sub.close()


def run_loadgen(cfg: ApexConfig, identity: RoleIdentity,
                family: str = "dqn", stop_event=None,
                max_seconds: float = 86400.0,
                rollout_len: int | None = None) -> dict:
    """Loadgen role: the on-device Anakin rollout engine as a standalone
    traffic source (:mod:`apex_tpu.training.anakin`).

    Subscribes the param stream like an actor, then ships device-rate
    sealed chunks down the normal chunk plane — hashed to the replay
    shards when ``comms.replay_shards > 0``, learner-direct otherwise —
    with heartbeats (role ``loadgen``) and episode stats riding the stat
    channel, so the registry/status/chaos planes cover it for free.  The
    credit window is the only throttle: this role exists to SATURATE the
    ingest path for honest load measurement, where the CI box's host
    actors top out two orders of magnitude lower.  Skips the startup
    barrier (useful from the first publish, launch order free).  Returns
    the counter dict for callers/tests."""
    import time as time_lib

    from apex_tpu.fleet.chaos import maybe_wrap_sender
    from apex_tpu.fleet.heartbeat import HeartbeatEmitter
    from apex_tpu.obs import spans as obs_spans
    from apex_tpu.obs.trace import set_process_label
    from apex_tpu.training.anakin import make_anakin_engine

    if family != "dqn":
        raise NotImplementedError(
            f"--role loadgen currently serves the dqn family only "
            f"(got {family!r}) — see ROADMAP.md")
    stop_event = stop_event or threading.Event()
    from apex_tpu.tenancy import namespace as tenancy_ns
    name = tenancy_ns.qualify(tenancy_ns.current_tenant(),
                              f"loadgen-{identity.actor_id}")
    set_process_label(name)
    comms = _with_ips(cfg.comms, identity)
    # engine first: make_jax_env's non-jittable ValueError must fire
    # before any socket waits
    engine = make_anakin_engine(
        cfg, rollout_len=rollout_len,
        n_envs=max(1, cfg.actor.n_envs_per_actor),
        slot_band=identity.actor_id,
        total_slots=max(identity.n_actors, 1)
        * max(1, cfg.actor.n_envs_per_actor))

    sub = transport.ParamSubscriber(comms)
    sender = transport.ChunkSender(comms, name)
    if comms.replay_shards > 0:
        from apex_tpu.replay_service.sender import ShardedChunkSender
        sender = ShardedChunkSender(comms, name, direct=sender)
    sender = maybe_wrap_sender(sender, name)
    sub.on_mismatch = lambda v: sender.send_stat(
        wire_codec.KeyframeRequest(name, int(v)))
    beat = HeartbeatEmitter(
        name, role="loadgen", interval_s=comms.heartbeat_interval_s,
        counters_fn=(lambda: {
            "chunks_sent": getattr(sender, "chunks_sent", 0),
            "acks_received": getattr(sender, "acks_received", 0),
            "resends": getattr(sender, "resends", 0),
            "rerouted": getattr(sender, "rerouted", 0)}),
        gauges_fn=(lambda: {
            "ondevice_chunks": engine.chunks,
            "ondevice_frames": engine.frames,
            "ondevice_dispatches": engine.dispatches,
            **(sender.wire_gauges()
               if hasattr(sender, "wire_gauges") else {})}))
    try:
        got = sub.wait_first(stop_event)
        if got is None:
            return {"chunks": 0, "frames": 0, "dispatches": 0}
        version, params = got
        t_end = time_lib.monotonic() + max_seconds
        while not stop_event.is_set() and time_lib.monotonic() < t_end:
            fresh = sub.poll(0)
            if fresh is not None:
                version, params = fresh
            msgs, stats = engine.rollout(params)
            beat.tick(engine.T * engine.B)
            for stat in stats:
                stat.param_version = version
                sender.send_stat(stat)
            hb = beat.maybe_beat(version)
            if hb is not None:
                sender.send_stat(hb)
            for msg in msgs:
                obs_spans.mark_send(msg, version)
                sender.send_chunk(msg, stop_event)   # credit backpressure
        return {"chunks": engine.chunks, "frames": engine.frames,
                "dispatches": engine.dispatches}
    finally:
        sender.close()
        sub.close()


def run_evaluator(cfg: ApexConfig, identity: RoleIdentity | None = None,
                  family: str = "dqn", stop_event=None, episodes: int = 0,
                  max_steps: int = 10_000, logdir: str | None = None,
                  verbose: bool = False,
                  barrier_timeout_s: float = 120.0) -> list[float]:
    """Evaluator role (``eval.py:49-87``): greedy episodes on the unclipped
    env, refreshing params per episode, forever (or ``episodes`` if > 0).
    Scores are logged locally AND shipped to the learner (actor_id = -(id+1))."""
    import uuid

    from apex_tpu.envs.registry import make_eval_env
    from apex_tpu.utils.metrics import MetricLogger

    stop_event = stop_event or threading.Event()
    identity = identity or RoleIdentity(role="evaluator")
    if family == "r2d2":        # single frames: the LSTM is the memory
        cfg = cfg.replace(env=dataclasses.replace(cfg.env, frame_stack=1))
    # unique per-evaluator socket/barrier identity: duplicate identities
    # dedup at the barrier (deadlock) and misroute on the ROUTER.  The
    # random suffix makes N default-launched evaluators safe — unlike
    # actors, evaluator ids carry no semantics (no epsilon ladder slot)
    from apex_tpu.fleet.chaos import maybe_wrap_sender
    from apex_tpu.fleet.park import ParkController

    from apex_tpu.tenancy import namespace as tenancy_ns
    name = tenancy_ns.qualify(
        tenancy_ns.current_tenant(),
        f"evaluator-{identity.actor_id}-{uuid.uuid4().hex[:6]}")
    comms = _with_ips(cfg.comms, identity)
    sub = _join_fleet(comms, name, stop_event, barrier_timeout_s)

    sender = maybe_wrap_sender(transport.ChunkSender(comms, name), name)
    park = ParkController(comms, name, stop_event, sub=sub, sender=sender,
                          role="evaluator")
    sub.on_mismatch = lambda v: sender.send_stat(
        wire_codec.KeyframeRequest(name, int(v)))
    log = MetricLogger("evaluator", logdir, verbose=verbose)
    env = make_eval_env(cfg.env.env_id, cfg.env, seed=cfg.env.seed + 7777)
    try:
        return _evaluator_body(cfg, identity, family, stop_event, episodes,
                               max_steps, sub, sender, log, env, park=park)
    finally:
        sender.close()
        sub.close()
        env.close()


def _evaluator_body(cfg, identity, family, stop_event, episodes, max_steps,
                    sub, sender, log, env, park=None) -> list[float]:
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu.actors.pool import EpisodeStat
    from apex_tpu.fleet.chaos import chaos_from_env
    from apex_tpu.fleet.heartbeat import HeartbeatEmitter
    from apex_tpu.obs.trace import get_ring, set_process_label

    # evaluators were the one role without a trace ring: label the
    # process by its fleet identity (obs.merge joins it against the
    # registry's clock offsets) and record episode/param-refresh events
    set_process_label(park.identity if park is not None
                      else f"evaluator-{identity.actor_id}")
    ring = get_ring()

    reset_act = None            # recurrent families override per episode
    if family == "dqn":
        from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
        from apex_tpu.training.apex import dqn_model_spec
        model = DuelingDQN(**dqn_model_spec(cfg))
        policy = jax.jit(make_policy_fn(model))

        def act(params, obs, key):
            a, _ = policy(params, obs[None], jnp.float32(0.0), key)
            return int(a[0])
    elif family == "aql":
        from apex_tpu.envs.registry import make_env
        from apex_tpu.models.aql import AQLNetwork, make_aql_policy_fn
        from apex_tpu.training.aql import aql_model_spec
        probe = make_env(cfg.env.env_id, cfg.env, seed=0)
        model = AQLNetwork(**aql_model_spec(cfg, probe),
                           noisy_deterministic=True)
        probe.close()
        policy = jax.jit(make_aql_policy_fn(model))

        def act(params, obs, key):
            a, _, _, _ = policy(params, obs[None], jnp.float32(0.0), key)
            return np.asarray(a[0])
    elif family == "r2d2":
        from apex_tpu.models.recurrent import (RecurrentDuelingDQN,
                                               make_recurrent_policy_fn)
        from apex_tpu.training.r2d2 import r2d2_model_spec
        model = RecurrentDuelingDQN(**r2d2_model_spec(cfg))
        policy = jax.jit(make_recurrent_policy_fn(model))
        carry_box = [model.initial_state(1)]

        def act(params, obs, key):
            a, _, carry_box[0] = policy(params, obs[None], carry_box[0],
                                        jnp.float32(0.0), key)
            return int(a[0])

        def reset_act():
            carry_box[0] = model.initial_state(1)
    else:
        raise ValueError(f"unknown family {family!r}")

    got = sub.wait_first(stop_event)
    if got is None:
        return []
    version, params = got
    if park is not None:
        park.note_params()
    # eval-ladder scores ride the heartbeat gauges: each evaluator IS
    # one band (its actor_id slot — N evaluators span the eval ladder
    # the way actor ids span the epsilon ladder), and its recent-window
    # mean + episode count reach the registry/status/Prometheus surface
    # on the beats it already sends — so the SLO engine (and the future
    # canary/promotion gate) can objective on MODEL QUALITY
    # (obs/slo.py `eval_score`), not just plumbing.
    from collections import deque as _deque
    recent_scores: _deque = _deque(maxlen=16)
    scores: list[float] = []

    def _eval_gauges() -> dict:
        return {
            "eval_band": identity.actor_id,
            "eval_episodes": len(scores),
            "eval_score_last": (round(scores[-1], 3) if scores else 0.0),
            "eval_score_mean": (round(sum(recent_scores)
                                      / len(recent_scores), 3)
                                if recent_scores else 0.0)}

    emitter = HeartbeatEmitter(
        park.identity if park is not None
        else f"evaluator-{identity.actor_id}",
        role="evaluator", interval_s=cfg.comms.heartbeat_interval_s,
        counters_fn=(lambda: {
            "chunks_sent": getattr(sender, "chunks_sent", 0),
            "acks_received": getattr(sender, "acks_received", 0)}),
        park_fn=park.park_state if park is not None else None,
        gauges_fn=_eval_gauges)
    # chaos score_bias (serving-tier canary drills): a scheduled
    # model-quality regression — after after_s of this run, every
    # reported score shifts by delta, so the eval-ladder gauges and the
    # eval_score SLO see a degraded model on a deterministic schedule
    chaos = chaos_from_env()
    plan = (chaos.plan_for(emitter.identity) if chaos is not None
            else None)
    bias_t0 = time.monotonic()
    key = jax.random.key(cfg.env.seed + 31337)
    ep = 0
    while not stop_event.is_set() and (episodes <= 0 or ep < episodes):
        obs, _ = env.reset()
        if reset_act is not None:       # recurrent: fresh carry per episode
            reset_act()
        total, done, steps = 0.0, False, 0
        ep_t0 = time.perf_counter()
        while not done and steps < max_steps and not stop_event.is_set():
            key, k = jax.random.split(key)
            obs, r, term, trunc, _ = env.step(act(params, np.asarray(obs), k))
            total += float(r)
            done = term or trunc
            steps += 1
            emitter.tick()
            hb = emitter.maybe_beat(version)
            if hb is not None:
                sender.send_stat(hb)
        if (plan is not None and plan.score_bias_after_s is not None
                and time.monotonic() - bias_t0
                >= plan.score_bias_after_s):
            total += plan.score_bias_delta
        scores.append(total)
        recent_scores.append(total)
        ring.complete("episode", ep_t0, time.perf_counter() - ep_t0,
                      track="eval-episodes",
                      args={"reward": round(total, 3), "steps": steps,
                            "param_version": version})
        log.scalars({"episode_reward": total, "episode_length": steps,
                     "param_version": version}, ep)
        sender.send_stat(EpisodeStat(-(identity.actor_id + 1), total, steps,
                                     version))
        got = sub.poll(0)               # param refresh per episode
        if got is not None:
            version, params = got
            if park is not None:
                park.note_params()
            ring.instant("param_refresh", track="eval-episodes",
                         args={"version": version})
        elif park is not None and park.stale():
            # the stream died mid-run: park between episodes, resume on
            # the respawned learner's first publish
            got = park.park_and_rejoin()
            if got is not None:
                park.take_pending()
                version, params = got
        ep += 1
    return scores


def _with_ips(comms: CommsConfig, identity: RoleIdentity) -> CommsConfig:
    """An EXPLICIT learner/replay IP on the role identity wins over the
    config (``actor.py:18-25`` env-var pattern); a default-constructed
    identity must not stomp a configured ``comms.learner_ip`` (or
    ``replay_ip``) with localhost."""
    default = RoleIdentity()
    overrides = {}
    if identity.learner_ip != default.learner_ip:
        overrides["learner_ip"] = identity.learner_ip
    if identity.replay_ip != default.replay_ip:
        overrides["replay_ip"] = identity.replay_ip
    return dataclasses.replace(comms, **overrides) if overrides else comms
