"""THE allowlisted unpickler for every socket/IPC boundary.

The ZMQ plane's wire format is pickle (``runtime/transport.py``), which the
reference justified with a trusted-cluster assumption — but a bare
``pickle.loads`` turns any reachable port into remote code execution
(``__reduce__`` payloads run arbitrary callables at load time).  This
module closes that hole without changing the wire format:
:class:`RestrictedUnpickler` resolves only the globals the fleet's real
messages need — the stat/heartbeat dataclasses and the numpy/jax array
reconstruction machinery — and anything else raises :class:`WireRejected`
for the caller to count and drop.

Every deserialization of cross-process bytes routes through
:func:`restricted_loads`; apexlint rule C005 (``naked-pickle-loads``) flags
``pickle.loads``/``pickle.Unpickler`` anywhere outside this module so the
discipline cannot silently regress.

Scope note: message CONTENT is structural (dicts/tuples/ndarrays pickle
without find_class), so the allowlist stays tiny and adding a new message
dataclass means adding exactly one ``(module, name)`` pair here.
"""

from __future__ import annotations

import io
import pickle


class WireRejected(pickle.UnpicklingError):
    """A payload referenced a global outside the wire allowlist."""


#: exact (module, name) pairs the fleet's wire messages resolve.  Stats:
#: the worker stat dataclasses + fleet heartbeats.  Arrays: numpy's
#: reconstruction helpers (both the numpy>=2 ``_core`` and the numpy<2
#: ``core`` spellings, so mixed-version fleets interoperate) and jax's
#: array rebuild hook (params are device_get before publish, but a jax
#: array handed to a send path must not brick the receiver).
ALLOWED_GLOBALS: frozenset[tuple[str, str]] = frozenset({
    ("apex_tpu.actors.pool", "EpisodeStat"),
    ("apex_tpu.actors.pool", "ActorTimingStat"),
    ("apex_tpu.fleet.heartbeat", "Heartbeat"),
    ("apex_tpu.runtime.codec", "KeyframeRequest"),
    ("apex_tpu.serving.deploy", "ServingStat"),
    ("apex_tpu.tenancy.scheduler", "TenancyStat"),
    ("apex_tpu.population.controller", "PopulationStat"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy.core.numeric", "_frombuffer"),
    ("jax._src.array", "_reconstruct_array"),
    ("flax.core.frozen_dict", "FrozenDict"),
})


class RestrictedUnpickler(pickle.Unpickler):
    """Unpickler whose global resolution is exactly :data:`ALLOWED_GLOBALS`."""

    def find_class(self, module: str, name: str):
        if (module, name) in ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise WireRejected(
            f"wire payload references {module}.{name}, which is outside "
            f"the apex_tpu.runtime.wire allowlist — rejected")


def restricted_loads(data: bytes):
    """``pickle.loads`` with the wire allowlist; raises :class:`WireRejected`
    on any global outside it (callers count and drop — a hostile or
    corrupt payload must cost one message, never the process)."""
    return RestrictedUnpickler(io.BytesIO(data)).load()


def dumps(obj, protocol: int = 5) -> bytes:
    """Serialization twin, so both wire directions import one module."""
    return pickle.dumps(obj, protocol=protocol)
