"""CLI entry points: `python -m apex_tpu.runtime --role ...`.

The reference is launched as four role scripts sharing one argparse
(``origin_repo/arguments.py:5-83``) with role identity injected through env
vars by the deploy scripts (``deploy/actor.sh:4-9``).  Same scheme here:
every flag has an env-var twin (flag wins), `--role` defaults to
``$APEX_ROLE``, and one binary serves every role — so the localhost
topology script and a cluster template launch identical commands.

Examples::

    # learner expecting 2 actors + 1 evaluator on this host
    python -m apex_tpu.runtime --role learner --n-actors 2 \
        --env-id ApexCartPole-v0 --total-steps 5000

    APEX_ROLE=actor ACTOR_ID=0 N_ACTORS=2 LEARNER_IP=10.0.0.2 \
        python -m apex_tpu.runtime --env-id ApexCartPole-v0

    python -m apex_tpu.runtime --role evaluator --learner-ip 10.0.0.2

    # single-process (no sockets) drivers
    python -m apex_tpu.runtime --role dqn --total-frames 20000
    python -m apex_tpu.runtime --role enjoy --checkpoint ckpt_5000.msgpack
"""

from __future__ import annotations

import argparse
import os

from apex_tpu.config import (ActorConfig, ApexConfig, AQLConfig, CommsConfig,
                             EnvConfig, LearnerConfig, ReplayConfig,
                             RoleIdentity)


def _env_bool(value: str) -> bool:
    """Env-var booleans: '0'/'false'/'no'/'' are off (bool(str) is not)."""
    return value.lower() not in ("", "0", "false", "no")


def build_parser() -> argparse.ArgumentParser:
    e = os.environ
    ident = RoleIdentity.from_env(e)
    p = argparse.ArgumentParser(
        prog="apex_tpu",
        description="TPU-native Ape-X/AQL roles (reference arguments.py)")
    p.add_argument("--role", default=ident.role,
                   choices=["learner", "actor", "evaluator", "replay",
                            "infer", "serve-ctl", "tenant-ctl", "pbt-ctl",
                            "status", "loadgen", "dqn", "aql", "r2d2",
                            "apex", "enjoy"],
                   help="socket roles: learner/actor/evaluator/replay "
                        "(one prioritized-replay shard — see "
                        "--replay-shards/--shard-id)/infer (one "
                        "batched-inference shard for --remote-policy "
                        "actors — see --infer-shards/--infer-shard-id)/"
                        "serve-ctl (the serving tier's canary "
                        "deployment controller, apex_tpu/serving)/"
                        "tenant-ctl (the multi-tenant placement "
                        "controller, apex_tpu/tenancy — admissions, "
                        "weighted shard bands, evictions)/"
                        "pbt-ctl (the population-based-training "
                        "controller, apex_tpu/population — task "
                        "ladders, exploit/explore over the "
                        "APEX_POPULATION lineage roster); "
                        "status: print the live fleet table from the "
                        "learner's registry; "
                        "loadgen: standalone on-device rollout fleet "
                        "saturating the chunk plane (training/anakin.py); "
                        "single-host drivers: dqn/aql/r2d2/apex; "
                        "enjoy: eval a checkpoint")
    p.add_argument("--family", default=e.get("APEX_FAMILY", "dqn"),
                   choices=["dqn", "aql", "r2d2"])
    p.add_argument("--rollout", default=e.get("APEX_ROLLOUT", "host"),
                   choices=["host", "ondevice", "fused"],
                   help="learner/apex roles: 'ondevice' co-locates an "
                        "Anakin rollout engine with the learner — env "
                        "step + epsilon-greedy policy + chunk assembly "
                        "fuse into one lax.scan on the training device, "
                        "params never leave it (jittable envs only: "
                        "ApexCatch*/ApexRally*; see envs/registry."
                        "make_jax_env).  'fused' goes further "
                        "(apex_tpu/ondevice): rollout + ingest + "
                        "prioritized sample + train + priority "
                        "write-back run as ONE jitted program per "
                        "dispatch — the host wakes once per "
                        "--steps-per-dispatch macro steps, sharded "
                        "over --mesh-dp chips (dqn family, in-learner "
                        "replay only).  'host' "
                        "(default) keeps the generic actor-process "
                        "pipeline")
    p.add_argument("--rollout-len", type=int,
                   default=int(e.get("APEX_ROLLOUT_LEN", 0)),
                   help="on-device scan length per dispatch (env steps "
                        "per slot); 0 derives the chunk size "
                        "(--send-interval twin) so each dispatch seals "
                        "about one chunk per env slot")
    p.add_argument("--steps-per-dispatch", type=int,
                   default=int(e.get("APEX_STEPS_PER_DISPATCH", 4)),
                   help="--rollout fused: macro steps (rollout segment "
                        "-> ingest -> train -> write-back) scanned into "
                        "one device dispatch (env twin "
                        "APEX_STEPS_PER_DISPATCH); the host wakes once "
                        "per dispatch for publish/checkpoint/stats")
    # multi-tenant namespace (apex_tpu/tenancy): a whole tenant's roles
    # opt in with one env export (or this flag twin); everything — wire
    # identities, chunk ids, param topics, infer requests — qualifies
    # off it.  Unset = the default tenant, byte-identical single-tenant
    # behavior.  APEX_TENANTS (JSON roster) configures the SHARED
    # planes (replay/infer shards, tenant-ctl) with every tenant's
    # spec; see tenancy/namespace.py.
    p.add_argument("--tenant", default=e.get("APEX_TENANT", ""),
                   help="this process's tenant name (env twin "
                        "APEX_TENANT; empty = the default tenant t0)")
    # env
    p.add_argument("--env-id", default=e.get("APEX_ENV_ID",
                                             "SeaquestNoFrameskip-v4"))
    p.add_argument("--seed", type=int, default=int(e.get("APEX_SEED", 1122)))
    p.add_argument("--frame-stack", type=int, default=4)
    p.add_argument("--no-clip-rewards", action="store_true")
    p.add_argument("--no-episodic-life", action="store_true")
    # identity (env-var twins are the reference's names, actor.py:18-25;
    # RoleIdentity.from_env above is the canonical reader, flags win)
    p.add_argument("--actor-id", type=int, default=ident.actor_id)
    p.add_argument("--n-actors", type=int, default=ident.n_actors)
    p.add_argument("--n-envs-per-actor", type=int,
                   default=int(e.get("N_ENVS_PER_ACTOR", 1)),
                   help="env slots per actor process, driven through one "
                        "batched policy call; the exploration ladder spans "
                        "n_actors * n_envs_per_actor slots (8 x 32 = the "
                        "256-actor spectrum in 8 processes)")
    p.add_argument("--n-evaluators", type=int,
                   default=int(e.get("N_EVALUATORS", 1)))
    p.add_argument("--learner-ip", default=ident.learner_ip)
    # comms ports (env twins let topology tests / multi-fleet hosts remap
    # the whole plane without code changes)
    c = CommsConfig()
    p.add_argument("--batch-port", type=int,
                   default=int(e.get("APEX_BATCH_PORT", c.batch_port)))
    p.add_argument("--param-port", type=int,
                   default=int(e.get("APEX_PARAM_PORT", c.param_port)))
    p.add_argument("--barrier-port", type=int,
                   default=int(e.get("APEX_BARRIER_PORT", c.barrier_port)))
    p.add_argument("--status-port", type=int,
                   default=int(e.get("APEX_STATUS_PORT", c.status_port)))
    # sharded replay service (apex_tpu/replay_service): the whole fleet
    # must agree on these, so they ride the shared COMMON flag set / env
    # twins like the ports above
    p.add_argument("--replay-shards", type=int,
                   default=int(e.get("APEX_REPLAY_SHARDS",
                                     c.replay_shards)),
                   help="N > 0: run prioritized replay as N standalone "
                        "shard processes (--role replay); actors hash "
                        "chunks to shards, the learner pulls pre-sampled "
                        "batches.  0 (default) = in-learner replay")
    p.add_argument("--replay-port-base", type=int,
                   default=int(e.get("APEX_REPLAY_PORT_BASE",
                                     c.replay_port_base)),
                   help="shard s binds replay_port_base + s")
    p.add_argument("--replay-ip", default=ident.replay_ip,
                   help="host the replay shards run on (env twin "
                        "REPLAY_IP); defaults to localhost")
    p.add_argument("--shard-id", type=int,
                   default=int(e.get("SHARD_ID", 0)),
                   help="replay role: this process's shard index in "
                        "[0, replay_shards)")
    p.add_argument("--replay-loose", action="store_true",
                   default=_env_bool(e.get("APEX_REPLAY_LOOSE", "")),
                   help="loose shard ordering (reference semantics: "
                        "pre-sample ahead, apply write-backs whenever "
                        "they land) instead of the default strict "
                        "lockstep that is bit-identical to in-learner "
                        "replay at N=1")
    p.add_argument("--replay-snapshot-dir",
                   default=e.get("APEX_REPLAY_SNAPSHOT_DIR"),
                   help="replay role: restore the newest shard snapshot "
                        "from here on startup (warm respawn) and keep "
                        "snapshotting at --replay-snapshot-every")
    p.add_argument("--replay-snapshot-every", type=float,
                   default=float(e.get("APEX_REPLAY_SNAPSHOT_S")
                                 or c.replay_snapshot_s),
                   help="seconds between shard snapshots (atomic "
                        "write, quiescent points only); 0 = off")
    # centralized inference plane (apex_tpu/infer_service): the whole
    # fleet must agree on the endpoint, so it rides COMMON like the
    # replay-service flags above
    p.add_argument("--remote-policy", action="store_true",
                   default=_env_bool(e.get("APEX_REMOTE_POLICY", "")),
                   help="actors ship half-group observations to the "
                        "--role infer server (one batched device "
                        "dispatch across actor hosts) instead of "
                        "running the policy locally; the local policy "
                        "stays as the bit-identical fallback after "
                        "--infer-wait")
    p.add_argument("--infer-port", type=int,
                   default=int(e.get("APEX_INFER_PORT", c.infer_port)))
    p.add_argument("--infer-ip", default=e.get("APEX_INFER_IP",
                                               c.infer_ip),
                   help="host the infer server runs on (env twin "
                        "APEX_INFER_IP); defaults to localhost")
    p.add_argument("--infer-batch-max", type=int,
                   default=int(e.get("APEX_INFER_BATCH_MAX",
                                     c.infer_batch_max)),
                   help="max requests coalesced into one scan-stacked "
                        "dispatch (also the pow2 padding cap)")
    p.add_argument("--infer-window-ms", type=float,
                   default=float(e.get("APEX_INFER_WINDOW_MS")
                                 or c.infer_window_ms),
                   help="coalesce window opened by the first queued "
                        "request")
    p.add_argument("--infer-wait", type=float,
                   default=float(e.get("APEX_INFER_WAIT")
                                 or c.infer_wait_s),
                   help="actor-side reply timeout before the local-"
                        "policy fallback (a dead server costs this "
                        "once, then re-probes every --infer-reprobe)")
    p.add_argument("--infer-reprobe", type=float,
                   default=float(e.get("APEX_INFER_REPROBE")
                                 or c.infer_reprobe_s))
    p.add_argument("--infer-device-params", action="store_true",
                   default=_env_bool(e.get("APEX_INFER_DEVICE_PARAMS",
                                           "")),
                   help="keep the infer server's params device-placed "
                        "(device_put per publish — the d2d path on a "
                        "shared-device deployment); skipped on the CPU "
                        "backend")
    # sharded serving tier (apex_tpu/serving): shard count rides COMMON
    # (clients hash to shards, so the whole fleet must agree); the
    # serve-ctl knobs are controller-local
    p.add_argument("--infer-shards", type=int,
                   default=int(e.get("APEX_INFER_SHARDS",
                                     c.infer_shards)),
                   help="N infer servers, shard s binding infer_port+s; "
                        "remote-policy workers route by a stable "
                        "identity hash (1 = the single PR 9 server)")
    p.add_argument("--infer-shard-id", type=int,
                   default=int(e.get("INFER_SHARD_ID", 0)),
                   help="infer role: this process's shard index in "
                        "[0, infer_shards)")
    # wire codec (apex_tpu/runtime/codec.py): the chunk plane's byte
    # format + the sparse param publish.  Both ride COMMON in the deploy
    # scripts for uniform fleets, but receivers negotiate per chunk off
    # the wire tag, so MIXED fleets (one actor still on raw) are fine.
    p.add_argument("--wire-codec", choices=["raw", "delta", "dict"],
                   default=(e.get("APEX_WIRE_CODEC") or "").strip()
                   or "raw",
                   help="chunk wire codec: raw = legacy pickle "
                        "(bit-identical wire, default), delta = XOR "
                        "frame-delta + RLE (~sparse Catch frames), "
                        "dict = per-chunk deflate dictionary (pixel "
                        "stacks); env twin APEX_WIRE_CODEC")
    p.add_argument("--param-delta", action="store_true",
                   default=_env_bool(e.get("APEX_PARAM_DELTA", "")),
                   help="publish sparse per-leaf param deltas with "
                        "periodic keyframes (first publish and every "
                        "learner-epoch bump stay dense); env twin "
                        "APEX_PARAM_DELTA")
    p.add_argument("--param-keyframe-every", type=int,
                   default=int(e.get("APEX_PARAM_KEYFRAME_EVERY")
                               or c.param_keyframe_every),
                   help="dense keyframe at least every N publishes in "
                        "--param-delta mode")
    p.add_argument("--serve-canary-frac", type=float,
                   default=float(e.get("APEX_SERVE_CANARY_FRAC") or 0.5),
                   help="serve-ctl: fraction of shards canarying a new "
                        "model version (lowest indices; the rest pin "
                        "the incumbent)")
    p.add_argument("--serve-soak", type=float,
                   default=float(e.get("APEX_SERVE_SOAK_S") or 60.0),
                   help="serve-ctl: seconds the canary's eval-score and "
                        "round-trip SLOs must hold before fleet-wide "
                        "promotion")
    p.add_argument("--serve-version-every", type=int,
                   default=int(e.get("APEX_SERVE_VERSION_EVERY") or 0),
                   help="serve-ctl: minimum param-version spacing "
                        "between deployments within one learner epoch "
                        "(0 = deploy on epoch changes only)")
    p.add_argument("--serve-interval", type=float,
                   default=float(e.get("APEX_SERVE_INTERVAL_S") or 5.0),
                   help="serve-ctl: seconds between control rounds "
                        "(learner probe + shard reconcile)")
    # population plane (apex_tpu/population): pbt-ctl decision knobs.
    # The lineage roster itself rides APEX_POPULATION (JSON list of
    # LineageSpec dicts) — env-only like APEX_TENANTS, so every
    # shared-plane process and the controller load the same one.
    p.add_argument("--pbt-decide", type=float,
                   default=float(e.get("APEX_PBT_DECIDE_S") or 30.0),
                   help="pbt-ctl: seconds between exploit/explore "
                        "decision rounds (probes keep the "
                        "--serve-interval cadence)")
    p.add_argument("--pbt-frac", type=float,
                   default=float(e.get("APEX_PBT_FRAC") or 0.25),
                   help="pbt-ctl: truncation fraction — the bottom "
                        "frac of each task ladder restores the top "
                        "frac's checkpoint (>= 1 lineage each)")
    p.add_argument("--pbt-resample", type=float,
                   default=float(e.get("APEX_PBT_RESAMPLE") or 0.25),
                   help="pbt-ctl: per-field probability explore "
                        "resamples from the hyperparameter band "
                        "instead of perturbing x0.8/x1.2")
    p.add_argument("--pbt-min-episodes", type=int,
                   default=int(e.get("APEX_PBT_MIN_EPISODES") or 4),
                   help="pbt-ctl: eval episodes a lineage needs behind "
                        "its score before selection judges it")
    # fleet control-plane thresholds (apex_tpu/fleet): heartbeat cadence
    # and the registry/park state-machine windows — env twins so a whole
    # topology (tests, chaos drills) retunes them without flag plumbing
    p.add_argument("--heartbeat-interval", type=float,
                   default=float(e.get("APEX_HEARTBEAT_INTERVAL",
                                       c.heartbeat_interval_s)))
    p.add_argument("--suspect-after", type=float,
                   default=float(e.get("APEX_SUSPECT_AFTER",
                                       c.suspect_after_s)))
    p.add_argument("--dead-after", type=float,
                   default=float(e.get("APEX_DEAD_AFTER", c.dead_after_s)))
    p.add_argument("--park-after", type=float,
                   default=float(e.get("APEX_PARK_AFTER", c.park_after_s)))
    # learner
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--lr", type=float, default=6.25e-5)
    p.add_argument("--lr-decay-steps", type=int,
                   default=int(e.get("APEX_LR_DECAY_STEPS", 1000)),
                   help="StepLR parity (DQN.py:39): lr *= rate every this "
                        "many learner steps; 0 = constant lr")
    p.add_argument("--lr-decay-rate", type=float,
                   default=float(e.get("APEX_LR_DECAY_RATE", 0.99)))
    p.add_argument("--gamma", type=float, default=0.99)
    p.add_argument("--n-steps", type=int, default=3)
    p.add_argument("--target-update-interval", type=int, default=2500)
    p.add_argument("--save-interval", type=int,
                   default=int(e.get("APEX_SAVE_INTERVAL", 5000)),
                   help="learner steps between checkpoints (env twin "
                        "APEX_SAVE_INTERVAL — PBT fleets compress it so "
                        "donor checkpoints exist early)")
    p.add_argument("--mesh-dp", type=int,
                   default=int(e.get("APEX_MESH_DP", 0)),
                   help="learner dp mesh degree: shard the replay across "
                        "this many chips with pmean gradient sync; 0 = all "
                        "local devices (learner/apex roles), 1 = single "
                        "chip")
    p.add_argument("--total-steps", type=int, default=1_000_000)
    p.add_argument("--total-frames", type=int, default=1_000_000)
    p.add_argument("--max-seconds", type=float, default=86400.0)
    p.add_argument("--train-ratio", type=float, default=None)
    p.add_argument("--min-train-ratio", type=float, default=None)
    # replay
    p.add_argument("--capacity", type=int, default=2 ** 19)
    p.add_argument("--warmup", type=int, default=50_000)
    p.add_argument("--alpha", type=float, default=0.6)
    p.add_argument("--beta", type=float, default=0.4)
    # observability (apex_tpu/obs)
    p.add_argument("--metrics", action="store_true",
                   help="status role: print the Prometheus text "
                        "exposition (scalars, rates, fleet, latency "
                        "histograms) instead of the fleet table — one "
                        "REQ round-trip to the learner's status server")
    p.add_argument("--http", type=int,
                   default=int(e.get("APEX_METRICS_HTTP", 0)),
                   help="status role with --metrics: serve the "
                        "exposition over plain HTTP on this port (GET "
                        "/metrics proxies one zmq round-trip per "
                        "scrape) so a stock Prometheus server can poll "
                        "directly; 0 = one-shot print")
    p.add_argument("--trace-dir", default=e.get("APEX_TRACE_DIR"),
                   help="enable the per-role trace ring and dump Chrome "
                        "trace-event JSON here (atexit/periodic/SIGUSR2); "
                        "merge a fleet's dumps with "
                        "`python -m apex_tpu.obs.merge DIR`")
    # misc
    p.add_argument("--logdir", default=e.get("APEX_LOGDIR"))
    p.add_argument("--profile-dir", default=e.get("APEX_PROFILE_DIR"),
                   help="capture a jax.profiler (XProf) trace of the "
                        "learner run into this directory")
    p.add_argument("--checkpoint-dir", default=e.get("APEX_CKPT_DIR"))
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint path (enjoy role)")
    p.add_argument("--restore", action=argparse.BooleanOptionalAction,
                   default=_env_bool(e.get("APEX_RESTORE", "")),
                   help="resume the learner from the newest checkpoint in "
                        "--checkpoint-dir before training (bit-exact "
                        "learner state; actors re-sync from the first "
                        "post-restore publish); --no-restore overrides the "
                        "APEX_RESTORE env var")
    p.add_argument("--episodes", type=int, default=0,
                   help="evaluator/enjoy episode budget (0 = forever)")
    p.add_argument("--render", choices=["ascii", "save"], default=None,
                   help="enjoy role: terminal ASCII rendering, or capture "
                        "observations to --render-dir as per-episode .npy "
                        "stacks (enjoy.py:29-48 on headless hosts)")
    p.add_argument("--render-dir", default=e.get("APEX_RENDER_DIR"))
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--barrier-timeout", type=float, default=120.0)
    return p


def _mesh_shape(args: argparse.Namespace) -> tuple[int, ...]:
    """dp degree for the learner mesh; 0 = every local device (only the
    learner-side roles initialize jax to count them)."""
    dp = args.mesh_dp
    if dp == 0:
        if args.role in ("learner", "apex"):
            import jax
            dp = len(jax.devices())
        else:
            dp = 1
    return (dp,)


def config_from_args(args: argparse.Namespace) -> ApexConfig:
    return ApexConfig(
        env=EnvConfig(env_id=args.env_id, seed=args.seed,
                      frame_stack=args.frame_stack,
                      clip_rewards=not args.no_clip_rewards,
                      episodic_life=not args.no_episodic_life),
        replay=ReplayConfig(capacity=args.capacity, warmup=args.warmup,
                            alpha=args.alpha, beta=args.beta),
        learner=LearnerConfig(batch_size=args.batch_size, lr=args.lr,
                              lr_decay_steps=args.lr_decay_steps,
                              lr_decay_rate=args.lr_decay_rate,
                              gamma=args.gamma, n_steps=args.n_steps,
                              target_update_interval=
                              args.target_update_interval,
                              save_interval=args.save_interval,
                              mesh_shape=_mesh_shape(args)),
        actor=ActorConfig(n_actors=args.n_actors,
                          n_envs_per_actor=args.n_envs_per_actor,
                          remote_policy=args.remote_policy),
        aql=AQLConfig(),
        comms=CommsConfig(batch_port=args.batch_port,
                          param_port=args.param_port,
                          barrier_port=args.barrier_port,
                          status_port=args.status_port,
                          heartbeat_interval_s=args.heartbeat_interval,
                          suspect_after_s=args.suspect_after,
                          dead_after_s=args.dead_after,
                          park_after_s=args.park_after,
                          replay_shards=args.replay_shards,
                          replay_port_base=args.replay_port_base,
                          replay_ip=args.replay_ip,
                          replay_strict_order=not args.replay_loose,
                          replay_snapshot_s=args.replay_snapshot_every,
                          infer_port=args.infer_port,
                          infer_ip=args.infer_ip,
                          infer_batch_max=args.infer_batch_max,
                          infer_window_ms=args.infer_window_ms,
                          infer_wait_s=args.infer_wait,
                          infer_reprobe_s=args.infer_reprobe,
                          infer_device_params=args.infer_device_params,
                          infer_shards=args.infer_shards,
                          wire_codec=args.wire_codec,
                          param_delta=args.param_delta,
                          param_keyframe_every=args.param_keyframe_every),
    )


def identity_from_args(args: argparse.Namespace) -> RoleIdentity:
    return RoleIdentity(role=args.role, actor_id=args.actor_id,
                        n_actors=args.n_actors, learner_ip=args.learner_ip,
                        replay_ip=args.replay_ip)


def main(argv: list[str] | None = None) -> int:
    import contextlib

    args = build_parser().parse_args(argv)
    if args.restore and not args.checkpoint_dir:
        raise SystemExit("--restore requires --checkpoint-dir")
    if args.trace_dir:
        # the trace ring reads the env at creation; the flag is its twin
        # (exporting here also covers worker processes, which inherit it)
        os.environ["APEX_TRACE_DIR"] = args.trace_dir
    if args.tenant:
        # the tenant namespace reads the env at each qualification site
        # (tenancy/namespace.current_tenant); exporting here covers the
        # worker processes too, exactly like the trace dir
        os.environ["APEX_TENANT"] = args.tenant
    cfg = config_from_args(args)
    # population lineage dispatch (apex_tpu/population): a tenant that
    # names an APEX_POPULATION roster lineage adopts ITS env id and
    # hyperparameter vector — make_env/make_jax_env (host and ondevice
    # rollout paths), the n-step chunk assembly, the priority
    # exponents, and the epsilon ladder all dispatch per lineage off
    # the one roster.  No roster entry (or a no-override one) leaves
    # the config untouched: population-of-1 is a plain run.
    from apex_tpu.population.lineage import load_population
    population = load_population()
    if population:
        from apex_tpu.population.lineage import apply_lineage
        from apex_tpu.tenancy import namespace as tenancy_ns
        lineage = population.get(tenancy_ns.current_tenant())
        if lineage is not None:
            cfg = apply_lineage(cfg, lineage)
    identity = identity_from_args(args)

    if args.profile_dir and args.role in ("learner", "apex", "dqn", "aql",
                                          "r2d2"):
        from apex_tpu.utils.profiling import trace
        profile_ctx = trace(args.profile_dir)
    else:
        profile_ctx = contextlib.nullcontext()

    with profile_ctx:
        return _dispatch(args, cfg, identity)


def _dispatch(args: argparse.Namespace, cfg: ApexConfig,
              identity: RoleIdentity) -> int:
    if args.role == "learner":
        from apex_tpu.runtime.roles import run_learner
        run_learner(cfg, n_peers=args.n_actors + args.n_evaluators,
                    total_steps=args.total_steps,
                    max_seconds=args.max_seconds, family=args.family,
                    logdir=args.logdir, verbose=args.verbose,
                    checkpoint_dir=args.checkpoint_dir,
                    train_ratio=args.train_ratio,
                    min_train_ratio=args.min_train_ratio,
                    barrier_timeout_s=args.barrier_timeout,
                    restore=args.restore, rollout=args.rollout,
                    rollout_len=args.rollout_len or None,
                    steps_per_dispatch=args.steps_per_dispatch)
    elif args.role == "loadgen":
        # standalone on-device rollout fleet (training/anakin.py): ships
        # device-rate sealed chunks at the learner / replay shards — the
        # synthetic heavy traffic the scale planes are measured against.
        # Skips the startup barrier like replay/infer roles: it acts the
        # moment the first param publish lands.
        from apex_tpu.runtime.roles import run_loadgen
        run_loadgen(cfg, identity, family=args.family,
                    max_seconds=args.max_seconds,
                    rollout_len=args.rollout_len or None)
    elif args.role == "actor":
        from apex_tpu.runtime.roles import run_actor
        run_actor(cfg, identity, family=args.family,
                  barrier_timeout_s=args.barrier_timeout)
    elif args.role == "evaluator":
        from apex_tpu.runtime.roles import run_evaluator
        run_evaluator(cfg, identity, family=args.family,
                      episodes=args.episodes, logdir=args.logdir,
                      verbose=args.verbose,
                      barrier_timeout_s=args.barrier_timeout)
    elif args.role == "replay":
        # one prioritized-replay shard (apex_tpu/replay_service): binds
        # replay_port_base + shard_id, serves until killed/--max-seconds.
        # Shards skip the startup barrier — the learner counts only
        # actors/evaluators there, and a shard is useful the moment its
        # ROUTER binds.
        if not 0 <= args.shard_id < max(1, cfg.comms.replay_shards):
            raise SystemExit(
                f"--shard-id {args.shard_id} outside [0, "
                f"{cfg.comms.replay_shards}) — set --replay-shards/"
                f"APEX_REPLAY_SHARDS fleet-wide")
        from apex_tpu.replay_service.service import run_replay_shard
        from apex_tpu.runtime.roles import _with_ips
        cfg = cfg.replace(comms=_with_ips(cfg.comms, identity))
        run_replay_shard(cfg, args.shard_id, family=args.family,
                         max_seconds=args.max_seconds,
                         snapshot_dir=args.replay_snapshot_dir)
    elif args.role == "infer":
        # one batched-inference shard (apex_tpu/infer_service +
        # apex_tpu/serving): binds infer_port + shard id, subscribes the
        # learner's param channel, serves its hashed worker band until
        # killed / --max-seconds.  Skips the startup barrier like replay
        # shards — actors act locally until it answers, so launch order
        # is free.
        from apex_tpu.infer_service.service import run_infer_server
        from apex_tpu.runtime.roles import _with_ips
        cfg = cfg.replace(comms=_with_ips(cfg.comms, identity))
        run_infer_server(cfg, family=args.family,
                         server_id=args.infer_shard_id,
                         max_seconds=args.max_seconds)
    elif args.role == "serve-ctl":
        # the serving tier's deployment controller (apex_tpu/serving/
        # deploy): canaries new model versions onto a shard fraction,
        # promotes on healthy SLO soak, rolls back by epoch on breach.
        # Skips the barrier — it holds until the learner's status port
        # answers.
        from apex_tpu.runtime.roles import _with_ips
        from apex_tpu.serving.deploy import run_serve_ctl
        cfg = cfg.replace(comms=_with_ips(cfg.comms, identity))
        run_serve_ctl(cfg, identity,
                      canary_frac=args.serve_canary_frac,
                      soak_s=args.serve_soak,
                      version_every=args.serve_version_every,
                      interval_s=args.serve_interval,
                      max_seconds=args.max_seconds)
    elif args.role == "tenant-ctl":
        # the multi-tenant placement controller (apex_tpu/tenancy/
        # scheduler): admits the APEX_TENANTS roster, assigns weighted
        # replay/infer shard bands, probes each tenant's learner, and
        # evicts/rebalances on death.  Skips the barrier like the other
        # controllers.
        from apex_tpu.runtime.roles import _with_ips
        from apex_tpu.tenancy.scheduler import run_tenant_ctl
        cfg = cfg.replace(comms=_with_ips(cfg.comms, identity))
        run_tenant_ctl(cfg, interval_s=args.serve_interval,
                       max_seconds=args.max_seconds)
    elif args.role == "pbt-ctl":
        # the population-based-training controller (apex_tpu/population/
        # controller): probes each APEX_POPULATION lineage's learner,
        # runs truncation-selection exploit (donor checkpoint copy +
        # epoch bump via the learner ctl surface) and perturb/resample
        # explore per task ladder.  Skips the barrier like the other
        # controllers.
        from apex_tpu.population.controller import run_pbt_ctl
        from apex_tpu.runtime.roles import _with_ips
        cfg = cfg.replace(comms=_with_ips(cfg.comms, identity))
        run_pbt_ctl(cfg, interval_s=args.serve_interval,
                    decide_every_s=args.pbt_decide,
                    frac=args.pbt_frac,
                    resample_prob=args.pbt_resample,
                    min_episodes=args.pbt_min_episodes,
                    max_seconds=args.max_seconds)
    elif args.role == "status":
        # operator surface: one REQ round-trip to the learner's fleet
        # status server — the live membership table, or (--metrics) the
        # Prometheus text exposition for standard scrape tooling
        if args.metrics:
            if args.http:
                # plain-HTTP Prometheus sidecar: a stock Prometheus
                # server polls GET /metrics; each scrape proxies one zmq
                # REQ round-trip to the learner's status server
                from apex_tpu.obs.metrics import make_http_sidecar
                server = make_http_sidecar(cfg.comms, port=args.http,
                                           learner_ip=args.learner_ip)
                print(f"metrics sidecar: http://0.0.0.0:{args.http}"
                      f"/metrics -> zmq {args.learner_ip}:"
                      f"{cfg.comms.status_port}", flush=True)
                try:
                    server.serve_forever()
                except KeyboardInterrupt:
                    pass
                finally:
                    server.server_close()
                return 0
            from apex_tpu.obs.metrics import metrics_request
            text = metrics_request(cfg.comms, learner_ip=args.learner_ip)
            if text is None:
                print(f"no metrics from {args.learner_ip}:"
                      f"{cfg.comms.status_port} (learner not running, or "
                      f"an in-host trainer with no status server)")
                return 1
            print(text, end="")
            return 0
        from apex_tpu.fleet.registry import format_fleet_table, \
            status_request
        snap = status_request(cfg.comms, learner_ip=args.learner_ip)
        if snap is None:
            print(f"no fleet status from {args.learner_ip}:"
                  f"{cfg.comms.status_port} (learner not running, or "
                  f"an in-host trainer with no status server)")
            return 1
        print(format_fleet_table(snap))
    elif args.role in ("dqn", "aql", "r2d2", "apex"):
        # single-host drivers share one construct -> restore? -> train path
        if args.role == "dqn":
            from apex_tpu.training.dqn import DQNTrainer as trainer_cls
            extra, train_kw = {}, dict(total_frames=args.total_frames)
        elif args.role == "r2d2":
            from apex_tpu.training.r2d2 import R2D2Trainer as trainer_cls
            extra, train_kw = {}, dict(total_frames=args.total_frames)
        elif args.role == "aql":
            from apex_tpu.training.aql import AQLTrainer as trainer_cls
            extra, train_kw = {}, dict(total_frames=args.total_frames)
        else:
            if args.family == "aql":
                from apex_tpu.training.aql import \
                    AQLApexTrainer as trainer_cls
            elif args.family == "r2d2":
                from apex_tpu.training.r2d2 import \
                    R2D2ApexTrainer as trainer_cls
            else:
                from apex_tpu.training.apex import \
                    ApexTrainer as trainer_cls
            extra = dict(train_ratio=args.train_ratio,
                         min_train_ratio=args.min_train_ratio)
            if args.rollout == "fused":
                # the whole rollout -> ingest -> sample -> train ->
                # write-back cycle as one device program per dispatch
                # (apex_tpu/ondevice), sharded over the --mesh-dp axis;
                # make_jax_env's ValueError names non-jittable env ids,
                # the divisibility guards name --n-envs-per-actor /
                # --batch-size vs --mesh-dp, and the family gate fails
                # loud before construction
                if args.family != "dqn":
                    raise NotImplementedError(
                        f"--rollout fused currently serves the dqn "
                        f"family only (got {args.family!r}) — aql/r2d2 "
                        f"slot in behind the same scan hooks "
                        f"(ROADMAP.md)")
                from apex_tpu.ondevice.fused import FusedApexTrainer
                trainer_cls = FusedApexTrainer
                extra["rollout_len"] = args.rollout_len or None
                extra["steps_per_dispatch"] = args.steps_per_dispatch
            elif args.rollout == "ondevice":
                # co-located Anakin rollouts replace the actor processes;
                # make_jax_env raises a ValueError naming non-jittable
                # env ids, and the family gate fails loud before any
                # trainer construction
                if args.family != "dqn":
                    raise NotImplementedError(
                        f"--rollout ondevice currently serves the dqn "
                        f"family only (got {args.family!r}) — aql/r2d2 "
                        f"stay on the host pipeline (ROADMAP.md)")
                from apex_tpu.training.anakin import (AnakinPool,
                                                      make_anakin_engine)
                engine = make_anakin_engine(
                    cfg, rollout_len=args.rollout_len or None)
                extra["pool"] = AnakinPool(cfg, engine)
            train_kw = dict(total_steps=args.total_steps,
                            max_seconds=args.max_seconds)
        t = trainer_cls(cfg, logdir=args.logdir, verbose=args.verbose,
                        checkpoint_dir=args.checkpoint_dir, **extra)
        if args.restore:
            t.restore()
        t.train(**train_kw)
    elif args.role == "enjoy":
        from apex_tpu.training.checkpoint import evaluate_checkpoint
        if not args.checkpoint:
            raise SystemExit("--checkpoint required for enjoy")
        hook = None
        if args.render:
            if args.render == "save" and not args.render_dir:
                raise SystemExit("--render save requires --render-dir")
            from apex_tpu.utils.render import make_render_hook
            hook = make_render_hook(args.render, args.render_dir)
        score = evaluate_checkpoint(args.checkpoint,
                                    episodes=args.episodes or 10,
                                    render_hook=hook)
        print(f"enjoy: mean episode reward {score:.2f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
