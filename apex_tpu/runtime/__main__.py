from apex_tpu.runtime.cli import main

raise SystemExit(main())
