"""Wire codec — the ONE place bytes get smaller (namespace.py's
discipline applied to the data plane).

Every chunk the fleet ships today is ``pickle.dumps(("chunk", msg))`` of
raw uint8 frames (transport.py), and every param publish is the full
dense tree — so at fleet scale the wire, not the chips, is the
bottleneck.  This module owns all compression/decompression and
frame-delta arithmetic on wire payloads; apexlint J023
(``codec-outside-codec-module``) keeps it that way, exactly like J00x
keeps tenant-key derivation inside tenancy/namespace.py.

Chunk wire format
-----------------
``encode_chunk(msg, codec)`` returns the zmq payload plus (raw, wire)
byte counts.  Three codecs, negotiated PER CHUNK by the kind tag on the
wire — no handshake, so mixed-version fleets interoperate:

==========  ==========================================================
``raw``     ``("chunk", msg)`` — byte-identical to the historical wire;
            the default, and what every pre-codec peer speaks.
``delta``   ``("chunkc", enc)`` — per-frame XOR delta vs the previous
            frame in the chunk + run-length coding; built for the
            ~sparse binary Catch frames where successive frames differ
            in a handful of bytes.
``dict``    ``("chunkc", enc)`` — raw-deflate with the chunk's first
            frame as the compression dictionary; built for 84x84 pixel
            stacks where the 3/4 stack overlap frame_pool.py dedups
            device-side is still redundant on the wire.
==========  ==========================================================

Only the ``n_frames``/``n_trans`` real rows are encoded — pad rows
(repeat-last, the ``pad_to`` convention in replay/frame_chunks.py) cost
zero wire bytes and are regrown bit-exactly on decode.  A CRC over the
full padded frame block is carried and verified, so a decoded chunk is
BYTE-identical to its pre-encode form or it is rejected
(:class:`CodecError`) — counted and dropped unacked by the receivers,
like PR 5's RestrictedUnpickler.  When a compressed chunk would be
*larger* than raw (adversarial entropy, tiny chunks), the encoder ships
the legacy raw payload instead: compression never loses.

Param-delta publish
-------------------
``diff_tree``/``apply_delta``/``tree_checksum`` back ParamPublisher's
sparse-delta mode: deltas carry only the leaves whose bytes changed
since the last *keyframe* (not the last publish — the param SUB socket
is CONFLATE, so any intermediate frame may be dropped; keyframe-based
deltas stay applicable no matter how many the subscriber missed).
Subscribers reassemble against their stored keyframe and verify the
tree checksum; on mismatch (or a missed keyframe) they drop the frame
and send :class:`KeyframeRequest` up the stat plane, and the trainer
forces the next publish to be dense.  The first publish and every epoch
bump are always keyframes, so PBT/deploy fencing semantics are
untouched.
"""

from __future__ import annotations

import dataclasses
import pickle
import struct
import zlib
from collections.abc import Mapping

import numpy as np

#: Codec ids the sender may be configured with.
CODECS = ("raw", "delta", "dict")

#: Wire format version inside ``("chunkc", enc)`` bodies.  A receiver
#: that sees a newer version rejects the chunk (counted, unacked) —
#: the sender's negotiation fallback is "speak raw", never "guess".
WIRE_VERSION = 1

#: zlib external-dictionary cap (bytes beyond 32 KiB are ignored by
#: deflate; slicing keeps the *last* window, the part deflate matches).
_ZDICT_MAX = 32768


class CodecError(Exception):
    """Hostile, garbage, or version-unknown codec payload — the decode
    analogue of wire.WireRejected: count it, drop it, never ack it."""


def resolve_codec(name: str | None) -> str:
    """Effective codec id: explicit arg > ``APEX_WIRE_CODEC`` env twin >
    ``raw``.  Unknown names raise rather than silently shipping raw."""
    import os

    got = (name or "").strip() or os.environ.get("APEX_WIRE_CODEC", "").strip()
    got = got or "raw"
    if got not in CODECS:
        raise ValueError(
            f"unknown wire codec {got!r}: expected one of {CODECS}")
    return got


@dataclasses.dataclass(frozen=True)
class KeyframeRequest:
    """Stat-plane ask from a subscriber that could not apply a param
    delta (checksum mismatch or missed keyframe): the trainer answers
    by forcing the next publish dense.  Rides the existing chunk-plane
    ``("stat", obj)`` path; allowlisted in runtime/wire.py."""

    identity: str
    version_seen: int = -1


# -- run-length layer (delta codec) -----------------------------------------
#
# Tagged blob: b"\x00" + literal bytes (RLE would not have helped), or
# b"\x01" + <u64 total><u32 nruns> + nruns value bytes + nruns u32
# lengths.  Vectorized both ways; a Catch XOR-delta plane is almost all
# zero bytes, so runs are few and long.


def _rle_encode(b) -> bytes:
    """``b``: bytes or a flat uint8 array (no copy taken either way)."""
    a = b if isinstance(b, np.ndarray) else np.frombuffer(b, np.uint8)
    if a.size == 0:
        return b"\x00"
    idx = np.flatnonzero(a[1:] != a[:-1])
    starts = np.empty(idx.size + 1, np.int64)
    starts[0] = 0
    starts[1:] = idx + 1
    lengths = np.diff(np.append(starts, a.size)).astype(np.uint32)
    out = (b"\x01" + struct.pack("<QI", a.size, starts.size)
           + a[starts].tobytes() + lengths.tobytes())
    if len(out) >= a.size + 1:
        return b"\x00" + a.tobytes()
    return out


def _rle_decode(blob: bytes) -> np.ndarray:
    """-> writable uint8 array (decode mutates it in place downstream)."""
    tag = blob[:1]
    if tag == b"\x00":
        return np.frombuffer(blob, np.uint8, offset=1).copy()
    if tag != b"\x01":
        raise CodecError(f"bad RLE tag {tag!r}")
    if len(blob) < 13:
        raise CodecError("truncated RLE header")
    total, nruns = struct.unpack_from("<QI", blob, 1)
    if total > 1 << 32 or nruns > total:
        raise CodecError("implausible RLE geometry")
    if len(blob) != 13 + nruns * 5:
        raise CodecError("RLE body length mismatch")
    vals = np.frombuffer(blob, np.uint8, nruns, 13)
    lens = np.frombuffer(blob, np.uint32, nruns, 13 + nruns)
    out = np.repeat(vals, lens)
    if out.size != total:
        raise CodecError("RLE run lengths do not sum to total")
    return out


# -- frame-block codecs ------------------------------------------------------


def _frames_encode(rows: np.ndarray, codec: str) -> bytes:
    """Encode a (n, *frame_shape) block of real frame rows."""
    flat = np.ascontiguousarray(rows).view(np.uint8).reshape(
        rows.shape[0], -1)
    if codec == "delta":
        d = flat.copy()
        d[1:] ^= flat[:-1]
        return _rle_encode(d.reshape(-1))
    if codec == "dict":
        # The chunk's first frame IS the dictionary.  It ships as its
        # own deflate preamble (no external dict — that's the decoder's
        # bootstrap), then the remaining rows deflate against it, so
        # every stack-overlap byte in the chunk matches the dictionary
        # instead of riding the wire again.
        zd = flat[0].tobytes()
        head = zlib.compress(zd, 6)
        co = zlib.compressobj(6, zlib.DEFLATED, -15, 9,
                              zlib.Z_DEFAULT_STRATEGY, zd[-_ZDICT_MAX:])
        body = co.compress(flat[1:].tobytes()) + co.flush()
        return struct.pack("<I", len(head)) + head + body
    raise CodecError(f"unknown frame codec {codec!r}")


def _frames_decode(blob: bytes, codec: str, n: int,
                   row_nbytes: int) -> np.ndarray:
    """Inverse of :func:`_frames_encode` -> (n, row_nbytes) uint8."""
    if codec == "delta":
        d = _rle_decode(blob)
        if d.size != n * row_nbytes:
            raise CodecError("delta frame block size mismatch")
        d = d.reshape(n, row_nbytes)
        # XOR-accumulate down rows is the exact inverse of the
        # previous-frame delta: row[i] = d[0] ^ ... ^ d[i].  Explicit row
        # loop on purpose: ufunc.accumulate takes a generic strided path
        # ~10x slower than n-1 contiguous row XORs (measured in part 1g).
        for i in range(1, n):
            np.bitwise_xor(d[i], d[i - 1], out=d[i])
        return d
    if codec == "dict":
        if len(blob) < 4:
            raise CodecError("truncated dict frame block")
        (head_len,) = struct.unpack_from("<I", blob, 0)
        if head_len > len(blob) - 4:
            raise CodecError("dict preamble length mismatch")
        zd = zlib.decompress(blob[4:4 + head_len])
        if len(zd) != row_nbytes:
            raise CodecError("dict dictionary row size mismatch")
        do = zlib.decompressobj(-15, zd[-_ZDICT_MAX:])
        rest = (do.decompress(blob[4 + head_len:], (n - 1) * row_nbytes)
                + do.flush())
        if len(rest) != (n - 1) * row_nbytes:
            raise CodecError("dict frame block size mismatch")
        out = np.empty((n, row_nbytes), np.uint8)
        out[0] = np.frombuffer(zd, np.uint8)
        out[1:] = np.frombuffer(rest, np.uint8).reshape(-1, row_nbytes)
        return out
    raise CodecError(f"unknown frame codec {codec!r}")


# -- chunk pack/unpack -------------------------------------------------------
#
# Column specs are small tagged tuples (tuple/dict/bytes/ndarray only —
# everything the restricted unpickler already admits):
#   ("arr", shipped, total_rows)   real rows only; re-pad repeat-last
#   ("all", array)                 shipped whole (pad rows not repeat-last)
#   ("raw", value)                 non-array passthrough (ids, spans, ints)
#   ("map", {name: spec})          nested dict (chunk extras)
#   ("frm", blob, n, total, shape, dtype, crc)  frame block (crc of blob)


def _pad_check(v: np.ndarray, n: int) -> bool:
    """True when rows past ``n`` follow frame_chunks.pad_to's
    repeat-last convention (so decode can regrow them bit-exactly)."""
    return n >= v.shape[0] or bool((v[n:] == v[n - 1]).all())


def _repad(shipped: np.ndarray, total: int) -> np.ndarray:
    if shipped.shape[0] >= total:
        return shipped
    return np.concatenate(
        [shipped, np.repeat(shipped[-1:], total - shipped.shape[0],
                            axis=0)])


def _pack_col(v, n_trans: int, k: int):
    if not isinstance(v, np.ndarray) or v.ndim == 0:
        return ("raw", v)
    if v.shape[0] == k and _pad_check(v, n_trans):
        return ("arr", np.ascontiguousarray(v[:n_trans]), k)
    return ("all", v)


def _canon(v):
    """Byte-parity detail: numpy 2.x unpickles arrays with a FRESH dtype
    object where in-process arrays share the interned singleton, so a
    re-pickle of a decoded chunk would miss the memo hit the original
    gets and differ by a few bytes.  Rebind simple dtypes to their
    singleton (structured/object dtypes pass through untouched)."""
    if isinstance(v, np.ndarray) and not v.dtype.hasobject:
        try:
            dt = np.dtype(v.dtype.str)
        except TypeError:
            return v
        if dt == v.dtype:
            return v.view(dt)
    return v


def _unpack_col(spec):
    tag = spec[0]
    if tag == "raw":
        return _canon(spec[1])
    if tag == "all":
        return _canon(spec[1])
    if tag == "arr":
        _, shipped, total = spec
        if not isinstance(shipped, np.ndarray) or shipped.ndim == 0:
            raise CodecError("arr spec without array body")
        total = int(total)
        if not 1 <= shipped.shape[0] <= total <= 1 << 20:
            raise CodecError("implausible column geometry")
        return _repad(_canon(shipped), total)
    raise CodecError(f"unknown column spec {tag!r}")


def _pack_frames(frames: np.ndarray, n_frames: int, codec: str):
    kf = frames.shape[0]
    rows = frames[:n_frames] if _pad_check(frames, n_frames) else frames
    blob = _frames_encode(rows, codec)
    # crc over the WIRE blob (not the decoded frames): integrity of what
    # actually rode the network, at compressed-size cost — reconstruction
    # correctness is pinned bit-exactly by tests/test_codec.py, and a
    # plaintext crc was ~30% of both encode and decode in part 1g
    return ("frm", blob, rows.shape[0], kf, tuple(frames.shape[1:]),
            str(frames.dtype), zlib.crc32(blob))


def _unpack_frames(spec, codec: str) -> np.ndarray:
    if spec[0] != "frm" or len(spec) != 7:
        raise CodecError("bad frame spec")
    _, blob, n, kf, shape, dtype, crc = spec
    n, kf = int(n), int(kf)
    if not 1 <= n <= kf <= 1 << 20:
        raise CodecError("implausible frame geometry")
    dt = np.dtype(dtype)
    row_nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    if not 0 < row_nbytes <= 1 << 26:
        raise CodecError("implausible frame row size")
    blob = bytes(blob)
    if zlib.crc32(blob) != int(crc):
        raise CodecError("frame block checksum mismatch")
    flat = _frames_decode(blob, codec, n, row_nbytes)
    rows = flat.view(dt).reshape((n,) + tuple(int(s) for s in shape))
    return _repad(rows, kf)


def _array_bytes(v) -> int:
    """Cheap lower bound on a value's pickled size: its ndarray payload
    bytes (a pickle of the same tree is always at least this big)."""
    if isinstance(v, np.ndarray):
        return v.nbytes
    if isinstance(v, dict):
        return sum(_array_bytes(x) for x in v.values())
    return 0


def encode_chunk(msg: dict, codec: str = "raw") -> tuple[bytes, int, int]:
    """Chunk msg -> (zmq payload, raw_bytes, wire_bytes).

    ``raw`` returns exactly the historical ``("chunk", msg)`` pickle —
    bit-untouched.  ``delta``/``dict`` return ``("chunkc", enc)`` unless
    the encoded form would be larger (or the chunk shape defeats the
    encoder), in which case the raw payload ships: per-chunk
    negotiation, compression never loses.

    ``raw_bytes`` is the raw pickle's length — except on the clear-win
    fast path (wire at most half the chunk's array bytes), where the
    raw pickle is never built and its ARRAY-BYTES LOWER BOUND is
    reported instead: the codec_ratio gauge reads slightly conservative
    there, and the encoder skips a serialization that would only have
    been thrown away (it was ~30% of delta encode cost in part 1g).
    """
    if codec == "raw":
        raw = pickle.dumps(("chunk", msg), protocol=5)
        return raw, len(raw), len(raw)
    if codec not in CODECS:
        raise ValueError(f"unknown wire codec {codec!r}")
    try:
        payload = msg["payload"]
        n_frames = int(payload["n_frames"])
        n_trans = int(payload["n_trans"])
        k = int(payload["action"].shape[0])
        if not (1 <= n_frames <= payload["frames"].shape[0]
                and 1 <= n_trans <= k):
            raise CodecError("chunk row counts out of range")
        cols = {}
        for key, v in payload.items():
            if key == "frames":
                cols[key] = _pack_frames(v, n_frames, codec)
            elif key == "extras" and isinstance(v, dict):
                cols[key] = ("map", {name: _pack_col(a, n_trans, k)
                                     for name, a in v.items()})
            else:
                cols[key] = _pack_col(v, n_trans, k)
        enc = {"v": WIRE_VERSION, "codec": codec, "cols": cols}
        for key, v in msg.items():
            if key == "payload":
                continue
            enc.setdefault("top", {})[key] = _pack_col(v, n_trans, k)
        wire = pickle.dumps(("chunkc", enc), protocol=5)
    except (CodecError, KeyError, AttributeError, ValueError, TypeError,
            IndexError):
        raw = pickle.dumps(("chunk", msg), protocol=5)
        return raw, len(raw), len(raw)
    bound = _array_bytes(msg)
    if 2 * len(wire) <= bound:
        return wire, bound, len(wire)
    raw = pickle.dumps(("chunk", msg), protocol=5)
    if len(wire) >= len(raw):
        return raw, len(raw), len(raw)
    return wire, len(raw), len(wire)


def decode_chunk(enc: dict) -> dict:
    """``("chunkc", enc)`` body -> the original chunk msg, byte-exact.

    Raises :class:`CodecError` on anything hostile, truncated,
    version-unknown, or checksum-failing — callers count and drop the
    chunk WITHOUT acking, so a healthy sender retries and a garbage
    sender gets nothing.
    """
    try:
        if not isinstance(enc, dict) or int(enc.get("v", -1)) > WIRE_VERSION:
            raise CodecError("unknown chunkc version")
        codec = enc["codec"]
        if codec not in CODECS or codec == "raw":
            raise CodecError(f"unknown chunk codec {codec!r}")
        cols = enc["cols"]
        if not isinstance(cols, dict) or "frames" not in cols:
            raise CodecError("chunkc without frame block")
        payload = {}
        for key, spec in cols.items():
            if key == "frames":
                payload[key] = _unpack_frames(spec, codec)
            elif key == "extras" and spec[0] == "map":
                payload[key] = {name: _unpack_col(s)
                                for name, s in spec[1].items()}
            else:
                payload[key] = _unpack_col(spec)
        msg = {"payload": payload}
        for key, spec in (enc.get("top") or {}).items():
            msg[key] = _unpack_col(spec)
        return msg
    except CodecError:
        raise
    except Exception as e:
        raise CodecError(f"malformed chunkc body: {type(e).__name__}") from e


# -- param-delta plane -------------------------------------------------------


def _children(obj):
    """(key, child) pairs for one container level, or None for a leaf.
    Mapping iteration order is the traversal order — both ends flatten
    the same tree shape, so orders agree without sorting."""
    if isinstance(obj, Mapping):
        return [(str(k), obj[k]) for k in obj]
    if isinstance(obj, (list, tuple)):
        return [(str(i), v) for i, v in enumerate(obj)]
    return None


def _leaf_bytes(leaf) -> bytes:
    a = np.asarray(leaf)
    if a.dtype == object:
        return repr(leaf).encode()
    return (str(a.dtype).encode() + b"|" + str(a.shape).encode() + b"|"
            + a.tobytes())


def flatten_tree(tree, prefix: str = "") -> list:
    """Deterministic (path, leaf) walk; paths are '/'-joined."""
    kids = _children(tree)
    if kids is None:
        return [(prefix, tree)]
    out = []
    for key, child in kids:
        path = f"{prefix}/{key}" if prefix else key
        out.extend(flatten_tree(child, path))
    return out


def bytes_checksum(byte_map: Mapping) -> int:
    """crc32 chained over a ``path -> leaf bytes`` map in iteration
    order — :func:`diff_tree` builds these maps in flatten order, so
    this equals :func:`tree_checksum` of the same tree without a second
    tree walk."""
    crc = 0
    for path, b in byte_map.items():
        crc = zlib.crc32(path.encode(), crc)
        crc = zlib.crc32(b, crc)
    return crc


def tree_checksum(tree) -> int:
    """crc32 chained over (path, dtype, shape, bytes) of every leaf —
    what a subscriber verifies after reassembling a delta."""
    crc = 0
    for path, leaf in flatten_tree(tree):
        crc = zlib.crc32(path.encode(), crc)
        crc = zlib.crc32(_leaf_bytes(leaf), crc)
    return crc


def diff_tree(tree, base_bytes: dict) -> tuple[dict, dict, int]:
    """(updates, new_bytes, raw_total): leaves whose bytes differ from
    the keyframe base, the current per-leaf byte map, and the dense
    byte size (the publisher's wire_bytes_raw analogue)."""
    updates, new_bytes, raw_total = {}, {}, 0
    for path, leaf in flatten_tree(tree):
        b = _leaf_bytes(leaf)
        new_bytes[path] = b
        raw_total += len(b)
        if base_bytes.get(path) != b:
            updates[path] = np.asarray(leaf)
    return updates, new_bytes, raw_total


def apply_delta(base_tree, updates: Mapping):
    """Rebuild the tree with ``updates`` leaves swapped in (containers
    are rebuilt immutably — FrozenDict stays FrozenDict, tuple stays
    tuple).  Unknown paths raise :class:`CodecError`."""
    tree = base_tree
    try:
        for path, leaf in updates.items():
            tree = _set_path(tree, path.split("/"), leaf)
    except CodecError:
        raise
    except Exception as e:
        raise CodecError(f"delta does not apply: {type(e).__name__}") from e
    return tree


def _set_path(obj, parts: list, leaf):
    key = parts[0]
    if isinstance(obj, Mapping):
        match = None
        for k in obj:
            if str(k) == key:
                match = k
                break
        if match is None:
            raise CodecError(f"delta path {key!r} not in tree")
        d = dict(obj)
        d[match] = (leaf if len(parts) == 1
                    else _set_path(d[match], parts[1:], leaf))
        if type(obj) is dict:
            return d
        return obj.__class__(d)
    if isinstance(obj, (list, tuple)):
        i = int(key)
        if not 0 <= i < len(obj):
            raise CodecError(f"delta index {key!r} not in tree")
        items = list(obj)
        items[i] = (leaf if len(parts) == 1
                    else _set_path(items[i], parts[1:], leaf))
        return items if isinstance(obj, list) else type(obj)(items)
    raise CodecError("delta path descends into a leaf")
