"""Multi-host runtime: socket transport, process roles, CLI.

The reference's L4/L6 plane (ZeroMQ role scripts,
``origin_repo/{learner,actor,replay,eval}.py``) re-designed for the TPU
topology — replay dissolved into the learner's HBM, one shared concurrent
loop for in-host and multi-host, role identity via env vars or flags.
See :mod:`apex_tpu.runtime.transport` and :mod:`apex_tpu.runtime.roles`.
"""
