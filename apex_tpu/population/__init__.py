"""Population plane: multi-task lineages + the PBT controller.

Each lineage is a TENANT (:class:`~apex_tpu.population.lineage.
LineageSpec` extends :class:`~apex_tpu.tenancy.namespace.TenantSpec`), so
the whole multi-tenant substrate — per-tenant replay partitions, quotas,
infer params, ``@tenant`` SLO signals, chaos scope — carries a
population of learner lineages with zero new plumbing.  The
``--role pbt-ctl`` controller (:mod:`apex_tpu.population.controller`)
polls each lineage's eval-ladder scores and runs truncation-selection
exploit (checkpoint copy + learner-epoch bump) and perturb/resample
explore on the hyperparameter vector.
"""

from apex_tpu.population.lineage import (HPARAM_BANDS, LineageSpec,
                                         apply_lineage, load_population)

__all__ = ["HPARAM_BANDS", "LineageSpec", "apply_lineage",
           "load_population"]
