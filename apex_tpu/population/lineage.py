"""Lineage = tenant + a mutable hyperparameter vector.

The Ape-X epsilon ladder is a degenerate population: one lineage, a
spectrum of exploration hyperparameters.  The general form adds two
dimensions — TASKS (the roster assigns env ids per lineage, so one fleet
mixes Catch/Rally/... and ``make_env``/``make_jax_env`` dispatch per
lineage) and LINEAGES (each with its own learner fleet whose
hyperparameters evolve via exploit/explore decisions off eval scores,
:mod:`apex_tpu.population.controller`).

:class:`LineageSpec` extends :class:`~apex_tpu.tenancy.namespace.
TenantSpec`, so a lineage IS a tenant: its roles qualify their wire
identities/chunk ids/param topics off ``APEX_TENANT=<lineage>``, the
shared replay shards build it a quota-bounded partition, the infer
shards hold its params, the registry labels its peers, and chaos scopes
to it — all inherited from the PR 13 namespace grammar, zero new
plumbing.  The extra fields are the MUTABLE vector (lr, n-step,
priority exponent/beta, epsilon band — the knobs the PBT controller
perturbs) plus ``parent``/``generation`` lineage bookkeeping.

Field semantics: a hyperparameter left ``None`` INHERITS the config —
a roster of one lineage with no overrides configures exactly the plain
single-tenant run (population-of-1 parity, pinned in
tests/test_population.py).  ``env_id`` defaults to ``""`` (inherit) for
the same reason; :meth:`LineageSpec.as_tenant` fills the TenantSpec
default back in for the shared planes, which size partitions from it.

The ``APEX_POPULATION`` env var carries the lineage roster as JSON
(list of :class:`LineageSpec` dicts), the ``APEX_TENANTS`` discipline:
export and go, every shared-plane process loads the same one.
:func:`apex_tpu.tenancy.namespace.load_roster` folds the population in,
so lineages are admitted tenants everywhere without a second export.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass

from apex_tpu.tenancy import namespace

#: the mutable hyperparameter vector and its clamp bands — the space the
#: controller's perturb/resample explore moves through.  Bands follow
#: the PBT paper's practice (wide enough to matter, clamped so a run of
#: x1.2 perturbations cannot walk into a divergent regime); integer
#: bands (n_steps) perturb by +-1 instead of a factor.
HPARAM_BANDS: dict[str, tuple[float, float]] = {
    "lr": (1e-5, 1e-2),
    "n_steps": (1, 5),
    "prio_alpha": (0.4, 0.9),
    "prio_beta": (0.2, 0.8),
    "eps_base": (0.05, 0.7),
}

#: vector fields a LIVE learner absorbs mid-run
#: (:meth:`apex_tpu.training.apex.ConcurrentTrainer.apply_hparams`:
#: lr rebuilds the optimizer chain, prio_beta re-points the IS-weight
#: anneal).  The rest shape acting-side programs — n-step chunk
#: assembly, insert-time priority exponents, the epsilon ladder — and
#: apply at role (re)spawn via :func:`apply_lineage`.
LIVE_HPARAMS = ("lr", "prio_beta")


@dataclass(frozen=True)
class LineageSpec(namespace.TenantSpec):
    """One lineage's admission record: the TenantSpec base (name, env
    id, family, learner endpoint, replay quota, band weight) plus the
    mutable hyperparameter vector and lineage ancestry."""

    # env_id redeclared with an INHERIT default ("" = the launching
    # config's env) so a no-override lineage spec leaves a plain run
    # untouched; as_tenant() restores the TenantSpec default for the
    # shared planes, which need a concrete env to size partitions
    env_id: str = ""
    lr: float | None = None
    n_steps: int | None = None
    prio_alpha: float | None = None
    prio_beta: float | None = None
    eps_base: float | None = None
    parent: str = ""
    generation: int = 0

    def hparams(self) -> dict:
        """The mutable vector (None = inherit the config default)."""
        return {k: getattr(self, k) for k in HPARAM_BANDS}

    def as_tenant(self) -> "LineageSpec":
        """The admission-plane view: the inherited env id defaulted so
        partition sizing never sees an empty one.  Still a LineageSpec
        (a LineageSpec IS a TenantSpec) — the replay shards read the
        hyperparameter vector too, so a lineage's partition is built
        with ITS priority exponent/beta, not the shared default's."""
        if self.env_id:
            return self
        return dataclasses.replace(self,
                                   env_id=namespace.TenantSpec.env_id)


def parse_population(raw: str) -> dict[str, LineageSpec]:
    """``name -> LineageSpec`` from the roster JSON (duplicate lineage
    names are a config error, the roster discipline)."""
    specs = [LineageSpec.from_dict(d) for d in json.loads(raw)]
    out: dict[str, LineageSpec] = {}
    for spec in specs:
        if spec.name in out:
            raise ValueError(
                f"duplicate lineage {spec.name!r} in population roster")
        out[spec.name] = spec
    return out


def load_population(environ=None) -> dict[str, LineageSpec]:
    """The fleet's lineage roster (``APEX_POPULATION``, JSON list of
    :class:`LineageSpec` dicts); empty when unset.  The default tenant
    MAY carry an entry — that is how a plain fleet joins a population
    as lineage zero."""
    e = os.environ if environ is None else environ
    raw = str(e.get("APEX_POPULATION", "")).strip()
    if not raw:
        return {}
    return parse_population(raw)


def apply_lineage(cfg, spec: LineageSpec):
    """The lineage's config: env id + hyperparameter vector applied to
    the role's :class:`~apex_tpu.config.ApexConfig` — after this,
    ``make_env``/``make_jax_env`` (host and ondevice rollout paths
    alike), the n-step chunk assembly, the priority exponents, and the
    epsilon ladder all dispatch off the lineage.  A spec with no
    overrides returns ``cfg`` UNCHANGED (population-of-1 parity)."""
    out = cfg
    if spec.env_id and spec.env_id != cfg.env.env_id:
        out = out.replace(env=dataclasses.replace(out.env,
                                                  env_id=spec.env_id))
    learner = {}
    if spec.lr is not None:
        learner["lr"] = float(spec.lr)
    if spec.n_steps is not None:
        learner["n_steps"] = int(spec.n_steps)
    if learner:
        out = out.replace(learner=dataclasses.replace(out.learner,
                                                      **learner))
    replay = {}
    if spec.prio_alpha is not None:
        replay["alpha"] = float(spec.prio_alpha)
    if spec.prio_beta is not None:
        replay["beta"] = float(spec.prio_beta)
    if replay:
        out = out.replace(replay=dataclasses.replace(out.replay,
                                                     **replay))
    if spec.eps_base is not None:
        out = out.replace(actor=dataclasses.replace(
            out.actor, eps_base=float(spec.eps_base)))
    return out
