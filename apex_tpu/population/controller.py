"""The PBT control plane: task ladders, exploit/explore, lineage record.

``--role pbt-ctl`` is the population's control loop, built in the
serve-ctl/tenant-ctl mold: a socket-free, fake-clock-testable
:class:`PopulationController` drives the decisions, and a thin
one-thread socket wrapper (:class:`PbtCtl`) feeds it observations and
ships the evidence out.

What it decides (the PBT loop, arxiv 1711.09846 scaled to our fleet):

* **Task ladders** — lineages group by env id; scores only rank WITHIN
  a ladder (a Rally score means nothing on the Catch ladder — the
  epsilon ladder generalized to a task ladder).  A single-lineage
  ladder never exploits: population-of-1 is a plain run.
* **Exploit** — truncation selection per ladder: the bottom-k lineages
  restore the top-k's newest checkpoint.  The weight copy reuses the
  PR 8 snapshot machinery (:func:`apex_tpu.training.checkpoint.
  load_raw` on the donor's ``ckpt_*.msgpack``), applied to the LIVE
  loser learner via the status-port ctl surface
  (:meth:`apex_tpu.training.apex.ConcurrentTrainer.restore_weights`),
  which bumps the lineage's learner epoch — stale params and replay
  write-backs from the pre-copy life are rejected by the existing
  fencing, exactly as a restart's would be.
* **Explore** — perturb/resample on the donor's hyperparameter vector
  (x0.8/x1.2 factors, integer knobs step by one, ``resample_prob``
  draws fresh from the band; everything clamped to
  :data:`~apex_tpu.population.lineage.HPARAM_BANDS` and deterministic
  off the seeded RNG).  The mutated vector rides the same ctl command;
  the live learner absorbs the LIVE_HPARAMS half immediately and the
  rest applies to the lineage's next worker generation.

Every decision lands in a bounded ``population`` timeline —
``fleet_summary.json`` (via :class:`PopulationStat` on the stat
channel), ``--role status``, and ``apex_population_*`` Prometheus rows
all show the same machine, lineage survival/generation counts included.

Pure stdlib at module level (zmq/transport import lazily in the socket
wrapper), the scheduler.py discipline.
"""

from __future__ import annotations

import random
import time
from collections import deque
from dataclasses import dataclass, field

from apex_tpu.population.lineage import (HPARAM_BANDS, LineageSpec,
                                         load_population)
from apex_tpu.tenancy import namespace

EXPLOIT, EXPLORE, SKIPPED = "EXPLOIT", "EXPLORE", "SKIPPED"

#: integer-valued vector fields: explore steps them by +-1, not a factor
_INT_HPARAMS = ("n_steps",)


@dataclass
class PopulationStat:
    """The controller's state shipped to the host learner on the stat
    channel (wire-allowlisted): ``snapshot`` is
    :meth:`PopulationController.snapshot` — plain builtins only."""

    identity: str
    snapshot: dict = field(default_factory=dict)


@dataclass
class _LineageState:
    spec: LineageSpec
    hparams: dict               # the live vector the controller owns
    generation: int = 0
    parent: str = ""
    alive: bool = False
    score: float | None = None  # eval-ladder recent-window mean
    episodes: int = 0           # eval episodes behind the score
    steps: int | None = None    # lineage learner progress
    checkpoint: str | None = None   # newest donor-able ckpt path
    last_change: float | None = None
    exploits_taken: int = 0     # times this lineage copied a donor
    exploits_donated: int = 0   # times this lineage was the donor


def resolve_vector(spec: LineageSpec) -> dict:
    """The concrete vector explore mutates: spec overrides where set,
    band midpoints otherwise (geometric midpoint for the log-scaled
    lr).  Deterministic — two controllers over one roster agree."""
    out: dict = {}
    for name, (lo, hi) in HPARAM_BANDS.items():
        v = getattr(spec, name)
        if v is None:
            if name == "lr":
                v = (lo * hi) ** 0.5
            elif name in _INT_HPARAMS:
                v = int(round((lo + hi) / 2))
            else:
                v = (lo + hi) / 2
        out[name] = int(v) if name in _INT_HPARAMS else float(v)
    return out


class PopulationController:
    """The decision half of pbt-ctl (module docstring): socket-free,
    every clock injectable, every transition in a bounded timeline —
    the DeployController/PlacementScheduler testing discipline.

    ``decide_every_s`` paces decision rounds; ``frac`` is the
    truncation fraction (bottom-k copies top-k, k >= 1);
    ``min_episodes`` keeps a lineage from being judged off one lucky
    episode; ``min_delta`` is the strict score gap an exploit needs;
    ``cooldown_s`` (default two decision periods) keeps a just-exploited
    lineage from thrashing before its new weights have scored.
    """

    def __init__(self, population: dict[str, LineageSpec], *,
                 decide_every_s: float = 30.0, frac: float = 0.25,
                 resample_prob: float = 0.25, min_episodes: int = 4,
                 min_delta: float = 1e-9, cooldown_s: float | None = None,
                 seed: int = 0, clock=time.monotonic, wall=time.time,
                 timeline_cap: int = 128):
        self.decide_every_s = float(decide_every_s)
        self.frac = float(frac)
        self.resample_prob = float(resample_prob)
        self.min_episodes = int(min_episodes)
        self.min_delta = float(min_delta)
        self.cooldown_s = (2.0 * self.decide_every_s
                           if cooldown_s is None else float(cooldown_s))
        self._rng = random.Random(seed)
        self._clock = clock
        self._wall = wall
        self.lineages: dict[str, _LineageState] = {
            name: _LineageState(spec=spec, hparams=resolve_vector(spec),
                                generation=spec.generation,
                                parent=spec.parent)
            for name, spec in population.items()}
        self.decisions = 0
        self.exploits = 0
        self.explores = 0
        self.timeline: deque = deque(maxlen=timeline_cap)
        self._t0: float | None = None
        self._last_decide: float | None = None

    # -- observations ------------------------------------------------------

    def observe(self, name: str, *, alive: bool,
                score: float | None = None, episodes: int = 0,
                steps: int | None = None,
                checkpoint: str | None = None) -> None:
        """One probe result for a lineage's learner fleet: liveness,
        its eval-ladder score (recent-window mean + episode count off
        the registry gauges), progress, and its newest checkpoint path
        (the donor-able artifact)."""
        ls = self.lineages.get(name)
        if ls is None:
            return
        ls.alive = bool(alive)
        if alive:
            if score is not None:
                ls.score = float(score)
            ls.episodes = int(episodes)
            if steps is not None:
                ls.steps = int(steps)
            if checkpoint:
                ls.checkpoint = str(checkpoint)

    # -- the machine -------------------------------------------------------

    def _event(self, kind: str, lineage: str, reason: str,
               **extra) -> dict:
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        e = {"t_s": round(now - self._t0, 3),
             "wall": round(self._wall(), 3),
             "event": kind, "lineage": lineage, "reason": reason}
        e.update(extra)
        self.timeline.append(e)
        return e

    def ladders(self) -> dict[str, list[str]]:
        """Task ladders: lineage names grouped by env id (an inherited
        env groups under ``""`` — its launcher's env, one ladder)."""
        out: dict[str, list[str]] = {}
        for name, ls in sorted(self.lineages.items()):
            out.setdefault(ls.spec.env_id, []).append(name)
        return out

    def mutate(self, hparams: dict) -> tuple[dict, list[str]]:
        """Perturb/resample explore on one vector: per field, resample
        uniformly from the band with ``resample_prob``, else perturb
        x0.8/x1.2 (integer fields step +-1); everything clamps to the
        band.  Returns ``(mutated, human notes)``."""
        out, notes = {}, []
        for name, (lo, hi) in HPARAM_BANDS.items():
            v = hparams.get(name)
            if v is None:
                continue
            if self._rng.random() < self.resample_prob:
                nv = self._rng.uniform(lo, hi)
                how = "resample"
            elif name in _INT_HPARAMS:
                nv = v + self._rng.choice((-1, 1))
                how = "step"
            else:
                nv = v * self._rng.choice((0.8, 1.2))
                how = "perturb"
            nv = min(max(nv, lo), hi)
            nv = int(round(nv)) if name in _INT_HPARAMS else float(nv)
            if nv != v:
                notes.append(f"{name}: {v:g} -> {nv:g} ({how})")
            out[name] = nv
        return out, notes

    def _eligible(self, name: str, now: float) -> bool:
        ls = self.lineages[name]
        if not ls.alive or ls.score is None:
            return False
        if ls.episodes < self.min_episodes:
            return False
        if ls.last_change is not None \
                and now - ls.last_change < self.cooldown_s:
            return False
        return True

    def _exploit(self, loser: str, donor: str, now: float) -> dict:
        ll, dl = self.lineages[loser], self.lineages[donor]
        mutated, notes = self.mutate(dict(dl.hparams))
        ll.hparams = mutated
        # monotone per exploit AND >= the donor's depth: the count reads
        # as "how many selection events shaped this lineage's weights"
        ll.generation = max(ll.generation, dl.generation) + 1
        ll.parent = donor
        ll.last_change = now
        ll.exploits_taken += 1
        dl.exploits_donated += 1
        self.exploits += 1
        self.explores += 1
        self._event(
            EXPLOIT, loser,
            f"score {ll.score:g} < {donor} {dl.score:g}; restoring "
            f"{dl.checkpoint}",
            donor=donor, generation=ll.generation)
        self._event(EXPLORE, loser,
                    "; ".join(notes) or "vector unchanged (clamped)",
                    donor=donor)
        return {"op": "exploit", "restore_from": dl.checkpoint,
                "hparams": dict(mutated), "donor": donor,
                "generation": ll.generation}

    def tick(self) -> list[tuple[str, dict]]:
        """One decision round (paced to ``decide_every_s``; off-cadence
        calls are free).  Returns the ``(lineage, ctl command)`` sends
        for this round — at most one exploit per losing lineage."""
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        if self._last_decide is not None \
                and now - self._last_decide < self.decide_every_s:
            return []
        self._last_decide = now
        self.decisions += 1
        commands: list[tuple[str, dict]] = []
        for _task, names in sorted(self.ladders().items()):
            ranked = sorted(
                (n for n in names if self._eligible(n, now)),
                key=lambda n: (-self.lineages[n].score, n))
            if len(ranked) < 2:
                continue        # population-of-1 ladder: a plain run
            k = max(1, int(self.frac * len(ranked)))
            k = min(k, len(ranked) // 2)    # top and bottom disjoint
            tops, bottoms = ranked[:k], ranked[-k:]
            for i, loser in enumerate(bottoms):
                donor = tops[i % len(tops)]
                ll, dl = self.lineages[loser], self.lineages[donor]
                if dl.score - ll.score <= self.min_delta:
                    continue    # ladder is flat: nothing to copy
                if not dl.checkpoint:
                    self._event(SKIPPED, loser,
                                f"donor {donor} has no checkpoint yet",
                                donor=donor)
                    continue
                commands.append((loser, self._exploit(loser, donor, now)))
        return commands

    # -- read surface ------------------------------------------------------

    def snapshot(self) -> dict:
        """Serializable controller view (PopulationStat payload, the
        ``population`` section of fleet_summary.json): plain builtins
        only.  tests/test_population.py pins this schema."""
        lineages = {}
        for name, ls in sorted(self.lineages.items()):
            lineages[name] = {
                "task": ls.spec.env_id,
                "alive": ls.alive,
                "score": ls.score,
                "episodes": ls.episodes,
                "steps": ls.steps,
                "generation": ls.generation,
                "parent": ls.parent,
                "exploits_taken": ls.exploits_taken,
                "exploits_donated": ls.exploits_donated,
                "checkpoint": ls.checkpoint,
                "hparams": dict(ls.hparams),
            }
        return {
            "kind": "apex_population",
            "version": 1,
            "decide_every_s": self.decide_every_s,
            "frac": self.frac,
            "lineages": lineages,
            "decisions": self.decisions,
            "exploits": self.exploits,
            "explores": self.explores,
            "timeline": list(self.timeline),
        }


# -- operator/exposition surfaces --------------------------------------------


def prometheus_sections(population: dict) -> tuple[dict, dict]:
    """(gauges, labeled) — the ``apex_population_*`` row family the
    learner's scrape surface serves next to the slo/tenancy rows."""
    lineages = population.get("lineages") or {}
    gauges = {
        "population_lineages": len(lineages),
        "population_decisions": population.get("decisions", 0),
        "population_exploits": population.get("exploits", 0),
        "population_explores": population.get("explores", 0),
    }
    labeled = {
        "population_lineage_state": [
            ({"lineage": n, "task": v.get("task") or "inherit"},
             1.0 if v.get("alive") else 0.0)
            for n, v in sorted(lineages.items())],
        "population_lineage_generation": [
            ({"lineage": n}, v.get("generation", 0))
            for n, v in sorted(lineages.items())],
        "population_lineage_score": [
            ({"lineage": n}, v.get("score"))
            for n, v in sorted(lineages.items())
            if v.get("score") is not None],
    }
    return gauges, labeled


def format_population_lines(population: dict) -> list[str]:
    """Human population lines for the ``--role status`` table: one line
    per lineage plus the exploit/explore timeline tail."""
    lineages = population.get("lineages") or {}
    lines = [
        f"population: {len(lineages)} lineage(s) "
        f"decisions={population.get('decisions', 0)} "
        f"exploits={population.get('exploits', 0)} "
        f"explores={population.get('explores', 0)}"]
    for n, v in sorted(lineages.items()):
        score = v.get("score")
        lines.append(
            f"lineage {n}: {'ALIVE' if v.get('alive') else 'SILENT'} "
            f"task={v.get('task') or 'inherit'} "
            f"gen={v.get('generation', 0)} "
            f"parent={v.get('parent') or '-'} "
            f"score={'-' if score is None else round(score, 3)} "
            f"eps={v.get('episodes', 0)} "
            f"taken={v.get('exploits_taken', 0)} "
            f"donated={v.get('exploits_donated', 0)}")
    for e in (population.get("timeline") or [])[-4:]:
        lines.append(f"population t={e['t_s']}s {e['event']} "
                     f"{e['lineage']} ({e['reason']})")
    return lines


# -- the socket role ---------------------------------------------------------


class PbtCtl:
    """Socket wrapper around :class:`PopulationController` — the
    ``--role pbt-ctl`` process body (tenant-ctl's one-thread shape).

    Per tick: probe each lineage's OWN learner status port (liveness +
    eval-ladder score off the registry gauges + progress + its newest
    checkpoint path), feed the controller, send any exploit/explore
    commands to the losing lineages' learner ctl surfaces, judge the
    per-lineage roster SLOs, and ship the snapshot to the host learner
    as a :class:`PopulationStat`.
    """

    def __init__(self, cfg, interval_s: float = 5.0,
                 decide_every_s: float = 30.0, frac: float = 0.25,
                 resample_prob: float = 0.25, min_episodes: int = 4,
                 population: dict[str, LineageSpec] | None = None):
        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        from apex_tpu.obs.slo import SloEngine, roster_slos
        from apex_tpu.runtime import transport

        self.comms = cfg.comms
        self.interval_s = float(interval_s)
        self.population = (population if population is not None
                           else load_population())
        self.ctrl = PopulationController(
            self.population, decide_every_s=decide_every_s, frac=frac,
            resample_prob=resample_prob, min_episodes=min_episodes,
            seed=cfg.env.seed)
        # per-lineage roster SLOs (the PR 13 follow-up): progress-floor
        # + eval-score objectives declared from the roster, judged off
        # the controller's own probe stream
        self.slo = (SloEngine(roster_slos(self.population))
                    if self.population else None)
        self._probe_marks: dict[str, tuple[float, int]] = {}
        self._probe_rates: dict[str, float | None] = {}
        self.sender = transport.ChunkSender(cfg.comms, "pbt-ctl")
        self.beat = HeartbeatEmitter(
            "pbt-ctl", role="pbt-ctl",
            interval_s=cfg.comms.heartbeat_interval_s,
            gauges_fn=self._gauges)
        self.ticks = 0
        self.commands_sent = 0

    def _gauges(self) -> dict:
        return {"lineages": sum(ls.alive
                                for ls in self.ctrl.lineages.values())}

    def _probe_lineage(self, spec: LineageSpec) -> None:
        from apex_tpu.fleet.registry import status_request
        from apex_tpu.obs.slo import resolve_signal

        try:
            snap = status_request(
                namespace.tenant_comms(self.comms, spec),
                timeout_s=min(2.0, self.interval_s))
        except Exception:
            snap = None
        if not snap:
            self.ctrl.observe(spec.name, alive=False)
            self._probe_rates[spec.name] = None
            return
        steps = snap.get("steps")
        score = resolve_signal(snap, "gauge:evaluator:eval_score_mean:min")
        episodes = resolve_signal(snap, "gauge:evaluator:eval_episodes:max")
        m = snap.get("metrics") or {}
        self.ctrl.observe(
            spec.name, alive=True, score=score,
            episodes=int(episodes or 0), steps=steps,
            checkpoint=m.get("checkpoint_latest"))
        # probe-derived progress rate for the roster SLOs: steps
        # differenced against the previous probe of THIS lineage
        now = time.monotonic()
        rate = None
        mark = self._probe_marks.get(spec.name)
        if steps is not None:
            if mark is not None and now > mark[0]:
                rate = max(0.0, (int(steps) - mark[1]) / (now - mark[0]))
            self._probe_marks[spec.name] = (now, int(steps))
        self._probe_rates[spec.name] = rate

    def _slo_summary(self) -> dict:
        """The probe-derived signal space the roster objectives walk:
        ``tenants.<lineage>.steps_rate`` / ``.eval_score``."""
        tenants = {}
        for name, ls in self.ctrl.lineages.items():
            tenants[name] = {"steps_rate": self._probe_rates.get(name),
                             "eval_score": ls.score}
        return {"tenants": tenants}

    def _send_command(self, lineage: str, cmd: dict) -> None:
        from apex_tpu.fleet.registry import ctl_request

        spec = self.population[lineage]
        info = ctl_request(namespace.tenant_comms(self.comms, spec), cmd,
                           timeout_s=min(2.0, self.interval_s))
        self.commands_sent += 1
        print(f"pbt-ctl: {cmd['op']} -> {lineage} "
              f"(donor={cmd.get('donor')}, "
              f"{'accepted' if info and info.get('accepted') else 'no ack'})",
              flush=True)

    def step(self) -> None:
        """One control round: probe -> decide -> command -> judge ->
        report (new timeline events print like serve-ctl's do)."""
        for spec in self.population.values():
            self._probe_lineage(spec)
        before = len(self.ctrl.timeline)
        commands = self.ctrl.tick()
        for e in list(self.ctrl.timeline)[before:]:
            print(f"pbt-ctl: {e['event']} {e['lineage']} ({e['reason']})",
                  flush=True)
        for lineage, cmd in commands:
            self._send_command(lineage, cmd)
        if self.slo is not None:
            for tr in self.slo.sample(self._slo_summary()):
                print(f"pbt-ctl: slo {tr['objective']} {tr['from']} -> "
                      f"{tr['to']} (value={tr['value']})", flush=True)
        self.ticks += 1
        snap = self.ctrl.snapshot()
        if self.slo is not None:
            snap["slo"] = self.slo.snapshot()
        self.sender.send_stat(PopulationStat("pbt-ctl", snap))
        hb = self.beat.maybe_beat()
        if hb is not None:
            self.sender.send_stat(hb)

    def run(self, stop_event=None, max_seconds: float | None = None):
        deadline = (None if max_seconds is None
                    else time.monotonic() + max_seconds)
        try:
            while True:
                if stop_event is not None and stop_event.is_set():
                    break
                if deadline is not None and time.monotonic() > deadline:
                    break
                t0 = time.monotonic()
                self.step()
                rest = self.interval_s - (time.monotonic() - t0)
                if rest > 0:
                    if stop_event is not None:
                        stop_event.wait(rest)
                    else:
                        time.sleep(rest)
        finally:
            self.close()
        return self.ctrl.snapshot()

    def close(self) -> None:
        self.sender.close(drain_s=0.0)


def run_pbt_ctl(cfg, interval_s: float = 5.0, decide_every_s: float = 30.0,
                frac: float = 0.25, resample_prob: float = 0.25,
                min_episodes: int = 4, stop_event=None,
                max_seconds: float | None = None) -> dict:
    """The ``--role pbt-ctl`` entry point.  Skips the startup barrier
    like the other controllers — useful the moment any lineage's status
    port answers.  Returns the final controller snapshot."""
    from apex_tpu.obs.trace import get_ring, set_process_label

    set_process_label("pbt-ctl")
    get_ring()
    ctl = PbtCtl(cfg, interval_s=interval_s, decide_every_s=decide_every_s,
                 frac=frac, resample_prob=resample_prob,
                 min_episodes=min_episodes)
    ladders = {task or "inherit": names
               for task, names in ctl.ctrl.ladders().items()}
    print(f"pbt-ctl: {len(ctl.population)} lineage(s) over "
          f"{len(ladders)} task ladder(s) {ladders}, "
          f"decide={decide_every_s:g}s, frac={frac:g}, "
          f"tick={interval_s:g}s", flush=True)
    return ctl.run(stop_event=stop_event, max_seconds=max_seconds)
