"""Light intra-procedural dataflow: reaching defs + donation tracking.

Two analyses, both deliberately linear (no fixed-point CFG — statements
in source order, branches merged by union), because the hazards they
serve are straight-line epilogue bugs, not loop-carried lattice puzzles:

* :func:`reaching_defs` — for every local-name load in a function, the
  set of assignment statements that may reach it.  Branches contribute
  their defs without killing the pre-branch ones (may-reach, not
  must-reach), which is the safe direction for a linter.

* donation tracking — :func:`donated_callables` finds every callable in
  a module bound to ``jax.jit(..., donate_argnums=...)`` (direct
  assignment, ``@partial(jax.jit, donate_argnums=...)`` decoration, or
  assignment from a same-module/imported factory that returns one), and
  :func:`donation_hazards` walks each function for call sites of those
  callables where a donated argument buffer is READ again after the
  dispatch that consumed it.  XLA invalidates a donated buffer at
  dispatch: the post-call read returns garbage (or a deleted-buffer
  error), and only the rebind-from-results epilogue (the ``FusedStep.
  dispatch`` discipline) is safe.

Pure stdlib.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from apex_tpu.analysis.core import is_jit_expr

__all__ = ["DonatedCallable", "DonationHazard", "donated_callables",
           "donation_hazards", "expr_path", "reaching_defs"]


def expr_path(node: ast.AST) -> str | None:
    """Dotted spelling of a name/attribute chain (``train_state``,
    ``self.ingested_dev``, ``eng.carry``) — the alias key donation
    tracking matches on.  None for anything with a call/subscript in
    the chain (those are fresh values, not aliases)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- reaching definitions ----------------------------------------------------


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def reaching_defs(fn: ast.AST) -> dict[ast.Name, set[ast.stmt]]:
    """Map every ``Name`` LOAD in ``fn`` to the set of statements whose
    assignment may reach it (function parameters reach as a def-site of
    the ``arguments`` node's owning function).  Nested function bodies
    are skipped — their loads close over a different frame."""
    result: dict[ast.Name, set[ast.stmt]] = {}
    params = {a.arg for a in _all_args(fn)}
    env: dict[str, set] = {p: {fn} for p in params}

    def visit_block(stmts, env):
        for stmt in stmts:
            # loads in this statement see the CURRENT env
            for n in _own_nodes(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    if n.id in env:
                        result[n] = set(env[n.id])
            if isinstance(stmt, (ast.If,)):
                e1 = {k: set(v) for k, v in env.items()}
                e2 = {k: set(v) for k, v in env.items()}
                visit_block(stmt.body, e1)
                visit_block(stmt.orelse, e2)
                for k in set(e1) | set(e2):
                    env[k] = (e1.get(k, set()) | e2.get(k, set())
                              | env.get(k, set()))
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                for name in _assigned_names(stmt):
                    env.setdefault(name, set()).add(stmt)
                body_env = {k: set(v) for k, v in env.items()}
                visit_block(stmt.body, body_env)
                visit_block(list(stmt.orelse), body_env)
                for k in body_env:
                    env[k] = body_env.get(k, set()) | env.get(k, set())
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for name in _assigned_names(stmt):
                    env[name] = {stmt}
                visit_block(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                visit_block(stmt.body, env)
                for h in stmt.handlers:
                    visit_block(h.body, env)
                visit_block(stmt.orelse, env)
                visit_block(stmt.finalbody, env)
            else:
                for name in _assigned_names(stmt):
                    env[name] = {stmt}
        return env

    visit_block(list(fn.body), env)
    return result


def _all_args(fn: ast.AST):
    a = fn.args
    return (list(a.posonlyargs) + list(a.args)
            + ([a.vararg] if a.vararg else [])
            + list(a.kwonlyargs) + ([a.kwarg] if a.kwarg else []))


def _own_nodes(stmt: ast.stmt):
    """Nodes of ``stmt`` excluding nested statement bodies and nested
    function/class definitions (block statements recurse explicitly)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    skip_blocks = isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                    ast.While, ast.With, ast.AsyncWith,
                                    ast.Try))
    if not skip_blocks:
        yield from ast.walk(stmt)
        return
    # header expressions only (test/iter/items); bodies recurse elsewhere
    for field in ("test", "iter", "target"):
        sub = getattr(stmt, field, None)
        if sub is not None:
            yield from ast.walk(sub)
    for item in getattr(stmt, "items", ()):
        yield from ast.walk(item.context_expr)


# -- donation tracking -------------------------------------------------------


@dataclass(frozen=True)
class DonatedCallable:
    """A callable whose dispatch consumes (donates) argument buffers."""

    key: str                    # call spelling: "step" / "self._jit"
    positions: tuple[int, ...]  # donated positional indices
    node: ast.AST               # where the donation was declared


@dataclass(frozen=True)
class DonationHazard:
    """One post-dispatch read of a donated buffer."""

    call: ast.Call              # the consuming dispatch
    arg_path: str               # the donated argument's spelling
    read: ast.AST               # the offending read (call itself when the
                                # re-read is the next loop iteration)
    loop_carried: bool          # True: undonated re-dispatch in a loop


def _donation_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Donated positions of a ``jax.jit(fn, donate_argnums=...)`` call
    (None when the call is not a donating jit)."""
    if not (isinstance(call, ast.Call) and is_jit_expr(call.func)):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, (ast.Tuple, ast.List)):
            out = tuple(e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
            return out or None
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        return None
    return None


def _decorator_positions(fn: ast.AST) -> tuple[int, ...] | None:
    """``@partial(jax.jit, donate_argnums=...)`` decoration."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and is_jit_expr(dec):
            got = _donation_positions_from_partial(dec)
            if got:
                return got
    return None


def _donation_positions_from_partial(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return ()


def donated_callables(ctx) -> dict[str, DonatedCallable]:
    """Every call spelling in ``ctx`` bound to a donating jit.

    Three binding shapes::

        self._jit = jax.jit(self._dispatch, donate_argnums=(0, 1))
        @partial(jax.jit, donate_argnums=(0,))
        def step(ts, batch): ...
        self._train = self._make_train()     # factory returns a donating jit

    Factories resolve same-module by name; with a :class:`ProjectContext`
    attached (``ctx.project``) an imported factory resolves cross-module
    too."""
    out: dict[str, DonatedCallable] = {}
    factories: dict[str, tuple[int, ...]] = {}
    for fn in ctx.functions:
        pos = _decorator_positions(fn)
        if pos:
            out[fn.name] = DonatedCallable(fn.name, pos, fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                got = _donation_positions(node.value) \
                    if isinstance(node.value, ast.Call) else None
                if got:
                    factories[fn.name] = got
    project = getattr(ctx, "project", None)
    if project is not None:
        info = project.modules.get(ctx.path)
        if info is not None:
            for alias, (kind, target) in info.aliases.items():
                if kind != "symbol":
                    continue
                node = project.definitions.get(target)
                if node is None or not isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and \
                            isinstance(sub.value, ast.Call):
                        got = _donation_positions(sub.value)
                        if got:
                            factories.setdefault(alias, got)
    for node in ctx.nodes(ast.Assign):
        if not isinstance(node.value, ast.Call):
            continue
        pos = _donation_positions(node.value)
        if pos is None:
            # assignment from a known donated-jit FACTORY call
            callee = node.value.func
            base = (callee.id if isinstance(callee, ast.Name)
                    else callee.attr if isinstance(callee, ast.Attribute)
                    else None)
            pos = factories.get(base or "")
        if not pos:
            continue
        for t in node.targets:
            path = expr_path(t)
            if path is not None:
                out[path] = DonatedCallable(path, tuple(pos), node.value)
    return out


def _stmt_sequence(fn: ast.AST) -> list[ast.stmt]:
    """All statements of ``fn`` in source order, excluding nested defs."""
    out: list[ast.stmt] = []

    def walk(stmts):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            out.append(s)
            for field in ("body", "orelse", "finalbody"):
                walk(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                walk(h.body)

    walk(list(fn.body))
    return out


def _path_events(stmt: ast.stmt, paths: set[str]):
    """``path -> (loads, stores)`` touches of tracked paths in one
    statement's OWN expressions (nested block bodies are separate
    statements in the flattened sequence)."""
    out: dict[str, tuple[list, list]] = {}
    for node in _own_nodes(stmt):
        p = expr_path(node)
        if p not in paths:
            continue
        loads, stores = out.setdefault(p, ([], []))
        is_store = (hasattr(node, "ctx")
                    and isinstance(node.ctx, ast.Store))
        (stores if is_store else loads).append(node)
    return out


def donation_hazards(ctx) -> list[DonationHazard]:
    """Post-dispatch reads of donated buffers, per function."""
    donated = donated_callables(ctx)
    if not donated:
        return []
    hazards: list[DonationHazard] = []
    for fn in ctx.functions:
        seq = _stmt_sequence(fn)
        for i, stmt in enumerate(seq):
            for call in _own_nodes(stmt):
                if not isinstance(call, ast.Call):
                    continue
                key = expr_path(call.func)
                dc = donated.get(key or "")
                if dc is None:
                    continue
                arg_paths: dict[str, int] = {}
                for pos in dc.positions:
                    if pos < len(call.args):
                        p = expr_path(call.args[pos])
                        if p is not None:
                            arg_paths[p] = pos
                if not arg_paths:
                    continue
                # the rebind epilogue: targets of the SAME statement
                rebound: set[str] = set()
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        for n in (ast.walk(t)
                                  if isinstance(t, (ast.Tuple, ast.List))
                                  else [t]):
                            p = expr_path(n)
                            if p is not None:
                                rebound.add(p)
                live = set(arg_paths) - rebound
                if not live:
                    continue
                hazards.extend(self_reads_after(
                    seq, i, stmt, call, live))
                # loop-carried: an undonated re-dispatch next iteration
                loop = _enclosing_loop(ctx, call, fn)
                if loop is not None:
                    for p in sorted(live):
                        if not _stored_in(loop, p):
                            hazards.append(DonationHazard(
                                call, p, call, loop_carried=True))
    return hazards


def self_reads_after(seq, i, stmt, call, live: set[str]):
    """Reads of still-donated paths in statements after the dispatch.
    A statement that both loads and stores a path (``x = f(x)``) reads
    first at runtime, so the load wins."""
    out: list[DonationHazard] = []
    pending = set(live)
    for later in seq[i + 1:]:
        if not pending:
            break
        for p, (loads, stores) in _path_events(later, pending).items():
            if loads:
                out.append(DonationHazard(call, p, loads[0],
                                          loop_carried=False))
            pending.discard(p)      # either flagged or rebound: done
    return out


def _enclosing_loop(ctx, node: ast.AST, fn: ast.AST):
    for a in ctx.ancestors(node):
        if a is fn:
            return None
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            return a
    return None


def _stored_in(block: ast.AST, path: str) -> bool:
    for n in ast.walk(block):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                for sub in (ast.walk(t)
                            if isinstance(t, (ast.Tuple, ast.List))
                            else [t]):
                    if expr_path(sub) == path:
                        return True
    return False
