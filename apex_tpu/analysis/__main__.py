"""``python -m apex_tpu.analysis`` entry point."""

import sys

from apex_tpu.analysis.cli import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:       # `... | head` closed stdout: not an error
        sys.stderr.close()
        sys.exit(0)
