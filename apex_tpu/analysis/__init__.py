"""apexlint — JAX/TPU-aware WHOLE-PROGRAM static analysis for apex-tpu.

An AST-based rule engine for the hazard classes no generic linter sees:
un-donated jit step buffers (J001), host syncs inside compiled code (J002),
Python control flow on traced values (J003), PRNG key reuse (J004),
jit-in-loop retracing (J005), fork-after-thread deadlocks (C001), leaked
ZMQ sockets (C002), and shared-memory segments that violate the
creator-owns-unlink contract (C003/C004) — through the protocol family
that spans modules: donated-buffer reads after dispatch (J020), shard-band
arithmetic outside the tenancy helpers (J021), hand-built epoch/version
fence tuples (J022), and cross-module thread-affinity races (C006).

Per-file rules see a :class:`ModuleContext`; a tree run additionally
parses everything ONCE into a :class:`~apex_tpu.analysis.graph.
ProjectContext` (import/symbol graph, cross-module call graph, and the
light dataflow layer in :mod:`~apex_tpu.analysis.dataflow`) attached as
``ctx.project``, so cross-module rules hold invariants no single file
can.

Run it: ``python -m apex_tpu.analysis apex_tpu/`` (or ``scripts/lint.sh``;
``--changed-only`` lints just the git-diff set, ``--sarif`` writes the CI
artifact, ``--explain RULE`` prints a rule's why + fix recipe).
Suppress a deliberate pattern inline::

    q = float(np.max(scores))  # apexlint: disable=J002 -- host priority path

Accept pre-existing findings wholesale with the checked-in baseline
(``.apexlint-baseline.json``; regenerate via ``--write-baseline``).  The
package is pure stdlib — importing it never touches JAX or the TPU.
"""

from apex_tpu.analysis.core import (Baseline, Finding, ModuleContext, Rule,
                                    all_rules, analyze_paths, analyze_source,
                                    catalog, catalog_markdown, register,
                                    sarif_report)
from apex_tpu.analysis.graph import ProjectContext

__all__ = ["Baseline", "Finding", "ModuleContext", "ProjectContext", "Rule",
           "all_rules", "analyze_paths", "analyze_source", "catalog",
           "catalog_markdown", "register", "sarif_report"]
