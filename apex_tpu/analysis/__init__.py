"""apexlint — JAX/TPU-aware static analysis for the apex-tpu tree.

An AST-based rule engine for the hazard classes no generic linter sees:
un-donated jit step buffers (J001), host syncs inside compiled code (J002),
Python control flow on traced values (J003), PRNG key reuse (J004),
jit-in-loop retracing (J005), fork-after-thread deadlocks (C001), leaked
ZMQ sockets (C002), and shared-memory segments that violate the
creator-owns-unlink contract (C003/C004).

Run it: ``python -m apex_tpu.analysis apex_tpu/`` (or ``scripts/lint.sh``).
Suppress a deliberate pattern inline::

    q = float(np.max(scores))  # apexlint: disable=J002 -- host priority path

Accept pre-existing findings wholesale with the checked-in baseline
(``.apexlint-baseline.json``; regenerate via ``--write-baseline``).  The
package is pure stdlib — importing it never touches JAX or the TPU.
"""

from apex_tpu.analysis.core import (Baseline, Finding, ModuleContext, Rule,
                                    all_rules, analyze_paths, analyze_source,
                                    register)

__all__ = ["Baseline", "Finding", "ModuleContext", "Rule", "all_rules",
           "analyze_paths", "analyze_source", "register"]
