"""apexlint CLI: ``python -m apex_tpu.analysis [paths...]``.

Exit codes: 0 clean (every finding suppressed or baselined), 1 findings
(or, under ``--strict``, stale baseline entries), 2 usage errors.

``--sarif PATH`` additionally writes the findings as a SARIF 2.1.0 log
(the CI artifact); ``--explain RULE`` prints a rule's catalog entry
(why + fix recipe — the same metadata the README table is generated
from, via ``--catalog-md``); ``--changed-only`` lints just the git-diff
file set while the whole-program context still spans the full tree.

Configuration rides in ``[tool.apexlint]`` in pyproject.toml (paths,
exclude, baseline, disable); Python 3.10 has no tomllib, so a minimal
single-section reader handles the flat keys apexlint uses.
"""

from __future__ import annotations

import argparse
import ast as _ast
import json
import os
import re
import sys

from apex_tpu.analysis.core import (Baseline, all_rules, analyze_paths)

DEFAULT_BASELINE = ".apexlint-baseline.json"


def find_project_root(start: str | None = None) -> str | None:
    """Nearest ancestor of ``start`` (default cwd) holding pyproject.toml."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isfile(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


_SECTION_RE = re.compile(r"^\s*\[(?P<name>[^\]]+)\]\s*$")
_KEY_RE = re.compile(r"^\s*(?P<key>[A-Za-z0-9_-]+)\s*=\s*(?P<val>.+)$")


def _strip_comment(line: str) -> tuple[str, int]:
    """``(text up to the first comment, bracket depth delta)`` — both
    computed string-aware, so a ``#`` or ``[`` inside a quoted value
    neither truncates the line nor derails the multi-line fold."""
    out = []
    depth = 0
    quote = None
    i = 0
    while i < len(line):
        c = line[i]
        if quote is not None:
            if c == "\\":
                out.append(line[i:i + 2])
                i += 2
                continue
            if c == quote:
                quote = None
            out.append(c)
        elif c in "\"'":
            quote = c
            out.append(c)
        elif c == "#":
            break
        else:
            if c == "[":
                depth += 1
            elif c == "]":
                depth -= 1
            out.append(c)
        i += 1
    return "".join(out).rstrip(), depth


def load_config(root: str | None) -> dict:
    """Flat ``[tool.apexlint]`` keys from pyproject.toml.  Values are
    strings or arrays of strings (whose literal syntax TOML shares with
    Python); anything fancier is ignored.

    Multi-line arrays fold until their brackets balance, with comments
    stripped PER PHYSICAL LINE before folding (a per-item ``# why``
    comment inside the array used to truncate the folded buffer at its
    first ``#`` and silently drop the whole key).  A value that still
    fails to parse — or an array left unclosed at section end — is
    reported loudly on stderr instead of vanishing."""
    cfg: dict = {}
    if root is None:
        return cfg
    path = os.path.join(root, "pyproject.toml")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return cfg

    def complain(key: str, why: str) -> None:
        print(f"apexlint: [tool.apexlint] key {key!r} in {path} "
              f"ignored: {why}", file=sys.stderr)

    in_section = False
    buf = ""
    key = None
    depth = 0
    for line in lines:
        m = _SECTION_RE.match(line)
        if m and (key is None or depth <= 0):
            if key is not None:
                complain(key, "unterminated value at section boundary")
            in_section = m.group("name").strip() == "tool.apexlint"
            buf, key, depth = "", None, 0
            continue
        if not in_section:
            continue
        if key is None:
            m = _KEY_RE.match(line)
            if not m:
                continue
            key = m.group("key")
            buf, depth = _strip_comment(m.group("val"))
        else:
            folded, d = _strip_comment(line.strip())
            buf += " " + folded
            depth += d
        if depth > 0:
            continue                      # multiline array: keep folding
        try:
            cfg[key] = _ast.literal_eval(buf.strip())
        except (ValueError, SyntaxError) as e:
            complain(key, f"unparsable value ({e})")
        key, buf, depth = None, "", 0
    if key is not None:
        complain(key, "unterminated value at end of file")
    return cfg


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="apexlint: JAX/TPU-aware static analysis for apex-tpu")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: [tool.apexlint] "
                        "paths, else apex_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} at "
                        f"the project root, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current unsuppressed findings into the "
                        "baseline and exit 0")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries (fixed code "
                        "must leave the ledger)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write findings as a SARIF 2.1.0 log "
                        "(the CI artifact format)")
    p.add_argument("--explain", default=None, metavar="RULE",
                   help="print a rule's catalog entry (why + fix recipe) "
                        "and exit; comma-separate ids, or 'all'")
    p.add_argument("--changed-only", action="store_true",
                   help="lint only the git-diff file set (worktree + "
                        "index vs HEAD, plus untracked); the "
                        "whole-program context still spans the full tree")
    p.add_argument("--catalog-md", action="store_true",
                   help="print the rule catalog as a Markdown table "
                        "(the README table's generation source) and exit")
    return p


def explain(rule_ids: str, rules) -> int:
    """``--explain``: the rule catalog, filtered to ``rule_ids``."""
    from apex_tpu.analysis.core import catalog
    entries = {e["id"]: e for e in catalog()}
    wanted = (list(entries) if rule_ids.strip().lower() == "all"
              else [r.strip() for r in rule_ids.split(",") if r.strip()])
    unknown = [r for r in wanted if r not in entries]
    if unknown:
        print(f"apexlint: unknown rule id(s): {', '.join(unknown)}",
              file=sys.stderr)
        return 2
    for i, rid in enumerate(wanted):
        e = entries[rid]
        if i:
            print()
        print(f"{e['id']}  {e['name']}")
        print(f"  why: {e['why']}")
        if e["fix"]:
            print(f"  fix: {e['fix']}")
        print(f"\n  {e['description']}")
    return 0


def changed_files(root: str) -> set[str] | None:
    """Root-relative paths of files changed vs HEAD (worktree + index)
    plus untracked files; None when git is unavailable or errors."""
    import subprocess
    out: set[str] = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", "HEAD", "--"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        out |= {ln.strip().replace(os.sep, "/")
                for ln in proc.stdout.splitlines() if ln.strip()}
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()

    if args.list_rules:
        for rid, rule in rules.items():
            print(f"{rid}  {rule.name}\n    {rule.description}")
        return 0
    if args.explain is not None:
        return explain(args.explain, rules)
    if args.catalog_md:
        from apex_tpu.analysis.core import catalog_markdown
        print(catalog_markdown(), end="")
        return 0

    root = find_project_root()
    cfg = load_config(root)

    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    disabled |= set(cfg.get("disable", []))
    unknown = disabled - set(rules)
    if unknown:
        print(f"apexlint: unknown rule id(s): {', '.join(sorted(unknown))}",
              file=sys.stderr)
        return 2
    rules = {rid: r for rid, r in rules.items() if rid not in disabled}

    paths = args.paths
    if not paths:
        # config paths are project-root-relative, not cwd-relative
        base = root or os.getcwd()
        paths = [os.path.join(base, p)
                 for p in (cfg.get("paths") or ["apex_tpu"])]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"apexlint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    exclude = tuple(cfg.get("exclude", ()))

    baseline_path = args.baseline
    if baseline_path is None and cfg.get("baseline"):
        # config baseline is project-root-relative, like config paths
        baseline_path = os.path.join(root or os.getcwd(),
                                     cfg["baseline"])
    if baseline_path is None and root is not None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        if os.path.exists(cand) or args.write_baseline:
            baseline_path = cand
    if args.no_baseline:
        baseline_path = None

    only = None
    if args.changed_only:
        base = root or os.getcwd()
        changed = changed_files(base)
        if changed is None:
            print("apexlint: --changed-only needs a git checkout "
                  "(git diff failed)", file=sys.stderr)
            return 2
        only = {p for p in changed if p.endswith(".py")}
        if not only:
            print("apexlint: no changed python files")
            return 0

    findings, suppressed = analyze_paths(paths, exclude=exclude,
                                         rules=rules, root=root, only=only)

    if args.write_baseline:
        if baseline_path is None:
            baseline_path = DEFAULT_BASELINE
        Baseline.from_findings(findings).write(baseline_path)
        print(f"apexlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path)}")
        return 0

    baseline = Baseline()
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"apexlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, baselined, stale = baseline.partition(findings)
    if only is not None:
        # a partial run can only judge staleness for the files it linted
        stale = [e for e in stale if e["path"] in only]

    if args.sarif:
        from apex_tpu.analysis.core import sarif_report
        report = sarif_report(new, baselined, suppressed, rules=rules,
                              root=root)
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_baseline": stale,
            "summary": {"new": len(new), "baselined": len(baselined),
                        "suppressed": len(suppressed),
                        "stale_baseline": len(stale)},
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        tail = (f"{len(new)} finding(s) "
                f"({len(baselined)} baselined, "
                f"{len(suppressed)} suppressed inline)")
        if stale:
            tail += f", {len(stale)} stale baseline entr" \
                    f"{'y' if len(stale) == 1 else 'ies'}"
            if args.strict:
                for e in stale:
                    print(f"stale baseline entry: {e['rule']} {e['path']} "
                          f"{e['code']!r} x{e['count']}")
        print(f"apexlint: {tail}")

    if new:
        return 1
    if args.strict and stale:
        return 1
    return 0
