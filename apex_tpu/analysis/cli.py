"""apexlint CLI: ``python -m apex_tpu.analysis [paths...]``.

Exit codes: 0 clean (every finding suppressed or baselined), 1 findings
(or, under ``--strict``, stale baseline entries), 2 usage errors.

Configuration rides in ``[tool.apexlint]`` in pyproject.toml (paths,
exclude, baseline, disable); Python 3.10 has no tomllib, so a minimal
single-section reader handles the flat keys apexlint uses.
"""

from __future__ import annotations

import argparse
import ast as _ast
import json
import os
import re
import sys

from apex_tpu.analysis.core import (Baseline, all_rules, analyze_paths)

DEFAULT_BASELINE = ".apexlint-baseline.json"


def find_project_root(start: str | None = None) -> str | None:
    """Nearest ancestor of ``start`` (default cwd) holding pyproject.toml."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isfile(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


_SECTION_RE = re.compile(r"^\s*\[(?P<name>[^\]]+)\]\s*$")
_KEY_RE = re.compile(r"^\s*(?P<key>[A-Za-z0-9_-]+)\s*=\s*(?P<val>.+)$")


def load_config(root: str | None) -> dict:
    """Flat ``[tool.apexlint]`` keys from pyproject.toml.  Values are
    strings or arrays of strings (whose literal syntax TOML shares with
    Python); anything fancier is ignored."""
    cfg: dict = {}
    if root is None:
        return cfg
    path = os.path.join(root, "pyproject.toml")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return cfg
    in_section = False
    buf = ""
    key = None
    for line in lines:
        m = _SECTION_RE.match(line)
        if m:
            in_section = m.group("name").strip() == "tool.apexlint"
            buf, key = "", None
            continue
        if not in_section:
            continue
        if key is None:
            m = _KEY_RE.match(line)
            if not m:
                continue
            key, buf = m.group("key"), m.group("val")
        else:
            buf += " " + line.strip()
        if buf.count("[") > buf.count("]"):
            continue                      # multiline array: keep folding
        try:
            cfg[key] = _ast.literal_eval(buf.split("#")[0].strip())
        except (ValueError, SyntaxError):
            pass
        key, buf = None, ""
    return cfg


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="apexlint: JAX/TPU-aware static analysis for apex-tpu")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: [tool.apexlint] "
                        "paths, else apex_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} at "
                        f"the project root, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current unsuppressed findings into the "
                        "baseline and exit 0")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries (fixed code "
                        "must leave the ledger)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()

    if args.list_rules:
        for rid, rule in rules.items():
            print(f"{rid}  {rule.name}\n    {rule.description}")
        return 0

    root = find_project_root()
    cfg = load_config(root)

    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    disabled |= set(cfg.get("disable", []))
    unknown = disabled - set(rules)
    if unknown:
        print(f"apexlint: unknown rule id(s): {', '.join(sorted(unknown))}",
              file=sys.stderr)
        return 2
    rules = {rid: r for rid, r in rules.items() if rid not in disabled}

    paths = args.paths
    if not paths:
        # config paths are project-root-relative, not cwd-relative
        base = root or os.getcwd()
        paths = [os.path.join(base, p)
                 for p in (cfg.get("paths") or ["apex_tpu"])]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"apexlint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    exclude = tuple(cfg.get("exclude", ()))

    baseline_path = args.baseline
    if baseline_path is None and cfg.get("baseline"):
        # config baseline is project-root-relative, like config paths
        baseline_path = os.path.join(root or os.getcwd(),
                                     cfg["baseline"])
    if baseline_path is None and root is not None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        if os.path.exists(cand) or args.write_baseline:
            baseline_path = cand
    if args.no_baseline:
        baseline_path = None

    findings, suppressed = analyze_paths(paths, exclude=exclude,
                                         rules=rules, root=root)

    if args.write_baseline:
        if baseline_path is None:
            baseline_path = DEFAULT_BASELINE
        Baseline.from_findings(findings).write(baseline_path)
        print(f"apexlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path)}")
        return 0

    baseline = Baseline()
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"apexlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, baselined, stale = baseline.partition(findings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_baseline": stale,
            "summary": {"new": len(new), "baselined": len(baselined),
                        "suppressed": len(suppressed),
                        "stale_baseline": len(stale)},
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        tail = (f"{len(new)} finding(s) "
                f"({len(baselined)} baselined, "
                f"{len(suppressed)} suppressed inline)")
        if stale:
            tail += f", {len(stale)} stale baseline entr" \
                    f"{'y' if len(stale) == 1 else 'ies'}"
            if args.strict:
                for e in stale:
                    print(f"stale baseline entry: {e['rule']} {e['path']} "
                          f"{e['code']!r} x{e['count']}")
        print(f"apexlint: {tail}")

    if new:
        return 1
    if args.strict and stale:
        return 1
    return 0
