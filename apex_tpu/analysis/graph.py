"""Whole-program layer: import/symbol graph + cross-module call graph.

:class:`ProjectContext` parses the configured tree ONCE and derives the
facts no single :class:`~apex_tpu.analysis.core.ModuleContext` can hold:
which module a bare or dotted callee resolves to, which functions are
reachable from a ``threading.Thread(target=...)`` spawn anywhere in the
project, and where a symbol imported under an alias actually lives.  The
per-file rules run unchanged — ``analyze_paths`` attaches the project to
every ``ModuleContext`` as ``ctx.project``, and a rule that needs the
cross-module view reads it (``None`` when analyzing a lone snippet, so
every rule must degrade to per-file behavior).

Resolution is deliberately name-based and conservative (static analysis
cannot see through dynamic dispatch): a call edge exists only when the
callee resolves through a top-level def, a ``self.<method>`` of the
enclosing class, or an import alias to another project module.  Missing
edges make whole-program rules QUIETER, never noisier — the same
fail-silent bias as the jitted-scope heuristics in ``core.py``.

Pure stdlib, like the rest of apexlint.
"""

from __future__ import annotations

import ast
import os

__all__ = ["ModuleInfo", "ProjectContext", "modname_for"]


def modname_for(rel_path: str) -> str:
    """Dotted module name for a root-relative ``.py`` path
    (``apex_tpu/serving/shard.py`` -> ``apex_tpu.serving.shard``;
    package ``__init__.py`` collapses to the package name)."""
    p = rel_path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    mod = p.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _dotted(node: ast.AST) -> list[str] | None:
    """``a.b.c`` attribute chain as ``["a", "b", "c"]`` (None when the
    expression is not a pure name/attribute chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class ModuleInfo:
    """One module's resolution facts: import aliases and top-level defs."""

    def __init__(self, path: str, modname: str, ctx):
        self.path = path
        self.modname = modname
        self.ctx = ctx                       # the shared ModuleContext
        #: alias -> ("module", dotted modname) | ("symbol", dotted qualname)
        self.aliases: dict[str, tuple[str, str]] = {}
        #: top-level function/class name -> AST node
        self.toplevel: dict[str, ast.AST] = {}
        #: class name -> {method name -> FunctionDef}
        self.classes: dict[str, dict[str, ast.AST]] = {}
        self._collect()

    def _collect(self) -> None:
        tree = self.ctx.tree
        # relative imports anchor at the containing package: one level up
        # for a plain module, the module itself for a package __init__
        parts = self.modname.split(".")
        is_pkg = self.path.replace(os.sep, "/").endswith("/__init__.py")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    self.aliases[alias] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    drop = node.level - (1 if is_pkg else 0)
                    anchor = parts[: len(parts) - drop] if drop else parts
                    base = ".".join(anchor + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    self.aliases[alias] = ("symbol",
                                           f"{base}.{a.name}" if base
                                           else a.name)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.toplevel[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.toplevel[node.name] = node
                self.classes[node.name] = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


class ProjectContext:
    """The whole-tree view: one parse of every file, plus derived graphs.

    ``sources`` maps root-relative ``/``-separated paths to file text.
    Unparseable files are skipped here (``analyze_source`` still reports
    them as E001 on its own pass).
    """

    def __init__(self, sources: dict[str, str]):
        from apex_tpu.analysis.core import ModuleContext
        self.modules: dict[str, ModuleInfo] = {}          # rel path -> info
        self.by_modname: dict[str, ModuleInfo] = {}
        for path, source in sorted(sources.items()):
            try:
                ctx = ModuleContext(path, source)
            except (SyntaxError, ValueError):
                continue
            info = ModuleInfo(path, modname_for(path), ctx)
            self.modules[path] = info
            self.by_modname[info.modname] = info
        #: qualified def name ("mod.f" / "mod.Cls.m") -> AST node
        self.definitions: dict[str, ast.AST] = {}
        for info in self.modules.values():
            for name, node in info.toplevel.items():
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.definitions[f"{info.modname}.{name}"] = node
            for cls, methods in info.classes.items():
                for m, node in methods.items():
                    self.definitions[f"{info.modname}.{cls}.{m}"] = node
        self.import_graph = self._build_import_graph()
        self.call_graph = self._build_call_graph()
        self.thread_targets = self._collect_thread_targets()
        self.thread_reachable = self._closure(self.thread_targets)

    # -- lookup ------------------------------------------------------------

    def module_ctx(self, path: str):
        info = self.modules.get(path.replace(os.sep, "/"))
        return info.ctx if info is not None else None

    def qualname_of(self, info: ModuleInfo, fn: ast.AST) -> str:
        """Qualified name of a def inside ``info`` (class methods get the
        ``mod.Cls.m`` spelling; nested defs fold into their parent's)."""
        ctx = info.ctx
        parts = [getattr(fn, "name", "<module>")]
        for a in ctx.ancestors(fn):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
        return ".".join([info.modname] + list(reversed(parts)))

    # -- graphs ------------------------------------------------------------

    def _build_import_graph(self) -> dict[str, set[str]]:
        graph: dict[str, set[str]] = {}
        for info in self.modules.values():
            deps: set[str] = set()
            for _, (kind, target) in info.aliases.items():
                if kind == "module":
                    if target in self.by_modname:
                        deps.add(target)
                else:
                    # "symbol": the owning module is the dotted prefix
                    owner = target.rsplit(".", 1)[0]
                    if owner in self.by_modname:
                        deps.add(owner)
                    elif target in self.by_modname:      # from pkg import mod
                        deps.add(target)
            graph[info.modname] = deps
        return graph

    def resolve_callable(self, info: ModuleInfo, node: ast.AST,
                         enclosing_class: ast.ClassDef | None = None
                         ) -> str | None:
        """Qualified name a callee/target expression resolves to, or None.

        Handles: top-level names, ``self.m`` within a class, import
        aliases (``from m import f`` and ``import m as x; x.f``), and
        dotted chains through a module alias."""
        chain = _dotted(node)
        if not chain:
            return None
        head, rest = chain[0], chain[1:]
        if head == "self" and enclosing_class is not None:
            if len(rest) == 1 and rest[0] in \
                    info.classes.get(enclosing_class.name, {}):
                return f"{info.modname}.{enclosing_class.name}.{rest[0]}"
            return None
        if not rest:
            if head in info.toplevel:
                return f"{info.modname}.{head}"
            alias = info.aliases.get(head)
            if alias is not None:
                kind, target = alias
                if kind == "symbol":
                    return target
            return None
        alias = info.aliases.get(head)
        if alias is None:
            return None
        kind, target = alias
        qual = f"{target}.{'.'.join(rest)}"
        # prefer a resolution that lands on a known def; fall back to the
        # raw join so rules can still match by module prefix
        return qual

    def _build_call_graph(self) -> dict[str, set[str]]:
        graph: dict[str, set[str]] = {}
        for info in self.modules.values():
            ctx = info.ctx
            for node in ctx.nodes(ast.Call):
                fn = ctx.enclosing_function(node)
                caller = (self.qualname_of(info, fn) if fn is not None
                          else f"{info.modname}.<module>")
                cls = ctx.enclosing_class(node)
                callee = self.resolve_callable(info, node.func, cls)
                if callee is None:
                    continue
                graph.setdefault(caller, set()).add(callee)
        return graph

    def _collect_thread_targets(self) -> set[str]:
        """Qualified names handed to ``Thread(target=...)`` anywhere."""
        targets: set[str] = set()
        for info in self.modules.values():
            ctx = info.ctx
            for node in ctx.nodes(ast.Call):
                f = node.func
                basename = (f.id if isinstance(f, ast.Name)
                            else f.attr if isinstance(f, ast.Attribute)
                            else None)
                if basename != "Thread":
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    cls = ctx.enclosing_class(node)
                    qual = self.resolve_callable(info, kw.value, cls)
                    if qual is not None:
                        targets.add(qual)
        return targets

    def _closure(self, roots: set[str]) -> set[str]:
        """Call-graph closure: everything reachable from ``roots``."""
        seen: set[str] = set()
        stack = [r for r in roots]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(self.call_graph.get(q, ()))
        return seen
