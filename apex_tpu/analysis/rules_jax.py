"""J-series rules: JAX/TPU pipeline hazards.

These encode the throughput discipline the training stack already follows
by hand (``training/aql.py:153-163``, ``training/r2d2.py:265-275``): donated
step buffers, no host round-trips inside compiled code, split-don't-reuse
PRNG keys, trace-once jit.  Each rule's behavioral contract is its fixture
pair in ``tests/test_analysis.py``.
"""

from __future__ import annotations

import ast
import re

from apex_tpu.analysis.core import (Finding, ModuleContext, Rule, call_name,
                                    is_jit_expr, register)

# -- shared helpers ---------------------------------------------------------


def _is_step_name(name: str) -> bool:
    """Names that take large donated state as leading args: the train /
    fused / ingest step family.  Policy fns (params reused across calls)
    deliberately don't match."""
    n = name.lower().lstrip("_")
    if "ingest" in n:
        return True
    return "step" in n and any(t in n for t in
                               ("train", "fused", "update", "multi"))


def _has_donation(call: ast.Call) -> bool:
    return any(k.arg in ("donate_argnums", "donate_argnames")
               for k in call.keywords)


def _attr_root(node: ast.AST) -> str | None:
    """Leftmost name of an attribute chain: ``np.asarray`` -> np."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


_NUMPY_ALIASES = {"np", "numpy", "onp"}
_JNP_ALIASES = {"jnp", "jax"}


def _loops_between(ctx: ModuleContext, node: ast.AST, stop: ast.AST | None):
    """Enclosing For/While nodes of ``node`` up to (exclusive) ``stop`` or
    the enclosing function boundary.  A For whose ``iter``/``target`` holds
    the node doesn't count — that expression evaluates once, not per
    iteration (a While ``test`` does re-evaluate, so it counts)."""
    out = []
    child = node
    for a in ctx.ancestors(node):
        if a is stop:
            break
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(a, (ast.For, ast.AsyncFor)):
            if child is not a.iter and child is not a.target:
                out.append(a)
        elif isinstance(a, ast.While):
            out.append(a)
        child = a
    return out


# -- J001 -------------------------------------------------------------------


@register
class JitMissingDonation(Rule):
    id = "J001"
    name = "jit-missing-donation"
    why = ("Un-donated jit step buffers keep the old state alive across the "
           "update and double learner HBM.")
    fix = ("Pass donate_argnums for the state buffers the step consumes and "
           "rebind them from the result.")
    description = ("jit-wrapped train/ingest step without donate_argnums: "
                   "the old state buffers stay live across the update and "
                   "double learner HBM")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            if not is_jit_expr(node.func):
                continue
            if not node.args or _has_donation(node):
                continue
            tgt = node.args[0]
            if isinstance(tgt, ast.Name):
                name = tgt.id
            elif isinstance(tgt, ast.Attribute):
                name = tgt.attr
            else:
                continue                  # jit(factory(...)): not a step ref
            if is_jit_expr(tgt):          # the partial(jax.jit, ...) form
                continue
            if _is_step_name(name):
                out.append(ctx.finding(
                    self, node,
                    f"jax.jit({name}) without donate_argnums — donate the "
                    f"state args or the update keeps both copies in HBM"))
        # decorator form: @jax.jit / @partial(jax.jit, ...) on a step def
        for fn in ctx.functions:
            if not _is_step_name(fn.name):
                continue
            for dec in fn.decorator_list:
                if not is_jit_expr(dec):
                    continue
                if isinstance(dec, ast.Call) and _has_donation(dec):
                    continue
                out.append(ctx.finding(
                    self, dec,
                    f"@jit on step '{fn.name}' without donate_argnums — "
                    f"donate the state args or the update keeps both "
                    f"copies in HBM"))
        return out


# -- J002 -------------------------------------------------------------------


@register
class HostSyncInJit(Rule):
    id = "J002"
    name = "host-sync-in-jit"
    why = ("A host conversion on a traced value inside jit breaks tracing or "
           "forces a device sync.")
    fix = ("Keep the math in jnp inside the jitted scope; materialize on the "
           "host after dispatch.")
    description = ("float()/int()/bool()/.item()/np.asarray() on a traced "
                   "value inside a jitted function: forces a host-device "
                   "sync per call and serializes the pipeline")

    _BUILTINS = {"float", "int", "bool"}
    _METHODS = {"item", "tolist"}
    _NUMPY_FUNCS = {"asarray", "array"}

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            fn = ctx.in_jitted_scope(node)
            if fn is None:
                continue
            f = node.func
            if (isinstance(f, ast.Name) and f.id in self._BUILTINS
                    and node.args
                    and not all(isinstance(a, ast.Constant)
                                for a in node.args)):
                out.append(ctx.finding(
                    self, node,
                    f"{f.id}() inside jitted '{fn.name}' pulls the value "
                    f"to host — use jnp ops (or hoist out of the jit)"))
            elif (isinstance(f, ast.Attribute) and f.attr in self._METHODS
                    and not node.args):
                out.append(ctx.finding(
                    self, node,
                    f".{f.attr}() inside jitted '{fn.name}' pulls the "
                    f"value to host — keep it a traced array"))
            elif (isinstance(f, ast.Attribute)
                    and f.attr in self._NUMPY_FUNCS
                    and _attr_root(f) in _NUMPY_ALIASES):
                out.append(ctx.finding(
                    self, node,
                    f"np.{f.attr}() inside jitted '{fn.name}' materializes "
                    f"on host — use jnp.{f.attr} or hoist out of the jit"))
        return out


# -- J003 -------------------------------------------------------------------


@register
class TracedPythonBranch(Rule):
    id = "J003"
    name = "traced-python-branch"
    why = ("Python control flow on a traced value errors at trace time or "
           "silently retraces per branch.")
    fix = ("Branch with lax.cond/lax.select (or jnp.where) so the choice "
           "compiles into the program.")
    description = ("Python if/while on a traced value inside a jitted "
                   "function: either a tracer-bool error at trace time or "
                   "a silent retrace per branch — use lax.cond/lax.select")

    # parameters with these fragments are static config, not traced arrays
    _STATIC_HINTS = ("name", "axis", "mode", "dtype", "shape", "static",
                     "interpret", "config", "cfg", "spec")

    def _is_static_param(self, name: str) -> bool:
        n = name.lower()
        return n == "self" or any(h in n for h in self._STATIC_HINTS)

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.If, ast.While):
            fn = ctx.in_jitted_scope(node)
            if fn is None:
                continue
            why = self._traced_test(node.test, fn)
            if why:
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append(ctx.finding(
                    self, node,
                    f"Python {kind} on {why} inside jitted '{fn.name}' — "
                    f"use jax.lax.cond/select (or make the arg static)"))
        return out

    def _traced_test(self, test: ast.AST, fn) -> str | None:
        # identity tests and isinstance are static dispatch — fine
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                return None
            if (isinstance(n, ast.Call)
                    and call_name(n) in ("isinstance", "hasattr",
                                         "getattr", "len")):
                return None
        params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                  + fn.args.posonlyargs)
                  if not self._is_static_param(a.arg)}
        for n in ast.walk(test):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                    and _attr_root(n.func) in _JNP_ALIASES):
                return f"a {_attr_root(n.func)}.* result"
            if isinstance(n, ast.Compare):
                sides = [n.left] + list(n.comparators)
                for s in sides:
                    if isinstance(s, ast.Name) and s.id in params:
                        return f"traced arg '{s.id}'"
                    # ts.step > 0: a field of a traced arg is traced too
                    if isinstance(s, ast.Attribute) \
                            and _attr_root(s) in params:
                        return f"traced arg '{_attr_root(s)}'"
        return None


# -- J004 -------------------------------------------------------------------


#: split is NOT here: it needs a random-ish receiver (_is_key_source) or
#: str.split unpacks would mint phantom keys
_KEY_SOURCE_ATTRS = {"PRNGKey", "fold_in"}
# params opt into tracking by JAX's `key` convention only — `rng` is the
# numpy.random.Generator convention, where reuse is the whole point
_KEY_NAME_RE = re.compile(r"key", re.IGNORECASE)


def _is_key_source(call: ast.Call) -> bool:
    """jax.random.split / .key / .PRNGKey / .fold_in (any random alias)."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "split":
        # require a random-ish receiver, like `.key` below: plain
        # ``path.split(":")`` is str.split — its unpack targets are not
        # PRNG keys (the engine used to flag any later loop use of them)
        recv = f.value
        recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else "")
        return ("random" in recv_name
                or recv_name in ("jr", "jrandom", "rng"))
    if f.attr in _KEY_SOURCE_ATTRS:
        return True
    if f.attr == "key":
        # jax.random.key(...) but not cfg.key(...): require a random-ish
        # receiver
        recv = f.value
        recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else "")
        return "random" in recv_name or recv_name in ("jr", "jrandom")
    return False


@register
class PRNGKeyReuse(Rule):
    id = "J004"
    name = "prng-key-reuse"
    why = ("A PRNG key consumed twice correlates draws that must be "
           "independent.")
    fix = ("jax.random.split the key and consume each subkey exactly once "
           "(split per loop iteration).")
    description = ("a PRNG key consumed more than once (or consumed inside "
                   "a loop without a per-iteration split): correlated "
                   "randomness silently corrupts exploration and "
                   "prioritized sampling")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for fn in ctx.functions:
            # skip nested defs: the enclosing function's scan covers them
            # (their free-variable key uses belong to the outer scope)
            if ctx.enclosing_function(fn) is not None:
                continue
            out.extend(_scan_function_keys(self, ctx, fn))
        return out


def _terminates(body) -> bool:
    """A statement list that cannot fall through."""
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break,
                              ast.Continue)) for s in body)


def _scan_function_keys(rule: Rule, ctx: ModuleContext, fn) -> list[Finding]:
    """Source-order scan of one function (including nested defs): track key
    variables, count consumptions, flag the second use and any
    loop-enclosed use whose key was made outside the loop."""
    findings: list[Finding] = []
    # name -> (assignment node, uses-so-far)
    keys: dict[str, list] = {}
    for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs:
        if _KEY_NAME_RE.search(a.arg):
            keys[a.arg] = [fn, 0]

    def names_in(node: ast.AST, bound: frozenset = frozenset()):
        """Free names in an argument expression.  Does NOT descend into
        nested calls (``env.step(act(obs, k))`` charges k to ``act``
        alone) and drops names rebound by comprehension targets or lambda
        params along the way (``{k: float(v) for k, v in m.items()}``
        consumes no outer ``k``)."""
        out: set[str] = set()
        if isinstance(node, ast.Name):
            if node.id not in bound:
                out.add(node.id)
        elif isinstance(node, ast.Call):
            pass                      # every call owns its own args
        elif isinstance(node, ast.Subscript):
            pass                      # keys[i] picks one subkey from a
            #                           pre-split batch — not a reuse
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            b2 = set(bound)
            for g in node.generators:
                b2 |= {t.id for t in ast.walk(g.target)
                       if isinstance(t, ast.Name)}
            for c in ast.iter_child_nodes(node):
                out |= names_in(c, frozenset(b2))
        elif isinstance(node, ast.Lambda):
            b2 = frozenset(bound | {p.arg for p in
                                    (node.args.args + node.args.kwonlyargs
                                     + node.args.posonlyargs)})
            out |= names_in(node.body, b2)
        else:
            for c in ast.iter_child_nodes(node):
                out |= names_in(c, bound)
        return out

    def consume(name: str, at: ast.AST) -> None:
        entry = keys.get(name)
        if entry is None:
            return
        entry[1] += 1
        assigned_at, uses = entry
        if uses >= 2:
            findings.append(ctx.finding(
                rule, at,
                f"PRNG key '{name}' consumed again without "
                f"jax.random.split — every consumer needs a fresh subkey"))
            entry[1] = 1          # re-arm so each extra reuse flags once
            return
        loops = _loops_between(ctx, at, None)
        assign_loops = set(map(id, _loops_between(ctx, assigned_at, None)))
        if any(id(lp) not in assign_loops for lp in loops):
            findings.append(ctx.finding(
                rule, at,
                f"PRNG key '{name}' consumed inside a loop but created "
                f"outside it — split a fresh subkey per iteration"))
            entry[1] = 0          # one report per site, not one per use

    def comp_bound(at: ast.AST, name: str) -> bool:
        """True when ``name`` is rebound by an enclosing comprehension
        target or lambda parameter — it shadows the outer key there."""
        for a in ctx.ancestors(at):
            if isinstance(a, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                for g in a.generators:
                    if any(isinstance(t, ast.Name) and t.id == name
                           for t in ast.walk(g.target)):
                        return True
            elif isinstance(a, ast.Lambda):
                if any(p.arg == name for p in
                       (a.args.args + a.args.kwonlyargs
                        + a.args.posonlyargs)):
                    return True
            elif isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return False

    def visit_call(node: ast.Call) -> None:
        if _is_key_source(node):
            return                # split/fold_in refresh, not a consumption
        if call_name(node) in ("getattr", "hasattr", "isinstance", "len",
                               "type", "id"):
            return                # introspection reads no PRNG material
        for arg in list(node.args) + [k.value for k in node.keywords]:
            for name in names_in(arg):
                if name in keys and not comp_bound(node, name):
                    consume(name, node)

    def assign_targets(targets, value) -> None:
        from_key_source = isinstance(value, ast.Call) \
            and _is_key_source(value)
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if not isinstance(e, ast.Name):
                    continue
                if from_key_source or (e.id in keys):
                    if from_key_source:
                        keys[e.id] = [e, 0]
                    else:
                        keys.pop(e.id, None)    # overwritten by non-key

    def walk_expr(node: ast.AST) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                visit_call(n)

    def visit_stmt(stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            walk_expr(stmt.value)
            assign_targets(stmt.targets, stmt.value)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                walk_expr(stmt.value)
            assign_targets([stmt.target], stmt.value or stmt)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            walk_expr(stmt.iter)
            for s in stmt.body + stmt.orelse:
                visit_stmt(s)
        elif isinstance(stmt, ast.While):
            walk_expr(stmt.test)
            for s in stmt.body + stmt.orelse:
                visit_stmt(s)
        elif isinstance(stmt, ast.If):
            # if/else branches are mutually exclusive: one consumption in
            # each branch is one consumption at runtime, not two.  A
            # branch that terminates (return/raise/...) contributes
            # nothing to the fall-through path.
            walk_expr(stmt.test)
            snap = {k: list(v) for k, v in keys.items()}
            for s in stmt.body:
                visit_stmt(s)
            after_body = {k: list(v) for k, v in keys.items()}
            keys.clear()
            keys.update({k: list(v) for k, v in snap.items()})
            for s in stmt.orelse:
                visit_stmt(s)
            if not _terminates(stmt.body):
                for name, entry in after_body.items():
                    if name in keys:
                        keys[name][1] = max(keys[name][1], entry[1])
                    else:
                        keys[name] = entry
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                walk_expr(item.context_expr)
            for s in stmt.body:
                visit_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (stmt.body + stmt.orelse + stmt.finalbody
                      + [h for hh in stmt.handlers for h in hh.body]):
                visit_stmt(s)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: free-variable key uses count against the outer
            # scope, but its own params SHADOW same-named outer keys and
            # get their own fresh reuse budget
            params = (stmt.args.args + stmt.args.kwonlyargs
                      + stmt.args.posonlyargs)
            shadowed = {a.arg: keys.pop(a.arg) for a in params
                        if a.arg in keys}
            own = [a.arg for a in params if _KEY_NAME_RE.search(a.arg)]
            for name in own:
                keys[name] = [stmt, 0]
            for s in stmt.body:
                visit_stmt(s)
            for name in own:
                keys.pop(name, None)
            keys.update(shadowed)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if getattr(stmt, "value", None) is not None:
                walk_expr(stmt.value)
        else:
            walk_expr(stmt)

    for s in fn.body:
        visit_stmt(s)
    return findings


# -- J006 -------------------------------------------------------------------


_TIMING_CALLS = {"perf_counter", "monotonic", "perf_counter_ns",
                 "monotonic_ns", "time", "time_ns"}


def _is_trace_context(expr: ast.AST) -> bool:
    """``with trace(...)`` / ``profiling.trace(...)`` /
    ``jax.profiler.trace(...)`` — the sanctioned profiling scopes."""
    if not isinstance(expr, ast.Call):
        return False
    name = call_name(expr) or ""
    return name == "trace" or name.endswith("_trace")


@register
class HostSyncInHotLoop(Rule):
    id = "J006"
    name = "host-sync-in-hot-loop"
    why = ("A blocking device read in the hot loop serializes dispatch "
           "against the device each step.")
    fix = ("Drop the sync from the steady-state path; read results at "
           "episode/log boundaries.")
    description = ("block_until_ready()/jax.device_get() inside a host-side "
                   "loop outside profiling scopes: a full device drain per "
                   "iteration serializes the async-dispatch pipeline the "
                   "learner hot path depends on")

    def _sync_kind(self, node: ast.Call) -> str | None:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "block_until_ready":
            # jax.block_until_ready(x) and x.block_until_ready() alike
            return ("jax.block_until_ready()"
                    if _attr_root(f) in _JNP_ALIASES and node.args
                    else ".block_until_ready()")
        if f.attr == "device_get" and _attr_root(f) in _JNP_ALIASES:
            return "jax.device_get()"
        return None

    def _in_profiling_scope(self, ctx: ModuleContext, node: ast.AST,
                            loops: list) -> bool:
        # (a) lexically under `with trace(...)`: an explicit profiler
        # capture is allowed to fence the device
        for a in ctx.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(a, (ast.With, ast.AsyncWith)):
                if any(_is_trace_context(item.context_expr)
                       for item in a.items):
                    return True
        # (b) a measurement harness: some enclosing loop's body reads the
        # clock (bench-style `t0 = perf_counter(); ...; block_until_ready`)
        # — timing a device fence is the one legitimate hot-loop sync
        for loop in loops:
            for sub in ast.walk(loop):
                if (isinstance(sub, ast.Call)
                        and call_name(sub) in _TIMING_CALLS):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            kind = self._sync_kind(node)
            if kind is None:
                continue
            if ctx.in_jitted_scope(node):
                continue                     # J002's territory
            loops = _loops_between(ctx, node, None)
            if not loops:
                continue
            if self._in_profiling_scope(ctx, node, loops):
                continue
            out.append(ctx.finding(
                self, node,
                f"{kind} inside a host loop — a device drain per "
                f"iteration stalls async dispatch; stage it off the hot "
                f"loop (training/ingest_pipeline) or wrap the "
                f"measurement in a profiling trace scope"))
        return out


# -- J007 -------------------------------------------------------------------


@register
class DevicePutInJit(Rule):
    id = "J007"
    name = "device-put-in-jit"
    why = ("device_put inside compiled code is at best a redundant copy, at "
           "worst a per-call transfer.")
    fix = ("Stage operands onto the device before the dispatch and pass "
           "device arrays in.")
    description = ("jax.device_put inside jitted/shard_map scope: a "
                   "placement request inside compiled code is at best a "
                   "redundant copy and at worst a per-call transfer — "
                   "stage operands before the dispatch (the ingest "
                   "pipeline's staging thread exists for exactly this)")

    _PUT_ATTRS = {"device_put", "device_put_sharded",
                  "device_put_replicated"}

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in self._PUT_ATTRS
                    and _attr_root(f) in _JNP_ALIASES):
                continue
            fn = ctx.in_jitted_scope(node)
            if fn is None:
                continue
            out.append(ctx.finding(
                self, node,
                f"jax.{f.attr} inside jitted scope '{fn.name}' — "
                f"placement belongs before the jit/shard_map boundary; "
                f"stage the operand host-side "
                f"(training/ingest_pipeline.py staging thread)"))
        return out


# -- J008 -------------------------------------------------------------------


_MATERIALIZE_NUMPY = {"asarray", "array"}


def _jit_callable_names(ctx: ModuleContext) -> set[str]:
    """Names that dispatch compiled code when called: targets assigned from
    ``jax.jit(...)`` (``self.policy = jax.jit(...)`` -> ``policy``),
    functions passed to ``jax.jit`` by name, and ``@jit``-decorated defs.
    Deliberately NOT the transitive jitted-scope closure — calling a
    helper that jitted code also calls is a plain host call."""
    out: set[str] = set()
    for node in ctx.nodes(ast.Call):
        if not is_jit_expr(node.func):
            continue
        if node.args:
            tgt = node.args[0]
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                out.add(tgt.attr)
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, ast.Attribute):
                    out.add(t.attr)
    for fn in ctx.functions:
        if any(is_jit_expr(d) for d in fn.decorator_list):
            out.add(fn.name)
    return out


def _is_timed_context(expr: ast.AST) -> bool:
    """``with phase(...)`` / ``x.phase(...)`` or a trace scope — explicit
    wait accounting (utils/profiling.PhaseTimer), the sanctioned place to
    block on a device result."""
    if _is_trace_context(expr):
        return True
    return isinstance(expr, ast.Call) and call_name(expr) == "phase"


@register
class EagerJitMaterialize(Rule):
    id = "J008"
    name = "eager-jit-materialize"
    why = ("Materializing a jit result inline blocks the dispatch pipeline on "
           "the transfer.")
    fix = ("Keep results on device; convert to host types only where they are "
           "consumed.")
    description = ("np.asarray()/jax.device_get() materializing a jitted "
                   "result in a host step loop with the value consumed "
                   "more than one statement later: the blocking sync "
                   "serializes the dispatch pipeline against host work "
                   "that could overlap it — defer materialization to the "
                   "consumption site (the double-buffered actor step, "
                   "actors/vector.py)")

    def _materializer_args(self, call: ast.Call) -> list | None:
        f = call.func
        if not isinstance(f, ast.Attribute) or not call.args:
            return None
        if f.attr in _MATERIALIZE_NUMPY and _attr_root(f) in _NUMPY_ALIASES:
            return list(call.args)
        if f.attr == "device_get" and _attr_root(f) in _JNP_ALIASES:
            return list(call.args)
        return None

    @staticmethod
    def _stmt_position(ctx: ModuleContext, stmt: ast.AST):
        parent = ctx.parents.get(stmt)
        for field in ("body", "orelse", "finalbody"):
            seq = getattr(parent, field, None)
            if isinstance(seq, list) and stmt in seq:
                return seq, seq.index(stmt)
        return None, None

    def _in_timed_scope(self, ctx: ModuleContext, node: ast.AST) -> bool:
        for a in ctx.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(a, (ast.With, ast.AsyncWith)):
                if any(_is_timed_context(item.context_expr)
                       for item in a.items):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        jit_names = _jit_callable_names(ctx)
        if not jit_names:
            return []
        out: list[Finding] = []
        for fn in ctx.functions:
            if ctx.in_jitted_scope(fn):
                continue                      # host-side rule
            # values returned by a jit dispatch in this function
            device_vars: set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and call_name(node.value) in jit_names):
                    for t in node.targets:
                        elts = (t.elts if isinstance(t, (ast.Tuple,
                                                         ast.List))
                                else [t])
                        device_vars.update(e.id for e in elts
                                           if isinstance(e, ast.Name))
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                found = self._check_assign(ctx, fn, stmt, device_vars,
                                           jit_names)
                if found is not None:
                    out.append(found)
        return out

    def _check_assign(self, ctx, fn, stmt: ast.Assign, device_vars,
                      jit_names):
        calls = (stmt.value.elts
                 if isinstance(stmt.value, (ast.Tuple, ast.List))
                 else [stmt.value])
        sync = None
        for c in calls:
            if not isinstance(c, ast.Call):
                continue
            args = self._materializer_args(c)
            if args is None:
                continue
            refs_device = any(
                (isinstance(n, ast.Name) and n.id in device_vars)
                or (isinstance(n, ast.Call) and call_name(n) in jit_names)
                for a in args for n in ast.walk(a))
            if refs_device:
                sync = c
                break
        if sync is None or self._in_timed_scope(ctx, stmt):
            return None
        targets = {n.id for t in stmt.targets for n in ast.walk(t)
                   if isinstance(n, ast.Name)}
        seq, idx = self._stmt_position(ctx, stmt)
        if seq is None:
            return None
        consumer = None
        for dist, later in enumerate(seq[idx + 1:], start=1):
            if any(isinstance(n, ast.Name) and n.id in targets
                   for n in ast.walk(later)):
                consumer = (dist, later)
                break
        if consumer is None:
            return None
        dist, later = consumer
        if dist <= 1:
            return None                  # materialized at the use site
        hot = (bool(_loops_between(ctx, stmt, None))
               or isinstance(later, (ast.For, ast.AsyncFor, ast.While)))
        if not hot:
            return None
        return ctx.finding(
            self, sync,
            f"jitted result materialized {dist} statements before its "
            f"first use — the blocking sync runs before host work it "
            f"could overlap; defer np.asarray/device_get to the "
            f"consumption site (or wrap a deliberate wait in a "
            f"PhaseTimer.phase scope)")


# -- J009 -------------------------------------------------------------------


_QUEUE_NAME_RE = re.compile(r"(queue|_q$|^q$)", re.IGNORECASE)

#: calls that force a HOST value out of a device result — putting one of
#: these on the queue ships plain numpy/python, which is the point
_J009_MATERIALIZERS = {"asarray", "array", "device_get", "int", "float",
                       "bool", "tolist", "item"}


@register
class DeviceArrayOnMpQueue(Rule):
    id = "J009"
    name = "device-array-on-mp-queue"
    why = ("Queue.put pickles a device array, forcing an implicit "
           "device->host copy and sync.")
    fix = ("Materialize with np.asarray/jax.device_get first and enqueue the "
           "host array.")
    description = ("mp.Queue put of a jitted/device result without a host "
                   "materialize: Queue.put pickles the object, forcing an "
                   "implicit device->host copy (and a device sync) per "
                   "chunk inside the worker loop — np.asarray/device_get "
                   "it once at the producer and ship host data")

    @staticmethod
    def _queue_receiver(call: ast.Call) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("put", "put_nowait")):
            return False
        recv = f.value
        name = None
        if isinstance(recv, ast.Name):
            name = recv.id
        elif isinstance(recv, ast.Attribute):
            name = recv.attr
        return bool(name and _QUEUE_NAME_RE.search(name))

    @staticmethod
    def _materialized(ctx: ModuleContext, name_node: ast.AST,
                      put: ast.Call) -> bool:
        """True when the device name is wrapped in a materializer call
        somewhere between itself and the put() — ``q.put(np.asarray(x))``
        ships host data and is fine."""
        for a in ctx.ancestors(name_node):
            if a is put:
                return False
            if isinstance(a, ast.Call):
                base = call_name(a)
                if base in _J009_MATERIALIZERS:
                    return True
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        jit_names = _jit_callable_names(ctx)
        if not jit_names:
            return []
        out = []
        for fn in ctx.functions:
            if ctx.in_jitted_scope(fn):
                continue
            device_vars: set[str] = set()
            rematerialized: set[str] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if isinstance(node.value, ast.Call) \
                        and call_name(node.value) in jit_names:
                    for t in node.targets:
                        elts = (t.elts if isinstance(t, (ast.Tuple,
                                                         ast.List))
                                else [t])
                        device_vars.update(e.id for e in elts
                                           if isinstance(e, ast.Name))
                elif isinstance(node.value, ast.Call) \
                        and call_name(node.value) in _J009_MATERIALIZERS:
                    # `host = np.asarray(dev)` re-binds a host value:
                    # putting THAT name is fine
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            rematerialized.add(t.id)
            if not device_vars:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and self._queue_receiver(node)):
                    continue
                offenders = [
                    n for arg in node.args for n in ast.walk(arg)
                    if isinstance(n, ast.Name) and n.id in device_vars
                    and n.id not in rematerialized
                    and not self._materialized(ctx, n, node)]
                if offenders:
                    names = ", ".join(sorted({n.id for n in offenders}))
                    out.append(ctx.finding(
                        self, node,
                        f"device result(s) {names} put on an mp queue "
                        f"without a host materialize — the pickle in "
                        f"Queue.put forces a device->host copy + sync per "
                        f"message; np.asarray/device_get at the producer "
                        f"and ship host data"))
        return out


# -- J010 -------------------------------------------------------------------


#: span/ring emission calls of the obs plane (apex_tpu/obs) — host-side
#: observability primitives that record NOTHING per call once traced
_OBS_EMIT_NAMES = {"stamp", "stamp_spans", "mark_send"}
_OBS_RING_METHODS = {"complete", "complete_wall", "instant"}


@register
class HostClockInJit(Rule):
    id = "J010"
    name = "host-clock-in-jit"
    why = ("time.time() under jit bakes the trace-time clock into the "
           "compiled program as a constant.")
    fix = "Read clocks on the host and pass timestamps in as arguments."
    description = ("time.time()/time.perf_counter()/time.monotonic() (or an "
                   "obs-plane span/ring emission) inside jit/shard_map "
                   "trace scope: the clock reads at TRACE time, so every "
                   "call sees the same frozen timestamp — and a span "
                   "stamped there records nothing per step.  Hoist the "
                   "measurement to the host loop around the dispatch "
                   "(utils/profiling, apex_tpu/obs)")

    def _clock_read(self, node: ast.Call) -> str | None:
        f = node.func
        if isinstance(f, ast.Name) and f.id in _TIMING_CALLS:
            return f"{f.id}()"
        if (isinstance(f, ast.Attribute) and f.attr in _TIMING_CALLS
                and _attr_root(f) == "time"):
            return f"time.{f.attr}()"
        return None

    def _obs_emit(self, node: ast.Call) -> str | None:
        f = node.func
        name = call_name(node) or ""
        if name in _OBS_EMIT_NAMES:
            return f"{name}()"
        if (isinstance(f, ast.Attribute) and f.attr in _OBS_RING_METHODS):
            recv = f.value
            recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
            if "ring" in recv_name.lower():
                return f"{recv_name}.{f.attr}()"
        return None

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            fn = ctx.in_jitted_scope(node)
            if fn is None:
                continue
            what = self._clock_read(node) or self._obs_emit(node)
            if what is None:
                continue
            out.append(ctx.finding(
                self, node,
                f"{what} inside jitted scope '{fn.name}' reads the host "
                f"clock at trace time — the compiled program replays one "
                f"frozen timestamp per compile; measure around the "
                f"dispatch on the host loop instead"))
        return out


# -- J011 -------------------------------------------------------------------


#: the canonical fleet mesh axes, as declared by
#: apex_tpu.parallel.mesh.make_mesh — modules that import from that
#: module inherit these as their declared axis vocabulary
_CANONICAL_MESH_AXES = frozenset({"dp", "tp"})

_SPEC_CTORS = {"P", "PartitionSpec"}
_SHARD_MAP_NAMES = {"shard_map", "shard_map_compat", "pjit"}


@register
class ShardingAnnotationDrift(Rule):
    id = "J011"
    name = "sharding-annotation-drift"
    why = ("A PartitionSpec axis name no declared mesh axis matches silently "
           "degrades to replication.")
    fix = ("Name axes from the declared mesh ('dp'/'tp' in parallel/mesh.py) "
           "or extend the mesh.")
    description = ("a PartitionSpec axis name in pjit/shard_map "
                   "in/out shardings that no declared mesh axis matches "
                   "(parallel/mesh.py declares ('dp', 'tp')): the spec "
                   "silently stops sharding — or errors at dispatch — "
                   "when the annotation drifts from the mesh")

    def _declared_axes(self, ctx: ModuleContext) -> frozenset[str] | None:
        """Axis names this module's meshes declare: literal axis-name
        tuples in ``Mesh(...)`` constructions, plus the canonical
        ``make_mesh`` axes when the module uses apex_tpu.parallel.mesh.
        None = no mesh vocabulary in scope -> the rule stays silent (it
        judges drift, not style)."""
        axes: set[str] = set()
        canonical = False
        for node in ctx.nodes(ast.ImportFrom, ast.Call):
            if isinstance(node, ast.ImportFrom):
                if node.module and node.module.endswith("parallel.mesh"):
                    canonical = True
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name == "make_mesh":
                    canonical = True
                elif name == "Mesh":
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        if isinstance(arg, (ast.Tuple, ast.List)):
                            names = [e.value for e in arg.elts
                                     if isinstance(e, ast.Constant)
                                     and isinstance(e.value, str)]
                            if names and len(names) == len(arg.elts):
                                axes.update(names)
        if canonical:
            axes.update(_CANONICAL_MESH_AXES)
        return frozenset(axes) if axes else None

    def _spec_axis_names(self, call: ast.Call):
        """(axis_name, node) pairs of the string constants a
        P/PartitionSpec construction mentions (nested tuples included:
        ``P(("dp", "tp"))`` shards one dim over both axes)."""
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for n in ast.walk(arg):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    yield n.value, n

    def _annotation_scope(self, ctx: ModuleContext,
                          call: ast.Call) -> str | None:
        """The sharding-annotation surface ``call`` sits on, or None.
        Surfaces: in_specs/out_specs of shard_map (+compat) and
        in_shardings/out_shardings of jit/pjit — directly, or via a
        NamedSharding wrapping this spec anywhere (a NamedSharding is
        always a placement against a concrete mesh)."""
        for a in ctx.ancestors(call):
            if isinstance(a, ast.Call):
                name = call_name(a) or ""
                if name == "NamedSharding":
                    return "NamedSharding"
                if name in _SHARD_MAP_NAMES or is_jit_expr(a.func):
                    for kw in a.keywords:
                        if kw.arg in ("in_specs", "out_specs",
                                      "in_shardings", "out_shardings") \
                                and call in ast.walk(kw.value):
                            return f"{name}({kw.arg}=...)"
        return None

    def check(self, ctx: ModuleContext) -> list[Finding]:
        declared = self._declared_axes(ctx)
        if declared is None:
            return []
        out = []
        for node in ctx.nodes(ast.Call):
            if call_name(node) not in _SPEC_CTORS:
                continue
            scope = self._annotation_scope(ctx, node)
            if scope is None:
                continue
            for axis, at in self._spec_axis_names(node):
                if axis not in declared:
                    out.append(ctx.finding(
                        self, at,
                        f"PartitionSpec axis {axis!r} in {scope} matches "
                        f"no declared mesh axis {sorted(declared)} — the "
                        f"annotation drifted from the mesh "
                        f"(parallel/mesh.py); rename the axis or declare "
                        f"it on the Mesh"))
        return out


# -- J005 -------------------------------------------------------------------


@register
class JitInLoop(Rule):
    id = "J005"
    name = "jit-in-loop"
    why = ("jax.jit inside a loop builds a fresh callable per iteration, "
           "retracing every time.")
    fix = ("Hoist the jit to construction time and call the cached callable "
           "in the loop.")
    description = ("jax.jit(...) invoked inside a loop body: builds a fresh "
                   "wrapper (and usually retraces) every iteration — hoist "
                   "the jitted callable out of the loop")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            if not is_jit_expr(node.func):
                continue
            if _loops_between(ctx, node, None):
                out.append(ctx.finding(
                    self, node,
                    "jax.jit called inside a loop body — hoist it; each "
                    "call builds a new wrapper and retraces"))
        return out


# -- J014 -------------------------------------------------------------------


@register
class HostNumpyOpInScannedEnv(Rule):
    id = "J014"
    name = "host-numpy-op-in-scanned-env"
    why = ("Host numpy inside a scanned env step runs per step on the host, "
           "defeating the scan.")
    fix = "Express the step in jnp so lax.scan keeps the rollout on device."
    description = ("np.* / float() / .item() reachable from a function "
                   "passed to lax.scan (a scanned env/rollout body, "
                   "training/anakin.py discipline): host numpy executes at "
                   "TRACE time — a TracerError at best, a silently frozen "
                   "per-compile constant at worst.  Use jnp ops inside the "
                   "compiled rollout; hoist genuine host work out of the "
                   "scan")

    _BUILTINS = {"float", "int", "bool"}

    def _scanned_functions(self, ctx: ModuleContext) -> set:
        """FunctionDefs reachable from a ``lax.scan``/``jax.lax.scan``
        body argument: named callees, every call inside an inline lambda
        body, nested defs, and the transitive same-module call graph
        (the jitted-scope closure's discipline, re-rooted at scan)."""
        seeds: set[str] = set()
        for node in ctx.nodes(ast.Call):
            if not node.args:
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "scan"
                    and _attr_root(f) in ("lax", "jax")):
                continue
            tgt = node.args[0]
            if isinstance(tgt, ast.Name):
                seeds.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                seeds.add(tgt.attr)
            elif isinstance(tgt, ast.Lambda):
                # `lambda c, x: self._step(...)` — everything the lambda
                # calls runs inside the scanned program
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Call):
                        nm = call_name(sub)
                        if nm:
                            seeds.add(nm)
        if not seeds:
            return set()
        scanned = {fn for fn in ctx.functions if fn.name in seeds}
        by_name: dict[str, list] = {}
        for fn in ctx.functions:
            by_name.setdefault(fn.name, []).append(fn)
        changed = True
        while changed:
            changed = False
            for fn in list(scanned):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for cand in by_name.get(call_name(node) or "", []):
                        if cand not in scanned:
                            scanned.add(cand)
                            changed = True
        return scanned

    @staticmethod
    def _static_arg(a: ast.AST) -> bool:
        """Constants, attribute chains (``self.B`` — static config), and
        tuples thereof: legitimate trace-time shape/constant construction
        (``np.prod(self.frame_shape)``), not traced data."""
        if isinstance(a, ast.Constant):
            return True
        if isinstance(a, ast.Attribute):
            return _attr_root(a) is not None
        if isinstance(a, (ast.Tuple, ast.List)):
            return all(HostNumpyOpInScannedEnv._static_arg(e)
                       for e in a.elts)
        if isinstance(a, ast.UnaryOp):
            return HostNumpyOpInScannedEnv._static_arg(a.operand)
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        scanned = self._scanned_functions(ctx)
        out = []
        for fn in scanned:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                sub = ctx.enclosing_function(node)
                # nested defs inside a scanned fn are scanned too; a
                # node inside some OTHER nested non-scanned def is not
                # reachable this way unless the closure marked it
                while sub is not None and sub is not fn:
                    if sub in scanned:
                        break
                    sub = ctx.enclosing_function(sub)
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and _attr_root(f) in _NUMPY_ALIASES
                        and not all(self._static_arg(a)
                                    for a in node.args)):
                    out.append(ctx.finding(
                        self, node,
                        f"np.{f.attr}() in '{fn.name}', a lax.scan-scanned "
                        f"body — host numpy runs at trace time; use "
                        f"jnp.{f.attr} inside the compiled rollout"))
                elif (isinstance(f, ast.Name) and f.id in self._BUILTINS
                        and node.args
                        and not all(isinstance(a, ast.Constant)
                                    for a in node.args)):
                    out.append(ctx.finding(
                        self, node,
                        f"{f.id}() in '{fn.name}', a lax.scan-scanned "
                        f"body — pulls a traced value to host; keep it a "
                        f"traced array"))
                elif (isinstance(f, ast.Attribute) and f.attr == "item"
                        and not node.args):
                    out.append(ctx.finding(
                        self, node,
                        f".item() in '{fn.name}', a lax.scan-scanned "
                        f"body — pulls a traced value to host; keep it a "
                        f"traced array"))
        return out
