"""C-series rules: process/thread/socket/shared-memory lifecycle hazards.

These encode the runtime's hard-won discipline: spawn-context worker pools
around live threads (``actors/pool.py`` module docstring), close-on-every-
exit-path ZMQ sockets (``runtime/transport.py``), and the creator-owns-
unlink shared-memory contract (``native/ring.py``).  Contracts are the
fixture pairs in ``tests/test_analysis.py``.
"""

from __future__ import annotations

import ast

from apex_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                    register)

# -- shared helpers ---------------------------------------------------------


def _callee_basename(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _kwarg(call: ast.Call, name: str):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_true(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a ``self.x`` expression."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _stmt_order(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


# -- C001 -------------------------------------------------------------------


@register
class ForkAfterThread(Rule):
    id = "C001"
    name = "fork-after-thread"
    why = ("fork after threads are live copies their lock state into the "
           "child and deadlocks it.")
    fix = ("Set the spawn/forkserver start method, or start processes before "
           "any thread.")
    description = ("multiprocessing.Process started after a threading."
                   "Thread is live, with no spawn/forkserver start method "
                   "in sight: fork copies the lock state of invisible "
                   "threads and deadlocks the child")

    _SAFE_METHODS = ("spawn", "forkserver")

    def _file_pins_safe_start(self, ctx: ModuleContext) -> bool:
        for node in ctx.nodes(ast.Call):
            if _callee_basename(node) in ("get_context",
                                          "set_start_method"):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value in self._SAFE_METHODS:
                    return True
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        if self._file_pins_safe_start(ctx):
            return []
        out = []
        for fn in ctx.functions:
            out.extend(self._scan_scope(ctx, fn.body, owner=fn))
        out.extend(self._scan_scope(ctx, ctx.tree.body, owner=None))
        return out

    def _scan_scope(self, ctx: ModuleContext, body,
                    owner=None) -> list[Finding]:
        """Linear scan of one scope: var kinds from Thread(...)/Process(...)
        constructions, then .start() events in source order.  Only nodes
        whose enclosing function is exactly ``owner`` belong to this scope
        — a thread started in one function and a process in another are
        different (runtime-unordered) scopes."""
        kinds: dict[str, str] = {}      # var -> "thread" | "process"
        events: list[tuple[tuple, str, ast.AST]] = []

        def kind_of(call: ast.Call) -> str | None:
            base = _callee_basename(call)
            if base == "Thread":
                return "thread"
            if base == "Process":
                return "process"
            return None

        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if ctx.enclosing_function(node) is not owner:
                    continue
                k = kind_of(node)
                if k is not None:
                    parent = ctx.parents.get(node)
                    if isinstance(parent, ast.Assign):
                        for t in parent.targets:
                            if isinstance(t, ast.Name):
                                kinds[t.id] = k
                            a = _self_attr(t)
                            if a:
                                kinds["self." + a] = k
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute) and f.attr == "start"):
                    continue
                recv = f.value
                recv_kind = None
                if isinstance(recv, ast.Call):        # Thread(...).start()
                    recv_kind = kind_of(recv)
                elif isinstance(recv, ast.Name):
                    recv_kind = kinds.get(recv.id)
                else:
                    a = _self_attr(recv)
                    if a:
                        recv_kind = kinds.get("self." + a)
                if recv_kind:
                    events.append((_stmt_order(node), recv_kind, node))

        events.sort(key=lambda e: e[0])
        out = []
        thread_live = False
        for _, kind, node in events:
            if kind == "thread":
                thread_live = True
            elif kind == "process" and thread_live:
                out.append(ctx.finding(
                    self, node,
                    "Process.start() after a Thread is live in this scope "
                    "— fork inherits the thread's lock state and can "
                    "deadlock; use mp.get_context('spawn') (or start "
                    "processes first)"))
        return out


# -- C002 -------------------------------------------------------------------


class _LifecycleRule(Rule):
    """Shared machinery for resource-lifecycle rules (C002/C003): a
    resource constructed in a scope must be released in that scope (local
    var) or by a teardown method of the owning class (``self.x``); values
    that escape (returned / stored elsewhere / passed on) are the
    receiver's problem."""

    #: attribute calls that count as releasing the resource
    release_attrs: frozenset = frozenset()

    def _is_resource_call(self, node: ast.Call, ctx: ModuleContext) -> bool:
        raise NotImplementedError

    def _message(self, where: str) -> str:
        raise NotImplementedError

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for fn in ctx.functions:
            out.extend(self._check_function(ctx, fn))
        return out

    def _check_function(self, ctx: ModuleContext, fn) -> list[Finding]:
        out = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and self._is_resource_call(node, ctx)):
                continue
            if ctx.enclosing_function(node) is not fn:
                continue                       # nested def handles its own
            parent = ctx.parents.get(node)
            if isinstance(parent, ast.withitem):
                continue                       # context manager releases
            if not isinstance(parent, ast.Assign):
                # constructed and passed/returned inline: escapes
                continue
            local, attr = None, None
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    local = t.id
                attr = attr or _self_attr(t)
            if attr is not None:
                if not self._class_releases(ctx, node, attr):
                    out.append(ctx.finding(
                        self, node, self._message(f"self.{attr}")))
            elif local is not None:
                if not self._function_releases(fn, local):
                    out.append(ctx.finding(
                        self, node, self._message(local)))
        return out

    def _function_releases(self, fn, var: str) -> bool:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.release_attrs
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == var):
                return True
            # escapes: returned, yielded, or handed to another owner
            if (isinstance(node, (ast.Return, ast.Yield))
                    and node.value is not None
                    and any(isinstance(n, ast.Name) and n.id == var
                            for n in ast.walk(node.value))):
                return True
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == var:
                        return True
            if isinstance(node, ast.Assign) and any(
                    not isinstance(t, ast.Name)
                    for t in node.targets) and any(
                    isinstance(n, ast.Name) and n.id == var
                    for n in ast.walk(node.value)):
                return True                    # stored into a structure
        return False

    def _class_releases(self, ctx: ModuleContext, node: ast.AST,
                        attr: str) -> bool:
        cls = ctx.enclosing_class(node)
        if cls is None:
            return True                        # module-level self? bail out
        for n in ast.walk(cls):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in self.release_attrs):
                recv = n.func.value
                if _self_attr(recv) == attr:
                    return True
                # released through iteration (`for q in [self.x, ...]:`)
                if isinstance(recv, ast.Name) and \
                        self._released_via_alias(cls, attr, recv.id):
                    return True
        return False

    @staticmethod
    def _released_via_alias(cls, attr: str, alias: str) -> bool:
        for n in ast.walk(cls):
            if isinstance(n, (ast.For, ast.comprehension)):
                tgt = n.target
                if isinstance(tgt, ast.Name) and tgt.id == alias:
                    for sub in ast.walk(n.iter):
                        if _self_attr(sub) == attr:
                            return True
        return False


@register
class ZmqSocketLeak(_LifecycleRule):
    id = "C002"
    name = "zmq-socket-leak"
    why = ("An unclosed zmq socket or context leaks its fd and can hang "
           "interpreter shutdown.")
    fix = ("close(linger=0)/term in a finally block, or tie the socket to the "
           "owner's close().")
    description = ("zmq socket/context created without close()/term() on "
                   "an exit path: lingering sockets hold ports and peer "
                   "connections past role death (transport.py closes every "
                   "socket it binds, including on the error path)")

    release_attrs = frozenset({"close", "term", "destroy", "stop",
                               "cleanup"})

    def _is_resource_call(self, node: ast.Call, ctx: ModuleContext) -> bool:
        # <ctx>.socket(zmq.ROUTER)-shaped creations (shared with J013)
        if _is_zmq_socket_call(node):
            return True
        f = node.func
        # zmq.Context() construction (NOT .instance(): shared singleton)
        if isinstance(f, ast.Attribute) and f.attr == "Context" \
                and isinstance(f.value, ast.Name) and f.value.id == "zmq":
            return True
        return False

    def _message(self, where: str) -> str:
        return (f"zmq socket bound to {where} has no close()/term() on any "
                f"exit path — close it in a finally/cleanup or the port "
                f"and peer connections leak")


# -- C003 -------------------------------------------------------------------


def _is_shm_ctor(node: ast.Call) -> bool:
    base = _callee_basename(node) or ""
    return ("SharedMemory" in base or "ShmRing" in base
            or base.startswith("shm_") or base.endswith("_shm"))


@register
class ShmLifecycle(_LifecycleRule):
    id = "C003"
    name = "shm-lifecycle"
    why = ("A created shared-memory segment with no matching unlink leaks "
           "/dev/shm until reboot.")
    fix = "The creator unlinks in its cleanup path; attachers only close()."
    description = ("shared-memory segment created (create=True) without "
                   "close()/unlink() in its owning scope: the segment "
                   "outlives the process in /dev/shm (ring.py contract: "
                   "the creator owns the segment and unlinks it on close)")

    release_attrs = frozenset({"close", "unlink", "cleanup", "stop"})

    def _is_resource_call(self, node: ast.Call, ctx: ModuleContext) -> bool:
        return _is_shm_ctor(node) and _is_true(_kwarg(node, "create"))

    def _message(self, where: str) -> str:
        return (f"shm segment created into {where} with create=True but "
                f"never closed/unlinked in its owning scope — the segment "
                f"leaks in /dev/shm on every run")


@register
class ShmForeignUnlink(Rule):
    id = "C004"
    name = "shm-foreign-unlink"
    why = ("Unlinking a segment this module only attached destroys it under "
           "its real owner.")
    fix = ("Only the creating module unlinks; attachers close() and leave "
           "lifecycle to the owner.")
    description = ("unlink() on a shared-memory segment this scope only "
                   "OPENED (create=False): unlinking from a non-creator "
                   "yanks the segment out from under the owner and every "
                   "sibling (ring.py contract: creator owns unlink)")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        # class-level map: attr -> created-here?
        created_attrs: dict[str, dict[str, bool]] = {}
        for cls in ctx.nodes(ast.ClassDef):
            attrs: dict[str, bool] = {}
            for n in ast.walk(cls):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call) and \
                        _is_shm_ctor(n.value):
                    for t in n.targets:
                        a = _self_attr(t)
                        if a:
                            attrs[a] = attrs.get(a, False) or \
                                _is_true(_kwarg(n.value, "create"))
            created_attrs[cls.name] = attrs

        for fn in ctx.functions:
            local_shm: dict[str, bool] = {}     # var -> created?
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _is_shm_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_shm[t.id] = _is_true(
                                _kwarg(node.value, "create"))
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "unlink"
                        and not node.args):
                    continue
                recv = node.func.value
                if self._owner_guarded(ctx, node):
                    continue
                if isinstance(recv, ast.Name):
                    if recv.id in local_shm and not local_shm[recv.id]:
                        out.append(ctx.finding(
                            self, node,
                            f"'{recv.id}.unlink()' but this scope opened "
                            f"the segment with create=False — only the "
                            f"creator unlinks (ring.py contract)"))
                else:
                    a = _self_attr(recv)
                    cls = ctx.enclosing_class(node)
                    if a and cls is not None:
                        attrs = created_attrs.get(cls.name, {})
                        if a in attrs and not attrs[a]:
                            out.append(ctx.finding(
                                self, node,
                                f"'self.{a}.unlink()' but this class only "
                                f"opens the segment (create=False) — only "
                                f"the creator unlinks (ring.py contract)"))
        return out

    @staticmethod
    def _owner_guarded(ctx: ModuleContext, node: ast.AST) -> bool:
        """unlink under ``if self._owner:``-style guards is the documented
        creator path even when the create= flag is runtime-determined."""
        for a in ctx.ancestors(node):
            if isinstance(a, ast.If):
                src_names = {n.attr if isinstance(n, ast.Attribute) else
                             getattr(n, "id", "")
                             for n in ast.walk(a.test)}
                if any("owner" in s or "creator" in s or "created" in s
                       for s in src_names if s):
                    return True
        return False


# -- C005 -------------------------------------------------------------------


@register
class NakedPickleLoads(Rule):
    id = "C005"
    name = "naked-pickle-loads"
    why = ("pickle.loads on wire bytes is arbitrary code execution in the "
           "receiving process.")
    fix = ("Route deserialization through runtime/wire.py's restricted "
           "unpickler.")
    description = ("pickle.loads / pickle.Unpickler outside the allowlisted "
                   "unpickler module (apex_tpu/runtime/wire.py): a bare "
                   "unpickle of cross-process bytes is arbitrary code "
                   "execution on a network/IPC boundary — route through "
                   "apex_tpu.runtime.wire.restricted_loads")

    #: THE designated unpickler module — the one place a raw Unpickler is
    #: allowed to exist (it is the thing implementing the allowlist)
    ALLOWED_SUFFIX = "runtime/wire.py"

    def _is_naked_load(self, node: ast.Call) -> str | None:
        f = node.func
        if not isinstance(f, ast.Attribute):
            # bare `Unpickler(...)` from `from pickle import Unpickler`
            if isinstance(f, ast.Name) and f.id == "Unpickler":
                return "Unpickler"
            return None
        root = f.value
        is_pickle_mod = (isinstance(root, ast.Name)
                         and root.id in ("pickle", "cPickle"))
        if f.attr in ("loads", "load") and is_pickle_mod:
            return f"pickle.{f.attr}"
        if f.attr == "Unpickler" and is_pickle_mod:
            return "pickle.Unpickler"
        return None

    def check(self, ctx: ModuleContext) -> list[Finding]:
        if ctx.path.replace("\\", "/").endswith(self.ALLOWED_SUFFIX):
            return []
        out = []
        for node in ctx.nodes(ast.Call):
            what = self._is_naked_load(node)
            if what is None:
                continue
            out.append(ctx.finding(
                self, node,
                f"{what} outside the allowlisted unpickler module — "
                f"deserializing cross-process bytes executes arbitrary "
                f"__reduce__ payloads; use "
                f"apex_tpu.runtime.wire.restricted_loads (add new message "
                f"types to its allowlist, don't bypass it)"))
        return out


# -- shared zmq-socket detection (C002 + J013) ------------------------------


def _is_zmq_socket_call(node: ast.Call) -> bool:
    """``<ctx>.socket(zmq.X)``-shaped creations (the C002 detection,
    factored out so J013 tracks the same attribute population)."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "socket"):
        return False
    for arg in node.args:
        root = arg
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id == "zmq":
            return True
    recv = f.value
    if isinstance(recv, ast.Name) and recv.id in ("zmq", "ctx", "context"):
        return True
    if isinstance(recv, ast.Call):
        base = _callee_basename(recv) or ""
        return "ctx" in base.lower() or "context" in base.lower() \
            or base == "instance"
    return False


# -- J012 -------------------------------------------------------------------


def _is_port_name(name: str) -> bool:
    return name.endswith("_port") or name.endswith("_port_base")


@register
class PortCollision(Rule):
    id = "J012"
    name = "port-collision"
    why = ("Two roles bound to one literal port collide at bind time when "
           "co-hosted.")
    fix = ("Derive every port from CommsConfig offsets so the topology "
           "allocates uniquely.")
    description = ("two roles config-bound to the same literal port in one "
                   "topology: a CommsConfig-style construction (or config "
                   "class body) assigning the same constant to two "
                   "*_port/*_port_base fields — the second bind dies with "
                   "EADDRINUSE on one host, or two fleets silently "
                   "cross-talk on separate hosts")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for node in ctx.nodes(ast.Call):
            out.extend(self._check_call(ctx, node))
        for node in ctx.nodes(ast.ClassDef):
            out.extend(self._check_class(ctx, node))
        return out

    def _collide(self, ctx: ModuleContext, node: ast.AST,
                 ports: dict[str, int]) -> list[Finding]:
        """One finding per duplicated literal value among ``ports``
        (field -> constant).  Port 0 is exempt: it means ephemeral/
        disabled, and N disabled planes are not one topology."""
        by_value: dict[int, list[str]] = {}
        for field, value in ports.items():
            if value:
                by_value.setdefault(value, []).append(field)
        out = []
        for value, fields in sorted(by_value.items()):
            if len(fields) > 1:
                out.append(ctx.finding(
                    self, node,
                    f"port collision: {', '.join(sorted(fields))} all "
                    f"bound to {value} in one topology — every role "
                    f"needs its own port (the second bind dies with "
                    f"EADDRINUSE, or streams cross-talk)"))
        return out

    def _check_call(self, ctx: ModuleContext,
                    call: ast.Call) -> list[Finding]:
        ports = {k.arg: k.value.value for k in call.keywords
                 if k.arg is not None and _is_port_name(k.arg)
                 and isinstance(k.value, ast.Constant)
                 and isinstance(k.value.value, int)}
        return self._collide(ctx, call, ports) if len(ports) > 1 else []

    def _check_class(self, ctx: ModuleContext,
                     cls: ast.ClassDef) -> list[Finding]:
        """Config-dataclass bodies: two port FIELDS defaulting to the
        same literal are a collision baked into every fleet built from
        the class."""
        ports: dict[str, int] = {}
        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    _is_port_name(stmt.target.id) and \
                    isinstance(stmt.value, ast.Constant) and \
                    isinstance(stmt.value.value, int):
                ports[stmt.target.id] = stmt.value.value
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and _is_port_name(t.id) \
                            and isinstance(stmt.value, ast.Constant) \
                            and isinstance(stmt.value.value, int):
                        ports[t.id] = stmt.value.value
        return self._collide(ctx, cls, ports) if len(ports) > 1 else []


# -- J013 -------------------------------------------------------------------


@register
class ZmqThreadAffinity(Rule):
    id = "J013"
    name = "zmq-thread-affinity"
    why = ("A zmq socket is thread-bound; touching it from two thread entries "
           "corrupts the channel.")
    fix = ("Give each thread its own socket, or marshal through the owning "
           "thread's queue.")
    description = ("a zmq socket attribute of one class is touched from "
                   "two different thread-entry methods (Thread targets): "
                   "zmq sockets are not thread-safe, and concurrent use "
                   "from two threads corrupts the socket state — route "
                   "one thread's work through a queue the other drains "
                   "(the ChunkReceiver ack-queue pattern)")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out = []
        for cls in ctx.nodes(ast.ClassDef):
            out.extend(self._check_class(ctx, cls))
        return out

    @staticmethod
    def _methods(cls: ast.ClassDef) -> dict[str, ast.AST]:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    @staticmethod
    def _socket_attrs(cls: ast.ClassDef) -> set[str]:
        """Attributes assigned from a zmq socket creation anywhere in the
        class body (``self.x = ctx.socket(zmq.ROUTER)``)."""
        attrs: set[str] = set()
        for n in ast.walk(cls):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _is_zmq_socket_call(n.value):
                for t in n.targets:
                    a = _self_attr(t)
                    if a:
                        attrs.add(a)
        return attrs

    @staticmethod
    def _thread_entries(cls: ast.ClassDef,
                        methods: dict[str, ast.AST]) -> list[str]:
        """Methods handed to ``threading.Thread(target=self.m)`` inside
        the class — each is one thread's entry point."""
        entries = []
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call)
                    and _callee_basename(n) == "Thread"):
                continue
            target = _kwarg(n, "target")
            if target is None:
                continue
            m = _self_attr(target)
            if m and m in methods and m not in entries:
                entries.append(m)
        return entries

    @classmethod
    def _reachable(cls_, entry: str,
                   methods: dict[str, ast.AST]) -> set[str]:
        """Intra-class call-graph closure from ``entry``: a socket touch
        in a helper belongs to every thread whose entry reaches it."""
        seen, stack = set(), [entry]
        while stack:
            m = stack.pop()
            if m in seen or m not in methods:
                continue
            seen.add(m)
            for n in ast.walk(methods[m]):
                if isinstance(n, ast.Call):
                    callee = _self_attr(n.func)
                    if callee and callee in methods:
                        stack.append(callee)
        return seen

    @staticmethod
    def _touched(method: ast.AST, socket_attrs: set[str]) -> set[str]:
        out = set()
        for n in ast.walk(method):
            a = _self_attr(n) if isinstance(n, ast.Attribute) else None
            if a in socket_attrs:
                out.add(a)
        return out

    def _check_class(self, ctx: ModuleContext,
                     cls: ast.ClassDef) -> list[Finding]:
        socket_attrs = self._socket_attrs(cls)
        if not socket_attrs:
            return []
        methods = self._methods(cls)
        entries = self._thread_entries(cls, methods)
        if len(entries) < 2:
            return []               # one thread (or none) cannot race
        touched_by: dict[str, list[str]] = {}
        for entry in entries:
            reach = self._reachable(entry, methods)
            for m in reach:
                for attr in self._touched(methods[m], socket_attrs):
                    owners = touched_by.setdefault(attr, [])
                    if entry not in owners:
                        owners.append(entry)
        out = []
        for attr in sorted(touched_by):
            owners = touched_by[attr]
            if len(owners) > 1:
                out.append(ctx.finding(
                    self, cls,
                    f"zmq socket 'self.{attr}' of {cls.name} is touched "
                    f"from {len(owners)} thread-entry methods "
                    f"({', '.join(sorted(owners))}) — zmq sockets are "
                    f"single-threaded; keep one owning thread and hand "
                    f"the others a queue (ChunkReceiver routes decoder "
                    f"acks through _ack_q for exactly this reason)"))
        return out


# -- J015 -------------------------------------------------------------------


@register
class UnregisteredGauge(Rule):
    id = "J015"
    name = "unregistered-gauge"
    why = ("A gauge key outside the registry silently vanishes from "
           "exposition and alerting.")
    fix = ("Declare the key in apex_tpu.obs.metrics (REGISTERED_GAUGES / "
           "REGISTERED_FAMILIES) first.")
    description = ("a literal heartbeat-gauge key or Prometheus "
                   "exposition family name outside the declared metric "
                   "registry (apex_tpu.obs.metrics REGISTERED_GAUGES / "
                   "REGISTERED_FAMILIES): an undeclared metric is "
                   "silently unscrapeable — the status table shows it, "
                   "but the SLO engine, dashboards, and alert rules can "
                   "never address it by name.  Register the key next to "
                   "its emitter")

    #: exposition dict kwargs with FIXED family-name keys (``gauges=``
    #: stays exempt: production gauge names there are dynamic scalar
    #: tails, not a closed registry)
    _RENDER_KWARGS = ("counters", "histograms", "labeled")

    @staticmethod
    def _registries() -> tuple[frozenset, frozenset] | None:
        """The declared registry, imported from the real module (pure
        stdlib — obs.metrics imports only ``re``); None disables the
        rule rather than inventing an empty registry that would flag
        every gauge in sight."""
        try:
            from apex_tpu.obs.metrics import (REGISTERED_FAMILIES,
                                              REGISTERED_GAUGES)
            return REGISTERED_GAUGES, REGISTERED_FAMILIES
        except Exception:
            return None

    @staticmethod
    def _dict_assigns(fn: ast.AST) -> dict[str, list[ast.Dict]]:
        """name -> dict-literal assignments inside one function (the
        one-hop local dataflow the rule follows)."""
        out: dict[str, list[ast.Dict]] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, []).append(n.value)
        return out

    def _resolve_dicts(self, value: ast.AST,
                       local: dict[str, list[ast.Dict]]) -> list[ast.Dict]:
        """Dict literals a sink argument resolves to: the literal
        itself, a local name assigned one, or a lambda returning one."""
        if isinstance(value, ast.Dict):
            return [value]
        if isinstance(value, ast.Name):
            return local.get(value.id, [])
        if isinstance(value, ast.Lambda) and isinstance(value.body,
                                                        ast.Dict):
            return [value.body]
        return []

    @staticmethod
    def _returned_dicts(fn: ast.AST) -> list[ast.Dict]:
        """Dict literals a function returns (directly or via one local
        assignment)."""
        local = UnregisteredGauge._dict_assigns(fn)
        out: list[ast.Dict] = []
        for n in ast.walk(fn):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            if isinstance(n.value, ast.Dict):
                out.append(n.value)
            elif isinstance(n.value, ast.Name):
                out.extend(local.get(n.value.id, []))
        return out

    def _check_keys(self, ctx: ModuleContext, d: ast.Dict,
                    registry: frozenset, what: str,
                    out: list[Finding]) -> None:
        for key in d.keys:
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)):
                continue            # dynamic keys: not literal dataflow
            if key.value not in registry:
                out.append(ctx.finding(
                    self, key,
                    f"{what} key '{key.value}' is not in the declared "
                    f"metric registry (apex_tpu.obs.metrics) — register "
                    f"it there or the SLO/scrape planes can never "
                    f"address it"))

    def check(self, ctx: ModuleContext) -> list[Finding]:
        regs = self._registries()
        if regs is None:
            return []
        gauges_reg, families_reg = regs
        out: list[Finding] = []
        by_name: dict[str, list] = {}
        for fn in ctx.functions:
            by_name.setdefault(fn.name, []).append(fn)
        # 1) functions literally named `gauges` (the infer server/client
        #    convention) — their returned dict literals ARE gauge sets
        for fn in by_name.get("gauges", []):
            for d in self._returned_dicts(fn):
                self._check_keys(ctx, d, gauges_reg, "heartbeat gauge",
                                 out)
        seen_fn_targets: set[str] = set()
        # _dict_assigns walks the whole enclosing function: memoize it
        # per scope (and skip it entirely for sink-free calls) or the
        # rule goes quadratic in function size over call-heavy modules
        local_cache: dict[ast.AST, dict] = {}

        def local_for(node: ast.Call) -> dict:
            fn_scope = ctx.enclosing_function(node)
            if fn_scope is None:
                return {}
            got = local_cache.get(fn_scope)
            if got is None:
                got = local_cache[fn_scope] = self._dict_assigns(fn_scope)
            return got

        for node in ctx.nodes(ast.Call):
            # 2) Heartbeat(gauges={...}) and gauges_fn=... sinks
            gv = _kwarg(node, "gauges")
            if gv is not None and _callee_basename(node) == "Heartbeat":
                for d in self._resolve_dicts(gv, local_for(node)):
                    self._check_keys(ctx, d, gauges_reg,
                                     "heartbeat gauge", out)
            gf = _kwarg(node, "gauges_fn")
            if gf is not None:
                for d in self._resolve_dicts(gf, local_for(node)):
                    self._check_keys(ctx, d, gauges_reg,
                                     "heartbeat gauge", out)
                # a named/bound hook (`gauges_fn=self.ondevice_counters`)
                # resolves to the module function of that name
                name = (gf.id if isinstance(gf, ast.Name)
                        else gf.attr if isinstance(gf, ast.Attribute)
                        else None)
                if name and name not in seen_fn_targets:
                    seen_fn_targets.add(name)
                    for fn in by_name.get(name, []):
                        for d in self._returned_dicts(fn):
                            self._check_keys(ctx, d, gauges_reg,
                                             "heartbeat gauge", out)
            # 3) exposition family names: dict literals handed to
            #    render(counters=/histograms=/labeled=)
            if _callee_basename(node) == "render":
                for kw in self._RENDER_KWARGS:
                    v = _kwarg(node, kw)
                    if v is None:
                        continue
                    for d in self._resolve_dicts(v, local_for(node)):
                        self._check_keys(ctx, d, families_reg,
                                         "exposition family", out)
        # 4) exposition builders: render_*/prometheus_* functions that
        #    ASSEMBLE the (gauges, labeled) sections other modules hand
        #    to render() — their literal dicts bound to the section
        #    names are family declarations too
        for fn in ctx.functions:
            if not fn.name.startswith(("render_", "prometheus")):
                continue
            local = self._dict_assigns(fn)
            # builder scope includes `gauges`: here the names ARE fixed
            # families (slo_severity...), unlike render()'s dynamic
            # scalar-tail gauges
            for kw in self._RENDER_KWARGS + ("gauges",):
                for d in local.get(kw, []):
                    self._check_keys(ctx, d, families_reg,
                                     "exposition family", out)
        return out


# -- J016 -------------------------------------------------------------------


@register
class RawEpochComparison(Rule):
    id = "J016"
    name = "raw-epoch-comparison"
    why = ("Raw ordering comparisons on epoch/version counters re-derive the "
           "fence protocol ad hoc.")
    fix = ("Compare through serving/fence.py's helpers, the one audited "
           "ordering site.")
    description = ("an ordering comparison (<, <=, >, >=) on a "
                   "learner_epoch/param_version attribute outside the "
                   "model-version fencing helpers (apex_tpu/serving/"
                   "fence.py): model versions order as the lexicographic "
                   "(epoch, version) pair — epoch-major — and a scattered "
                   "raw comparison is how a rollback path serves a dead "
                   "life's params or rejects a restored incumbent as "
                   "stale.  Route the comparison through "
                   "apex_tpu.serving.fence (fence_key/beyond/"
                   "newer_epoch/stale_epoch)")

    #: the fenced names — the wire-visible model-version components
    _NAMES = frozenset({"learner_epoch", "param_version"})
    #: THE fencing helper module: the one place raw ordering may live
    _EXEMPT = ("apex_tpu/serving/fence.py", "serving/fence.py")

    @staticmethod
    def _fenced_name(node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute):
            return (node.attr
                    if node.attr in RawEpochComparison._NAMES else None)
        if isinstance(node, ast.Name):
            return (node.id
                    if node.id in RawEpochComparison._NAMES else None)
        return None

    def check(self, ctx: ModuleContext) -> list[Finding]:
        import os as _os
        path = ctx.path.replace(_os.sep, "/")
        if path.endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ctx.nodes(ast.Compare):
            if not any(isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                       for op in node.ops):
                continue            # ==/!= identity checks are fine
            comparands = (node.left, *node.comparators)
            if all(self._fenced_name(c) is not None
                   or isinstance(c, ast.Constant) for c in comparands) \
                    and any(isinstance(c, ast.Constant)
                            for c in comparands):
                # ordering against a LITERAL (`param_version >= 2`, the
                # test-suite progress assertions) cannot smuggle a dead
                # life's value — the hazard is ordering two epoch/
                # version VARIABLES across lifetimes
                continue
            for comparand in comparands:
                name = self._fenced_name(comparand)
                if name is not None:
                    out.append(ctx.finding(
                        self, node,
                        f"ordering comparison on '{name}' outside the "
                        f"fencing helpers — epochs/versions order as the "
                        f"(epoch, version) pair; use "
                        f"apex_tpu.serving.fence"))
                    break           # one finding per comparison
        return out


# -- J017 -------------------------------------------------------------------


@register
class CrossTenantId(Rule):
    id = "J017"
    name = "cross-tenant-id"
    why = ("Hand-joined tenant identifiers drift from the namespace grammar "
           "and can cross tenants.")
    fix = ("Build ids with tenancy/namespace.py helpers (qualify/chunk_id), "
           "never by string concat.")
    description = ("a tenant-qualified identifier built by string "
                   "concatenation/formatting (a tenant value joined to "
                   "identity/chunk-id/topic parts with the namespace "
                   "separators '/' or '|') outside the tenancy "
                   "namespacing helpers (apex_tpu/tenancy/namespace.py): "
                   "the id grammar — tenant/base identities, "
                   "identity:seq chunk ids, apxt/<tenant>| param topics "
                   "— must have exactly ONE construction site, or two "
                   "planes eventually disagree on where a tenant's data "
                   "lives and one tenant's traffic lands in another's "
                   "partition.  Route construction through "
                   "apex_tpu.tenancy.namespace (qualify/chunk_id/"
                   "param_topic)")

    #: THE namespacing module: the one place the grammar may be built
    _EXEMPT = ("apex_tpu/tenancy/namespace.py", "tenancy/namespace.py")
    #: the grammar's separators; ids join tenant parts with exactly these
    _SEPS = ("/", "|")

    @staticmethod
    def _tenant_expr(node: ast.AST) -> bool:
        """Does this expression carry a tenant value?  Names/attributes
        spelled ``tenant``/``tenant_*``/``*_tenant`` (the repo's one
        spelling family — ``spec.tenant``, ``self.tenant``,
        ``spec_tenant``), including conversion wrappers like
        ``str(tenant)``."""
        if isinstance(node, ast.Call) and len(node.args) == 1 \
                and isinstance(node.func, ast.Name) \
                and node.func.id in ("str", "format"):
            return CrossTenantId._tenant_expr(node.args[0])
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is None:
            return False
        return (name == "tenant" or name.startswith("tenant_")
                or name.endswith("_tenant"))

    @classmethod
    def _sep_literal(cls, node: ast.AST, side: str) -> bool:
        """Is ``node`` a string literal that joins with a grammar
        separator on the given side ('head' = starts with one — the
        literal FOLLOWS the tenant; 'tail' = ends with one — the
        literal PRECEDES the tenant)?"""
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str) and node.value):
            return False
        ch = node.value[0] if side == "head" else node.value[-1]
        return ch in cls._SEPS

    def _check_joinedstr(self, node: ast.JoinedStr) -> bool:
        """f-string: a tenant-ish hole with a separator literal
        immediately adjacent (f"{tenant}/..." or f"...|{tenant}...")."""
        parts = node.values
        for i, part in enumerate(parts):
            if not (isinstance(part, ast.FormattedValue)
                    and self._tenant_expr(part.value)):
                continue
            if i + 1 < len(parts) \
                    and self._sep_literal(parts[i + 1], "head"):
                return True
            if i > 0 and self._sep_literal(parts[i - 1], "tail"):
                return True
        return False

    def _check_binop(self, node: ast.BinOp) -> bool:
        """Concat chain: flatten +-chains of strings and look for a
        tenant operand adjacent to a separator literal."""
        if not isinstance(node.op, ast.Add):
            return False
        flat: list[ast.AST] = []

        def walk(n: ast.AST) -> None:
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
                walk(n.left)
                walk(n.right)
            else:
                flat.append(n)

        walk(node)
        for i, part in enumerate(flat):
            if not self._tenant_expr(part):
                continue
            if i + 1 < len(flat) and self._sep_literal(flat[i + 1],
                                                       "head"):
                return True
            if i > 0 and self._sep_literal(flat[i - 1], "tail"):
                return True
        return False

    def _check_call(self, node: ast.Call) -> bool:
        """``"/".join([..tenant..])`` and
        ``"{}/{}".format(tenant, ...)`` shapes."""
        f = node.func
        if not isinstance(f, ast.Attribute) \
                or not (isinstance(f.value, ast.Constant)
                        and isinstance(f.value.value, str)):
            return False
        lit = f.value.value
        if f.attr == "join" and lit in self._SEPS:
            for arg in node.args:
                elts = (arg.elts if isinstance(arg, (ast.List, ast.Tuple))
                        else [arg])
                if any(self._tenant_expr(e) for e in elts):
                    return True
        if f.attr == "format" and any(s in lit for s in self._SEPS):
            if any(self._tenant_expr(a) for a in node.args) \
                    or any(self._tenant_expr(k.value)
                           for k in node.keywords):
                return True
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        import os as _os
        path = ctx.path.replace(_os.sep, "/")
        if path.endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        # one finding per concat CHAIN: sub-chains of an already-checked
        # Add chain are skipped (walk yields both)
        inner_adds: set[int] = set()
        for node in ctx.nodes(ast.BinOp):
            if isinstance(node.op, ast.Add):
                for child in (node.left, node.right):
                    if isinstance(child, ast.BinOp) \
                            and isinstance(child.op, ast.Add):
                        inner_adds.add(id(child))
        for node in ctx.nodes(ast.JoinedStr, ast.BinOp, ast.Call):
            hit = False
            if isinstance(node, ast.JoinedStr):
                hit = self._check_joinedstr(node)
            elif isinstance(node, ast.BinOp) and id(node) not in inner_adds:
                hit = self._check_binop(node)
            elif isinstance(node, ast.Call):
                hit = self._check_call(node)
            if hit:
                out.append(ctx.finding(
                    self, node,
                    "tenant-qualified id built outside the namespacing "
                    "helpers — the tenant/id grammar has ONE "
                    "construction site; use apex_tpu.tenancy.namespace "
                    "(qualify/chunk_id/param_topic)"))
        return out


# -- J018 -------------------------------------------------------------------


@register
class QuotaAccounting(Rule):
    id = "J018"
    name = "quota-accounting"
    why = ("Hand-rolled min(ingested, capacity) arithmetic drifts from the "
           "shard's residency ledger.")
    fix = ("Call replay_service/shard.py's residency accounting instead of "
           "recomputing it.")
    description = ("a replay residency count computed by hand — "
                   "min(<ingested>, <capacity>) — or an ordering "
                   "comparison between an ingested count and a quota "
                   "bound, outside the shard core (apex_tpu/"
                   "replay_service/shard.py): residency SATURATES at "
                   "ring capacity (the ring overwrites past it), so a "
                   "scattered raw count is how a quota check keeps "
                   "refusing a partition whose ring has long since "
                   "wrapped — cumulative ingest grows forever while "
                   "real residency stopped at capacity.  Route the "
                   "count through ReplayShardCore.resident()/"
                   "over_quota()")

    #: THE accounting module: the one place residency math may live
    _EXEMPT = ("apex_tpu/replay_service/shard.py",
               "replay_service/shard.py")
    #: the cumulative-ingest spelling family (shard/partition counters)
    _INGESTED = frozenset({"ingested"})
    #: ring-capacity spellings (FramePoolReplay and its frame pool)
    _CAPACITY = frozenset({"capacity", "f_capacity", "frame_capacity"})
    #: admission-bound spellings (TenantSpec.replay_quota, core.quota)
    _QUOTA = frozenset({"quota", "replay_quota"})

    @staticmethod
    def _named(node: ast.AST, names: frozenset) -> bool:
        """A bare name or attribute tail in the spelling family —
        ``core.ingested``, ``self.replay.capacity``, ``quota``.  Calls
        (``core.resident()``) are NOT named values: routing through the
        accessor is the fix, not a finding."""
        if isinstance(node, ast.Attribute):
            return node.attr in names
        if isinstance(node, ast.Name):
            return node.id in names
        return False

    def check(self, ctx: ModuleContext) -> list[Finding]:
        import os as _os
        path = ctx.path.replace(_os.sep, "/")
        if path.endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ctx.nodes(ast.Call, ast.Compare):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "min" and len(node.args) >= 2:
                if any(self._named(a, self._INGESTED)
                       for a in node.args) \
                        and any(self._named(a, self._CAPACITY)
                                for a in node.args):
                    out.append(ctx.finding(
                        self, node,
                        "hand-rolled residency count "
                        "(min(ingested, capacity)) outside the shard "
                        "core — use ReplayShardCore.resident()"))
            elif isinstance(node, ast.Compare) \
                    and any(isinstance(op, (ast.Lt, ast.LtE, ast.Gt,
                                            ast.GtE))
                            for op in node.ops):
                comparands = (node.left, *node.comparators)
                if any(self._named(c, self._INGESTED)
                       for c in comparands) \
                        and any(self._named(c, self._QUOTA)
                                for c in comparands):
                    out.append(ctx.finding(
                        self, node,
                        "quota judged against raw cumulative ingest — "
                        "residency saturates at ring capacity; use "
                        "ReplayShardCore.resident()/over_quota()"))
        return out


# -- J019 -------------------------------------------------------------------


@register
class CtlThreadAffinity(Rule):
    id = "J019"
    name = "ctl-thread-affinity"
    why = ("Status-server hooks run on their own thread; mutating trainer "
           "state there races the step.")
    fix = ("Hooks read snapshots or enqueue commands for the trainer thread "
           "to apply.")
    description = ("learner/trainer state mutated from a FleetStatusServer "
                   "hook: the status server runs ctl_fn/metrics_fn/"
                   "snapshot_fn on ITS OWN thread, while train_state/"
                   "replay_state/core and the jitted step closures are "
                   "trainer-thread-only by contract — a hook that restores "
                   "weights or rebinds the core races the hot loop "
                   "mid-dispatch.  Enqueue the command on a bounded queue "
                   "and apply it on the trainer thread's health tick "
                   "(ConcurrentTrainer._enqueue_ctl / _drain_ctl)")

    #: the server's callback keywords (fleet/registry.FleetStatusServer)
    _HOOK_KWARGS = ("ctl_fn", "metrics_fn", "snapshot_fn")
    #: trainer-thread-only attribute spellings (ConcurrentTrainer state)
    _STATE = frozenset({"train_state", "replay_state", "core", "key",
                        "learner_epoch", "param_version", "cfg",
                        "_fused", "_train", "_ingest", "_multi",
                        "_train_batch", "_ingest_multi"})
    #: trainer-thread-only appliers (each mutates the state above)
    _APPLIERS = frozenset({"restore_weights", "apply_hparams",
                           "_apply_ctl", "_drain_ctl", "save_checkpoint",
                           "restore"})

    def _class_methods(self, cls: ast.ClassDef) -> dict:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _enclosing_class(self, ctx: ModuleContext,
                         node: ast.AST) -> ast.ClassDef | None:
        n = ctx.parents.get(node)
        while n is not None:
            if isinstance(n, ast.ClassDef):
                return n
            n = ctx.parents.get(n)
        return None

    def _scan_body(self, ctx: ModuleContext, nodes,
                   hook_name: str) -> list[Finding]:
        out: list[Finding] = []
        for body_node in nodes:
            for node in ast.walk(body_node):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _self_attr(t)
                        if attr in self._STATE:
                            out.append(ctx.finding(
                                self, node,
                                f"self.{attr} assigned inside the "
                                f"status-server hook {hook_name!r} — "
                                f"learner state is trainer-thread-only; "
                                f"enqueue and drain on the health tick"))
                elif isinstance(node, ast.Call):
                    attr = _self_attr(node.func)
                    if attr in self._APPLIERS:
                        out.append(ctx.finding(
                            self, node,
                            f"self.{attr}() called inside the "
                            f"status-server hook {hook_name!r} — it "
                            f"mutates learner state on the server "
                            f"thread; enqueue and drain on the health "
                            f"tick"))
        return out

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.nodes(ast.Call):
            if _callee_basename(node) != "FleetStatusServer":
                continue
            cls = self._enclosing_class(ctx, node)
            methods = self._class_methods(cls) if cls is not None else {}
            for kwarg in self._HOOK_KWARGS:
                hook = _kwarg(node, kwarg)
                if hook is None:
                    continue
                if isinstance(hook, ast.Lambda):
                    out.extend(self._scan_body(ctx, [hook.body], kwarg))
                    continue
                attr = _self_attr(hook)
                fn = methods.get(attr) if attr else None
                if fn is None:
                    continue
                # the hook body plus one level of same-class calls —
                # enough to catch a hook delegating its mutation, without
                # walking the trainer's whole call graph
                bodies: list = [fn]
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        callee = _self_attr(sub.func)
                        target = methods.get(callee) if callee else None
                        if target is not None and target is not fn:
                            bodies.append(target)
                out.extend(self._scan_body(ctx, bodies, f"{kwarg}={attr}"))
        return out
