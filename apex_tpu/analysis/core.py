"""apexlint core: module model, rule registry, suppressions, baseline.

Pure stdlib (``ast`` + ``tokenize``): the analyzer imports nothing heavy, so
it runs before any JAX/TPU initialization and in CI images with no
accelerator deps.  Rules operate on a :class:`ModuleContext` — one parsed
file plus the derived facts every rule needs (parent links, which functions
are jitted scope, suppression comments).

Jitted-scope detection is deliberately heuristic (static analysis cannot see
through arbitrary higher-order wrapping); the per-rule fixture tests in
``tests/test_analysis.py`` are the behavioral contract.  A function counts
as jitted scope when:

* it is decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``;
* its name is passed to a ``jax.jit(...)`` call anywhere in the module
  (``jax.jit(self.train_step, ...)`` marks ``train_step``);
* its name is passed to a ``shard_map(...)`` call (any alias spelling) —
  the mapped body always ends up inside the jitted program;
* it is returned by a ``make_*_fn`` factory (the repo's policy-fn
  convention — call sites jit the factory's result in other modules);
* it is (transitively) called by name from another jitted function in the
  same module (``train_step -> update_from_batch``).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from collections import deque
from dataclasses import dataclass

# -- findings ---------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``code`` (the stripped source line) is the stable
    part of the baseline fingerprint — line numbers drift, code lines move
    with the finding."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    code: str = ""

    def fingerprint(self) -> tuple[str, str, str]:
        return (self.rule, self.path.replace(os.sep, "/"), self.code)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "code": self.code}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule} {self.message}")


# -- rule registry ----------------------------------------------------------


class Rule:
    """Base class; subclasses set ``id``/``name``/``description`` (and
    the catalog one-liners ``why``/``fix``) and implement :meth:`check`.

    ``why`` is the one-line hazard statement and ``fix`` the one-line
    recipe — the metadata ``--explain``/``--catalog-md`` print and the
    README rule table is generated from, so docs and CLI cannot drift."""

    id: str = ""
    name: str = ""
    description: str = ""
    why: str = ""
    fix: str = ""

    def check(self, ctx: "ModuleContext") -> list[Finding]:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    # rule modules register on import; import here to avoid a cycle
    from apex_tpu.analysis import (rules_concurrency,  # noqa: F401
                                   rules_jax, rules_protocol)
    return dict(sorted(_REGISTRY.items()))


def catalog() -> list[dict]:
    """The rule catalog ``--explain``/``--catalog-md`` and the README
    table render from: one entry per rule, why/fix falling back to the
    description's first sentence when a rule predates the metadata."""
    out = []
    for rid, rule in all_rules().items():
        why = rule.why or rule.description.split(". ")[0].strip()
        out.append({"id": rid, "name": rule.name, "why": why,
                    "fix": rule.fix, "description": rule.description})
    return out


def catalog_markdown() -> str:
    """Markdown rule table (README's generated block — regenerate with
    ``python -m apex_tpu.analysis --catalog-md``)."""
    lines = ["| Rule | Title | Why | Fix |", "|---|---|---|---|"]
    for e in catalog():
        row = [e["id"], f"`{e['name']}`", e["why"], e["fix"] or "—"]
        lines.append("| " + " | ".join(c.replace("|", "\\|")
                                       for c in row) + " |")
    return "\n".join(lines) + "\n"


# -- jit detection helpers --------------------------------------------------


def is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``(functools.)partial(jax.jit, ...)``."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return (node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax")
    if isinstance(node, ast.Call):
        f = node.func
        is_partial = ((isinstance(f, ast.Name) and f.id == "partial")
                      or (isinstance(f, ast.Attribute)
                          and f.attr == "partial"))
        return bool(is_partial and node.args and is_jit_expr(node.args[0]))
    return False


def is_shard_map_expr(node: ast.AST) -> bool:
    """``shard_map`` / ``jax.shard_map`` / ``shard_map_compat`` (the
    repo's version wrapper) — a function handed to any of these runs as
    the per-chip body of a compiled program, i.e. jitted scope."""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    else:
        return False
    return name == "shard_map" or name.startswith("shard_map_")


def call_name(node: ast.Call) -> str | None:
    """Bare name of the callee: ``g(...)`` -> g, ``x.g(...)`` -> g."""
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


_MAKE_FN_RE = re.compile(r"^make_\w+_fn$")


class ModuleContext:
    """One parsed module plus derived facts shared by all rules."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        #: the whole-program ProjectContext when this module was analyzed
        #: as part of a tree walk; None for lone-snippet analysis — every
        #: rule must degrade to per-file behavior without it
        self.project = None
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # one BFS (ast.walk order) builds every navigation index: parent
        # links, the per-type node lists `nodes()` serves, the O(1)
        # enclosing-function map, and the function list — 28 rules walk
        # this tree; they must not each re-walk it from the root
        self.parents: dict[ast.AST, ast.AST] = {}
        self._by_type: dict[type, list] = {}
        self._encl_fn: dict[ast.AST, ast.AST | None] = {}
        self.functions: list = []
        todo = deque([(self.tree, None)])
        while todo:
            node, fn = todo.popleft()
            self._by_type.setdefault(type(node), []).append(node)
            self._encl_fn[node] = fn
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(node)
                fn = node
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                todo.append((child, fn))
        self.jitted = self._collect_jitted()
        self._inline_supp, self._standalone_supp = \
            _collect_suppressions(source)

    # -- navigation --------------------------------------------------------

    def ancestors(self, node: ast.AST):
        n = self.parents.get(node)
        while n is not None:
            yield n
            n = self.parents.get(n)

    def enclosing_function(self, node: ast.AST):
        try:
            return self._encl_fn[node]
        except KeyError:        # node not from this tree: ancestor scan
            for a in self.ancestors(node):
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    return a
            return None

    def nodes(self, *types: type) -> list:
        """All nodes of the EXACT given AST types, in ast.walk order —
        the index-backed replacement for ``ast.walk(ctx.tree)`` +
        isinstance filtering (list subclasses explicitly)."""
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: list = []
        for t in types:
            out.extend(self._by_type.get(t, []))
        return out

    def enclosing_class(self, node: ast.AST):
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

    def in_jitted_scope(self, node: ast.AST):
        """Innermost enclosing jitted FunctionDef (nested defs inside a
        jitted function are jitted scope too), or None."""
        n = node
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if n in self.jitted:
                    return n
            n = self.parents.get(n)
        return None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: Rule, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule.id, path=self.path, line=line, col=col,
                       message=message, code=self.line_text(line))

    # -- jitted-scope collection ------------------------------------------

    def _collect_jitted(self) -> set:
        jitted: set = set()
        seeds: set[str] = set()
        for fn in self.functions:
            if any(is_jit_expr(d) for d in fn.decorator_list):
                jitted.add(fn)
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call) and node.args
                    and (is_jit_expr(node.func)
                         or is_shard_map_expr(node.func))):
                tgt = node.args[0]
                if isinstance(tgt, ast.Name):
                    seeds.add(tgt.id)
                elif isinstance(tgt, ast.Attribute):
                    seeds.add(tgt.attr)
        # make_*_fn factories: the returned closures are jitted at call
        # sites in other modules
        for fn in self.functions:
            if not _MAKE_FN_RE.match(fn.name):
                continue
            returned = {r.value.id for r in ast.walk(fn)
                        if isinstance(r, ast.Return)
                        and isinstance(r.value, ast.Name)}
            for sub in self.functions:
                if sub.name in returned and self._encloses(fn, sub):
                    jitted.add(sub)
        for fn in self.functions:
            if fn.name in seeds:
                jitted.add(fn)
        # transitive closure over the same-module call graph (by bare name)
        by_name: dict[str, list] = {}
        for fn in self.functions:
            by_name.setdefault(fn.name, []).append(fn)
        changed = True
        while changed:
            changed = False
            for fn in list(jitted):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for cand in by_name.get(call_name(node) or "", []):
                        if cand not in jitted:
                            jitted.add(cand)
                            changed = True
        return jitted

    def _encloses(self, outer: ast.AST, inner: ast.AST) -> bool:
        return outer is not inner and any(a is outer
                                          for a in self.ancestors(inner))

    # -- suppressions ------------------------------------------------------

    def is_suppressed(self, f: Finding) -> bool:
        rules = set(self._inline_supp.get(f.line, ()))
        # standalone `# apexlint: disable=...` comment lines apply to the
        # next code line; consecutive comment lines stack
        line = f.line - 1
        while line in self._standalone_supp:
            rules |= self._standalone_supp[line]
            line -= 1
        return "all" in rules or f.rule in rules


_DISABLE_RE = re.compile(r"apexlint:\s*disable=([A-Za-z0-9_,\s]+|all)")


def _collect_suppressions(source: str):
    """Line -> suppressed-rule-ids maps from ``# apexlint: disable=...``
    comments.  Inline comments cover their own line; comment-only lines
    cover the next code line.  Text after ``--`` is a justification."""
    inline: dict[int, set[str]] = {}
    standalone: dict[int, set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return inline, standalone
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string.split("--")[0])
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if tok.line[:tok.start[1]].strip():
            inline.setdefault(tok.start[0], set()).update(rules)
        else:
            standalone.setdefault(tok.start[0], set()).update(rules)
    return inline, standalone


# -- analysis entry points --------------------------------------------------

#: pseudo-rule id for unparseable files
PARSE_ERROR = "E001"

_EXCLUDE_DIRS = {"__pycache__", ".git", "_build", ".eggs", "build", "dist"}


def analyze_source(source: str, path: str = "<string>",
                   rules: dict[str, Rule] | None = None,
                   respect_suppressions: bool = True, project=None):
    """Analyze one module.  Returns ``(findings, suppressed)`` — both lists
    of :class:`Finding`, split by inline ``disable`` comments.  ``project``
    (a :class:`~apex_tpu.analysis.graph.ProjectContext`) attaches the
    whole-program view; its pre-parsed ModuleContext is reused when it
    holds one for ``path``."""
    rules = all_rules() if rules is None else rules
    try:
        ctx = (project.module_ctx(path)
               if project is not None else None) or ModuleContext(path,
                                                                  source)
    except (SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return [Finding(rule=PARSE_ERROR, path=path, line=line, col=0,
                        message=f"file does not parse: {e.msg}"
                        if isinstance(e, SyntaxError) else str(e))], []
    ctx.project = project
    findings: list[Finding] = []
    for rule in rules.values():
        findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    if not respect_suppressions:
        return findings, []
    kept = [f for f in findings if not ctx.is_suppressed(f)]
    suppressed = [f for f in findings if ctx.is_suppressed(f)]
    return kept, suppressed


def iter_python_files(paths, exclude=()):
    """Yield .py files under ``paths`` (files or directories), skipping
    build/cache dirs and any path containing an ``exclude`` substring."""
    exclude = tuple(exclude)

    def excluded(p: str) -> bool:
        norm = p.replace(os.sep, "/")
        return any(e in norm for e in exclude)

    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _EXCLUDE_DIRS
                                 and not excluded(os.path.join(dirpath, d)))
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                if fn.endswith(".py") and not excluded(full):
                    yield full


def analyze_paths(paths, exclude=(), rules: dict[str, Rule] | None = None,
                  root: str | None = None, only=None):
    """Analyze every .py file under ``paths``.  Finding paths are made
    relative to ``root`` (default: cwd) so baselines are machine-portable.

    The whole tree is parsed ONCE into a
    :class:`~apex_tpu.analysis.graph.ProjectContext` before any rule
    runs, so cross-module rules (J020+, C006) see every module's import/
    call graph.  ``only`` (an iterable of root-relative ``/``-separated
    paths) restricts which files get REPORTED — the project context
    still spans the full tree, so a ``--changed-only`` run keeps the
    whole-program rules accurate.  Returns ``(findings, suppressed)``."""
    from apex_tpu.analysis.graph import ProjectContext
    root = os.path.abspath(root or os.getcwd())
    only = None if only is None else {p.replace(os.sep, "/") for p in only}
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    sources: dict[str, str] = {}
    for file in iter_python_files(paths, exclude):
        rel = os.path.relpath(os.path.abspath(file), root)
        rel = rel.replace(os.sep, "/")
        try:
            with open(file, "r", encoding="utf-8", errors="replace") as fh:
                sources[rel] = fh.read()
        except OSError as e:
            if only is None or rel in only:
                findings.append(Finding(rule=PARSE_ERROR, path=rel, line=1,
                                        col=0, message=f"unreadable: {e}"))
    project = ProjectContext(sources)
    for rel, source in sources.items():
        if only is not None and rel not in only:
            continue
        got, supp = analyze_source(source, path=rel, rules=rules,
                                   project=project)
        findings.extend(got)
        suppressed.extend(supp)
    return findings, suppressed


# -- baseline ---------------------------------------------------------------


class Baseline:
    """Checked-in ledger of accepted pre-existing findings.

    Fingerprint = (rule, path, stripped code line) with a count — stable
    under unrelated edits (line numbers move, the flagged line's text
    doesn't).  ``--write-baseline`` regenerates it; strict mode fails on
    STALE entries (fixed code must leave the ledger) so the baseline only
    ever shrinks."""

    def __init__(self, counts: dict[tuple[str, str, str], int] | None = None):
        self.counts = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        counts: dict[tuple[str, str, str], int] = {}
        for e in data.get("findings", []):
            fp = (e["rule"], e["path"], e.get("code", ""))
            counts[fp] = counts.get(fp, 0) + int(e.get("count", 1))
        return cls(counts)

    @classmethod
    def from_findings(cls, findings) -> "Baseline":
        b = cls()
        for f in findings:
            fp = f.fingerprint()
            b.counts[fp] = b.counts.get(fp, 0) + 1
        return b

    def write(self, path: str) -> None:
        entries = [{"rule": r, "path": p, "code": c, "count": n}
                   for (r, p, c), n in sorted(self.counts.items())]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"comment": "apexlint baseline — accepted "
                                  "pre-existing findings; regenerate with "
                                  "--write-baseline, never hand-grow",
                       "version": 1, "findings": entries}, fh, indent=2,
                      sort_keys=True)
            fh.write("\n")

    def partition(self, findings):
        """Split ``findings`` into (new, baselined); returns the stale
        leftover entries third."""
        remaining = dict(self.counts)
        new, matched = [], []
        for f in findings:
            fp = f.fingerprint()
            if remaining.get(fp, 0) > 0:
                remaining[fp] -= 1
                matched.append(f)
            else:
                new.append(f)
        stale = [{"rule": r, "path": p, "code": c, "count": n}
                 for (r, p, c), n in sorted(remaining.items()) if n > 0]
        return new, matched, stale


# -- SARIF ------------------------------------------------------------------

SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"
SARIF_VERSION = "2.1.0"


def sarif_report(new, baselined=(), suppressed=(),
                 rules: dict[str, Rule] | None = None,
                 root: str | None = None) -> dict:
    """Findings as a SARIF 2.1.0 log (the CI gate's artifact format).

    New findings are level ``error`` (they fail the run); baselined and
    inline-suppressed findings ride along as suppressed results (kinds
    ``external`` / ``inSource``) so the artifact is the COMPLETE picture,
    not just the failing slice."""
    rules = all_rules() if rules is None else rules
    driver_rules = []
    for rid, rule in sorted(rules.items()):
        entry = {"id": rid, "name": rule.name or rid,
                 "shortDescription": {"text": rule.name or rid},
                 "fullDescription": {"text": rule.description}}
        if rule.why or rule.fix:
            entry["help"] = {"text": f"why: {rule.why}\nfix: {rule.fix}"}
        driver_rules.append(entry)

    def result(f: Finding, level: str, suppression: str | None):
        r = {"ruleId": f.rule, "level": level,
             "message": {"text": f.message},
             "locations": [{"physicalLocation": {
                 "artifactLocation": {"uri": f.path.replace(os.sep, "/"),
                                      "uriBaseId": "SRCROOT"},
                 "region": {"startLine": max(1, f.line),
                            "startColumn": f.col + 1}}}]}
        if suppression is not None:
            r["suppressions"] = [{"kind": suppression}]
        return r

    results = ([result(f, "error", None) for f in new]
               + [result(f, "note", "external") for f in baselined]
               + [result(f, "note", "inSource") for f in suppressed])
    run = {"tool": {"driver": {"name": "apexlint",
                               "informationUri":
                                   "https://github.com/apex-tpu/apex-tpu",
                               "rules": driver_rules}},
           "results": results}
    if root:
        uri = "file://" + os.path.abspath(root).replace(os.sep, "/")
        run["originalUriBaseIds"] = {"SRCROOT": {"uri": uri.rstrip("/")
                                                 + "/"}}
    return {"$schema": SARIF_SCHEMA, "version": SARIF_VERSION,
            "runs": [run]}
