"""Protocol rules: the cross-module invariants PRs 9-15 left to convention.

These ride the whole-program layer (``graph.ProjectContext`` +
``dataflow``): donation lifetimes (J020), shard-band membership (J021),
epoch/version fencing (J022), wire-codec containment (J023), and thread
affinity taken across module boundaries (C006).  Each follows the
single-construction-site pattern
J016/J017/J018 established — ONE module may hold the raw arithmetic,
everyone else routes through its helpers — and every rule degrades to
per-file behavior when ``ctx.project`` is None (lone-snippet analysis).
"""

from __future__ import annotations

import ast
import os

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                    register)

# -- shared helpers ---------------------------------------------------------


def _basename(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _norm_path(ctx: ModuleContext) -> str:
    return ctx.path.replace(os.sep, "/")


def _name_mentions(node: ast.AST, needles: tuple[str, ...]) -> bool:
    """Any Name/Attribute (or f-string text) under ``node`` whose lowered
    spelling contains one of ``needles``."""
    for n in ast.walk(node):
        name = _basename(n)
        if name and any(s in name.lower() for s in needles):
            return True
    return False


def _constant_expr(node: ast.AST) -> bool:
    """An expression made only of constants/operators (``2 ** 31``) —
    a literal modulus is a range clamp or seed mask, never a live shard
    count."""
    return not any(isinstance(n, (ast.Name, ast.Attribute, ast.Call))
                   for n in ast.walk(node))


# -- J020 -------------------------------------------------------------------


@register
class DonationAliasing(Rule):
    id = "J020"
    name = "donation-aliasing"
    description = (
        "a reference to a donated buffer read after the dispatch that "
        "consumed it: jax.jit(fn, donate_argnums=...) invalidates the "
        "donated argument buffers AT DISPATCH, so any post-call read of "
        "the pre-dispatch reference — a stale local, an attribute the "
        "epilogue forgot to rebind, or the same name re-passed on the "
        "next loop iteration without rebinding — returns a deleted "
        "buffer.  The FusedStep.dispatch epilogue contract is the fix: "
        "rebind EVERY donated argument from the dispatch results in the "
        "same statement, then touch only the results")
    why = ("donation invalidates the argument buffer at dispatch; a "
           "post-call read of the old reference is a deleted-buffer bug")
    fix = ("rebind every donated arg from the dispatch results in the "
           "same statement (the FusedStep.dispatch epilogue discipline)")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        out: list[Finding] = []
        for h in dataflow.donation_hazards(ctx):
            if h.loop_carried:
                out.append(ctx.finding(
                    self, h.read,
                    f"donated argument '{h.arg_path}' is re-passed on the "
                    f"next loop iteration without being rebound from the "
                    f"dispatch results — the second dispatch consumes a "
                    f"buffer the first already donated"))
            else:
                out.append(ctx.finding(
                    self, h.read,
                    f"'{h.arg_path}' read after the dispatch that donated "
                    f"it — the buffer was consumed; rebind it from the "
                    f"dispatch results (the FusedStep epilogue contract) "
                    f"or read the returned value instead"))
        return out


# -- J021 -------------------------------------------------------------------


@register
class BandMembership(Rule):
    id = "J021"
    name = "band-membership"
    description = (
        "shard-index arithmetic on a tenant identity outside the tenancy "
        "helpers (apex_tpu/tenancy/namespace.py): a raw "
        "crc32(key) % n_shards spelled at a call site hashes over the "
        "WHOLE tier, so the moment the placement scheduler assigns a "
        "tenant a weighted shard BAND the caller routes traffic to "
        "shards outside the band — another tenant's partition.  Route "
        "every identity->shard mapping through "
        "namespace.shard_in_band(key, band) (the full tier is "
        "shard_in_band(key, range(n)))")
    why = ("a raw hash % n_shards ignores the scheduler's shard bands "
           "and lands one tenant's traffic in another's partition")
    fix = ("route identity->shard mapping through tenancy "
           "namespace.shard_in_band(key, band); full tier = "
           "shard_in_band(key, range(n))")

    #: THE banding module: the one place the raw modulo may live
    _EXEMPT = ("apex_tpu/tenancy/namespace.py", "tenancy/namespace.py")
    #: integer content hashes the planes shard with (salted builtin hash()
    #: included: sharding with it is its own bug)
    _HASHES = frozenset({"crc32", "adler32", "hash"})
    #: shard/band-count spellings for the modulus side
    _COUNTS = ("shard", "band")
    #: identity-carrying spellings for the hashed key side
    _IDS = ("identity", "tenant", "chunk", "worker", "peer", "actor")

    def _hash_call(self, node: ast.AST) -> ast.Call | None:
        """The crc32-family call under (possibly int()/abs()-wrapped)
        ``node``."""
        if isinstance(node, ast.Call):
            base = _basename(node.func)
            if base in self._HASHES:
                return node
            if base in ("int", "abs") and node.args:
                return self._hash_call(node.args[0])
        return None

    def _countish(self, node: ast.AST) -> bool:
        """Does the modulus look like a shard/band count?  Names and
        attributes containing shard/band, ``len()`` of such, and
        ``max()``/``int()`` wrappers thereof."""
        if isinstance(node, ast.Call):
            base = _basename(node.func)
            if base in ("len", "max", "min", "int"):
                return any(self._countish(a) for a in node.args)
            return False
        name = _basename(node)
        return bool(name and any(s in name.lower() for s in self._COUNTS))

    def check(self, ctx: ModuleContext) -> list[Finding]:
        if _norm_path(ctx).endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ctx.nodes(ast.BinOp):
            if not isinstance(node.op, ast.Mod):
                continue
            call = self._hash_call(node.left)
            if call is None:
                continue
            if _constant_expr(node.right):
                continue        # seed mask / range clamp, not a tier size
            key_like = any(_name_mentions(a, self._IDS)
                           for a in call.args)
            if not (self._countish(node.right) or key_like):
                continue
            out.append(ctx.finding(
                self, node,
                "raw shard-index arithmetic (hash % shard count) outside "
                "the tenancy helpers — once scheduler bands go live this "
                "routes outside the tenant's band; use "
                "tenancy.namespace.shard_in_band(key, band) "
                "(full tier: shard_in_band(key, range(n)))"))
        return out


# -- J022 -------------------------------------------------------------------


@register
class FenceOrdering(Rule):
    id = "J022"
    name = "fence-ordering"
    description = (
        "a (learner_epoch, param_version) fence tuple constructed "
        "outside the fencing helpers (apex_tpu/serving/fence.py): J016 "
        "already bans raw ORDERING on the components; a hand-built pair "
        "is the cross-module version of the same fork — it skips "
        "fence_key's None/absent clamping, and a transposed "
        "(version, epoch) pair silently inverts the epoch-major order "
        "everywhere the tuple later flows.  Build fences with "
        "fence.fence_key(epoch, version) and compare with "
        "fence.beyond/at_or_before")
    why = ("a hand-built (epoch, version) tuple skips fence_key's "
           "clamping and can transpose the epoch-major order")
    fix = ("construct fences with serving.fence.fence_key(epoch, "
           "version); compare via fence.beyond/at_or_before")

    #: THE fencing module
    _EXEMPT = ("apex_tpu/serving/fence.py", "serving/fence.py")
    _NAMES = frozenset({"learner_epoch", "param_version"})

    def _component(self, node: ast.AST) -> str | None:
        name = _basename(node)
        return name if name in self._NAMES else None

    def _is_fence_pair(self, node: ast.AST) -> bool:
        if not (isinstance(node, ast.Tuple) and len(node.elts) == 2):
            return False
        got = {self._component(e) for e in node.elts}
        return got == self._NAMES

    def check(self, ctx: ModuleContext) -> list[Finding]:
        if _norm_path(ctx).endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ctx.nodes(ast.Tuple):
            if not self._is_fence_pair(node):
                continue
            parent = ctx.parents.get(node)
            # the parallel-assignment snapshot idiom reads the
            # components simultaneously without an ordered pair value
            # ever escaping: `pv, epoch = x.param_version, x.learner_epoch`
            if isinstance(parent, ast.Assign) and parent.value is node \
                    and all(isinstance(t, (ast.Tuple, ast.List))
                            for t in parent.targets):
                continue
            out.append(ctx.finding(
                self, node,
                "fence tuple (learner_epoch, param_version) built by "
                "hand outside serving/fence.py — construct it with "
                "fence.fence_key(epoch, version) so clamping and the "
                "epoch-major order have one spelling"))
        return out


# -- C006 -------------------------------------------------------------------


@register
class CrossModuleThreadAffinity(Rule):
    id = "C006"
    name = "cross-module-thread-affinity"
    description = (
        "trainer/device state mutated from a thread-spawn site in one "
        "module while a jitted hot path in ANOTHER module reads it "
        "un-locked: J019 catches the FleetStatusServer hooks per file; "
        "this is the same contract taken whole-program over the "
        "ProjectContext call graph — any function reachable from a "
        "threading.Thread(target=...) spawn that assigns a "
        "trainer-thread-only attribute (train_state/replay_state/core/"
        "carry...) races every other module's compiled step that closes "
        "over it.  Enqueue the mutation and apply it on the owning "
        "thread (the ctl-queue drain pattern), or hold the state's lock")
    why = ("a thread-reachable mutation of trainer-thread-only state "
           "races another module's jitted hot path mid-dispatch")
    fix = ("enqueue the mutation and drain it on the owning thread "
           "(ctl-queue pattern), or guard both sides with the state's "
           "lock")

    #: trainer/device-state spellings a spawned thread may never assign
    #: (the J019 contract minus the broad per-file names): each is read
    #: from inside a compiled program somewhere in the tree
    _STATE = frozenset({"train_state", "replay_state", "core",
                        "carry", "carry_frames", "ingested_dev"})

    @staticmethod
    def _under_lock(ctx: ModuleContext, node: ast.AST) -> bool:
        for a in ctx.ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    if _name_mentions(item.context_expr, ("lock",)):
                        return True
        return False

    def _hot_readers(self, project, attr: str, skip_path: str) -> str | None:
        """Path of another module whose jitted scope reads ``.attr``."""
        for path, info in project.modules.items():
            if path == skip_path:
                continue
            mctx = info.ctx
            for fn in mctx.jitted:
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) and n.attr == attr \
                            and isinstance(n.ctx, ast.Load):
                        return path
        return None

    def check(self, ctx: ModuleContext) -> list[Finding]:
        project = ctx.project
        if project is None:
            return []           # whole-program only: no project, no view
        info = project.modules.get(_norm_path(ctx))
        if info is None:
            return []
        out: list[Finding] = []
        for fn in ctx.functions:
            qual = project.qualname_of(info, fn)
            if qual not in project.thread_reachable:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and t.attr in self._STATE):
                        continue
                    if self._under_lock(ctx, node):
                        continue
                    reader = self._hot_readers(project, t.attr, info.path)
                    if reader is None:
                        continue
                    out.append(ctx.finding(
                        self, node,
                        f"'.{t.attr}' assigned in {fn.name}() — reachable "
                        f"from a Thread(target=...) spawn — while a "
                        f"jitted hot path in {reader} reads it un-locked; "
                        f"trainer/device state is owning-thread-only: "
                        f"enqueue the mutation and drain it there, or "
                        f"lock both sides"))
        return out


# -- J023 -------------------------------------------------------------------


@register
class CodecOutsideCodecModule(Rule):
    id = "J023"
    name = "codec-outside-codec-module"
    description = (
        "raw compression/decompression or hand-rolled frame-delta "
        "arithmetic on wire payloads outside the codec module "
        "(apex_tpu/runtime/codec.py): a zlib/lz4 call or a frame XOR "
        "spelled at a call site forks the wire format — the receiver's "
        "per-chunk negotiation, byte-parity CRC, and hostile-payload "
        "rejection all live in codec.py, so a second encode site ships "
        "bytes those guarantees never cover.  Route every wire "
        "encode/decode through codec.encode_chunk/decode_chunk "
        "(crc32/adler32 checksums and hash routing stay fine anywhere)")
    why = ("a second compression or frame-diff site forks the wire "
           "format outside the codec's version/checksum/reject "
           "guarantees — mixed fleets then decode garbage")
    fix = ("route wire bytes through apex_tpu.runtime.codec "
           "(encode_chunk/decode_chunk, diff_tree/apply_delta); "
           "checksums (crc32/adler32) are not compression and stay "
           "allowed")

    #: THE codec module: the one place wire compression may live
    _EXEMPT = ("apex_tpu/runtime/codec.py", "runtime/codec.py")
    #: compression API spellings (zlib/lz4/bz2/lzma/zstd all use them);
    #: crc32/adler32 are checksums, deliberately NOT in this set (J021's
    #: routing-hash distinction)
    _COMPRESS = frozenset({"compress", "decompress", "compressobj",
                           "decompressobj"})
    #: wire-payload spellings for the frame-diff half: XOR over plain
    #: ints (seeds, fold-ins) is fine; XOR touching these is a codec
    _WIRE = ("frame", "payload", "chunk_bytes", "wire")

    def check(self, ctx: ModuleContext) -> list[Finding]:
        if _norm_path(ctx).endswith(self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ctx.nodes(ast.Call):
            base = _basename(node.func)
            if base in self._COMPRESS:
                out.append(ctx.finding(
                    self, node,
                    f"raw {base}() on wire bytes outside "
                    f"runtime/codec.py — the codec module owns the wire "
                    f"format (versioning, byte-parity CRC, hostile-"
                    f"payload rejection); route through "
                    f"codec.encode_chunk/decode_chunk"))
            elif (base == "bitwise_xor"
                  or (isinstance(node.func, ast.Attribute)
                      and _basename(node.func.value) == "bitwise_xor")):
                if any(_name_mentions(a, self._WIRE) for a in node.args):
                    out.append(ctx.finding(
                        self, node,
                        "hand-rolled frame-delta arithmetic "
                        "(bitwise_xor over frames) outside "
                        "runtime/codec.py — use the codec module's "
                        "delta codec (encode_chunk)"))
        for node in ctx.nodes(ast.BinOp, ast.AugAssign):
            if not isinstance(node.op, ast.BitXor):
                continue
            sides = ((node.left, node.right)
                     if isinstance(node, ast.BinOp)
                     else (node.target, node.value))
            if any(_name_mentions(s, self._WIRE) for s in sides):
                out.append(ctx.finding(
                    self, node,
                    "hand-rolled frame-delta arithmetic (XOR over "
                    "frames/payload) outside runtime/codec.py — a "
                    "second delta site forks the wire format; use the "
                    "codec module's delta codec"))
        return out
