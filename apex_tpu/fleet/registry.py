"""Learner-side fleet membership: the heartbeat registry + status surface.

The registry replaces the passive ``RemotePool.silent_peers(60.0)`` report
with an explicit per-peer state machine driven by config thresholds
(:class:`~apex_tpu.config.CommsConfig`):

    JOINING --beat--> ALIVE --silence > suspect_after_s--> SUSPECT
    SUSPECT --activity--> ALIVE     (recovery, not counted)
    SUSPECT --silence > dead_after_s--> DEAD
    DEAD    --activity--> ALIVE     (a REJOIN — counted)

Two observation kinds feed it: :class:`~apex_tpu.fleet.heartbeat.Heartbeat`
messages off the stat channel (rich: fps, counters, self-reported park
state) and bare message-arrival times off the chunk socket
(``observe_seen`` — keeps a backpressured-but-flowing actor ALIVE even
when its stat puts drop).  ``fleet_rejoins`` sums registry-observed
DEAD→ALIVE transitions with the fleet's self-reported park→resume cycles,
so a learner restarted from checkpoint still credits the rejoins its
predecessor's registry never saw.

Thread contract: observations and ticks come from the trainer thread; the
status server thread only calls :meth:`snapshot`, which takes the same
lock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from apex_tpu.config import CommsConfig
from apex_tpu.fleet.heartbeat import Heartbeat

JOINING, ALIVE, SUSPECT, DEAD = "JOINING", "ALIVE", "SUSPECT", "DEAD"


def _min_transit_offset(samples) -> float:
    """Per-peer clock offset from recent heartbeat samples: the median of
    the smallest half (min-transit selection).  Each sample is
    ``skew + transit_i`` with ``transit_i >= 0``, so the smallest samples
    bound the skew most tightly; the median over that low half keeps one
    anomalous beat (queue dwell spike, clock step mid-window) from owning
    the estimate the way last-beat sampling did."""
    s = sorted(samples)
    low = s[:max(1, len(s) // 2)]
    mid = len(low) // 2
    med = (low[mid] if len(low) % 2
           else (low[mid - 1] + low[mid]) / 2.0)
    return round(med, 4)


@dataclass
class PeerState:
    identity: str
    role: str = "?"
    # owning tenant, parsed from the (possibly tenant-qualified) wire
    # identity at first sight (tenancy/namespace.py); unqualified peers
    # belong to the default tenant — every pre-tenancy fleet unchanged
    tenant: str = ""
    pid: int = 0
    host: str = ""
    state: str = JOINING
    fps: float = 0.0
    param_version: int = 0
    chunks_sent: int = 0
    acks_received: int = 0
    resends: int = 0
    rerouted: int = 0
    rejoins_reported: int = 0
    parked: bool = False
    beats: int = 0
    joined_at: float = 0.0
    last_any: float = 0.0           # newest activity of either kind
    last_beat: float | None = None  # newest heartbeat (gap statistics)
    deaths: int = 0                 # ALIVE/SUSPECT -> DEAD transitions
    # learner wall at receive - peer wall at send (skew + one transit),
    # from the heartbeat wall_ts; the obs.merge trace aligner consumes it
    # via fleet_summary.json.  None until a wall-stamped beat arrives.
    # Each sample overestimates the true skew by that beat's transit (+
    # any stat-queue dwell), so the published offset is NOT the last beat
    # but a min-transit median over the recent sample window: transit is
    # strictly additive, so the smallest samples are the closest to pure
    # skew, and the median over that low half rides out one lucky/broken
    # outlier (NTP's clock-filter idea, scaled down).
    clock_offset_s: float | None = None
    clock_offset_n: int = 0         # samples behind the estimate
    offset_samples: deque = field(default_factory=lambda: deque(maxlen=16))
    # latest role-specific serving gauges off the peer's heartbeats
    # (infer server: queue depth / batch percentiles; remote-policy
    # actors: fallback counts / round-trip percentiles)
    gauges: dict = field(default_factory=dict)


class FleetRegistry:
    """Per-peer membership for one learner process."""

    def __init__(self, comms: CommsConfig | None = None,
                 clock=time.monotonic, wall_clock=time.time):
        self.comms = comms or CommsConfig()
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self.peers: dict[str, PeerState] = {}
        self.dead_to_alive = 0          # registry-observed rejoins
        self.transitions: list[tuple[str, str, str]] = []
        self._gaps: deque[float] = deque(maxlen=512)   # beat-to-beat, s

    # -- observations ------------------------------------------------------

    def _peer(self, identity: str) -> PeerState:
        p = self.peers.get(identity)
        if p is None:
            from apex_tpu.tenancy import namespace as tenancy_ns
            now = self._clock()
            p = self.peers[identity] = PeerState(
                identity=identity,
                tenant=tenancy_ns.tenant_of(identity),
                joined_at=now, last_any=now)
        return p

    def _revive(self, p: PeerState) -> None:
        """Activity from a non-ALIVE peer: recovery (SUSPECT) or rejoin
        (DEAD, counted)."""
        if p.state == DEAD:
            self.dead_to_alive += 1
            self.transitions.append((p.identity, DEAD, ALIVE))
            p.state = ALIVE
        elif p.state == SUSPECT:
            self.transitions.append((p.identity, SUSPECT, ALIVE))
            p.state = ALIVE

    def observe(self, hb: Heartbeat) -> None:
        """One heartbeat arrived (trainer thread, off the stat drain)."""
        now = self._clock()
        with self._lock:
            p = self._peer(hb.identity)
            if p.last_beat is not None:
                self._gaps.append(now - p.last_beat)
            if p.state == JOINING:
                self.transitions.append((p.identity, JOINING, ALIVE))
                p.state = ALIVE
            else:
                self._revive(p)
            p.role, p.pid, p.host = hb.role, hb.pid, hb.host
            p.fps, p.param_version = hb.fps, hb.param_version
            p.chunks_sent, p.acks_received = hb.chunks_sent, hb.acks_received
            p.resends = getattr(hb, "resends", 0)
            p.rerouted = getattr(hb, "rerouted", 0)
            p.rejoins_reported = max(p.rejoins_reported, hb.rejoins)
            p.parked = hb.parked
            gauges = getattr(hb, "gauges", None)
            if gauges:
                p.gauges = dict(gauges)
            wall_ts = getattr(hb, "wall_ts", 0.0)
            if wall_ts:
                p.offset_samples.append(self._wall() - wall_ts)
                p.clock_offset_s = _min_transit_offset(p.offset_samples)
                p.clock_offset_n = len(p.offset_samples)
            p.beats += 1
            p.last_beat = p.last_any = now

    def observe_seen(self, seen: dict[str, float]) -> None:
        """Message-arrival liveness from the chunk socket
        (``RemotePool.peer_seen`` monotonic times): refreshes ``last_any``
        without touching heartbeat gap statistics."""
        with self._lock:
            for identity, t in seen.items():
                p = self._peer(identity)
                if t > p.last_any:
                    p.last_any = t
                    self._revive(p)

    # -- the clock-driven half of the machine ------------------------------

    def tick(self) -> list[tuple[str, str, str]]:
        """Apply the silence thresholds; returns the transitions taken
        SINCE the last tick (observation-driven ones included)."""
        now = self._clock()
        c = self.comms
        with self._lock:
            for p in self.peers.values():
                silent = now - p.last_any
                if p.state in (ALIVE, JOINING) and silent > c.suspect_after_s:
                    self.transitions.append((p.identity, p.state, SUSPECT))
                    p.state = SUSPECT
                if p.state == SUSPECT and silent > c.dead_after_s:
                    self.transitions.append((p.identity, SUSPECT, DEAD))
                    p.state = DEAD
                    p.deaths += 1
            out, self.transitions = self.transitions, []
            return out

    # -- read surface ------------------------------------------------------

    def _counts(self) -> dict[str, int]:
        out = {JOINING: 0, ALIVE: 0, SUSPECT: 0, DEAD: 0}
        for p in self.peers.values():
            out[p.state] += 1
        return out

    def rejoins(self) -> int:
        with self._lock:
            return self.dead_to_alive + sum(p.rejoins_reported
                                            for p in self.peers.values())

    def dead_fraction(self, roles: tuple[str, ...] = ("actor",)) -> float:
        """Fraction of the peers in ``roles`` currently DEAD — the input
        to the learner's replay-ratio-floor reaction (0.0 while no such
        peer has ever registered: an empty fleet is not a dead one)."""
        with self._lock:
            peers = [p for p in self.peers.values() if p.role in roles]
            if not peers:
                return 0.0
            return sum(p.state == DEAD for p in peers) / len(peers)

    def _gap_percentiles(self) -> tuple[float | None, float | None]:
        if not self._gaps:
            return None, None
        s = sorted(self._gaps)

        def pct(q: float) -> float:
            return round(s[min(len(s) - 1, int(q * len(s)))], 3)

        return pct(0.50), pct(0.99)

    def metrics(self) -> dict:
        """The ``fleet_*`` scalar set (MetricLogger + bench ``fleet``)."""
        with self._lock:
            counts = self._counts()
            p50, p99 = self._gap_percentiles()
            return {
                "peers": len(self.peers),
                "alive": counts[ALIVE],
                "joining": counts[JOINING],
                "suspect": counts[SUSPECT],
                "dead": counts[DEAD],
                "parked": sum(p.parked for p in self.peers.values()),
                "rejoins": self.dead_to_alive
                + sum(p.rejoins_reported for p in self.peers.values()),
                "dead_to_alive": self.dead_to_alive,
                "deaths": sum(p.deaths for p in self.peers.values()),
                "hb_gap_p50_s": p50,
                "hb_gap_p99_s": p99,
            }

    def snapshot(self) -> dict:
        """Serializable fleet view (status server, fleet_summary.json):
        plain builtins only, so the restricted wire carries it."""
        now = self._clock()
        with self._lock:
            peers = [{
                "identity": p.identity, "tenant": p.tenant,
                "role": p.role, "state": p.state,
                "pid": p.pid, "host": p.host, "fps": p.fps,
                "param_version": p.param_version,
                "chunks_sent": p.chunks_sent,
                "acks_received": p.acks_received,
                "resends": p.resends, "rerouted": p.rerouted,
                "rejoins": p.rejoins_reported, "parked": p.parked,
                "beats": p.beats, "deaths": p.deaths,
                "silent_s": round(now - p.last_any, 1),
                "clock_offset_s": p.clock_offset_s,
                "clock_offset_n": p.clock_offset_n,
                "gauges": dict(p.gauges),
            } for _, p in sorted(self.peers.items())]
        return {"peers": peers, "metrics": self.metrics()}


def format_fleet_table(snapshot: dict) -> str:
    """Human fleet table for ``--role status``.  Peers group by tenant
    (multi-tenant fleets get one block per tenant, default first); a
    single-tenant fleet renders exactly the pre-tenancy table."""
    from apex_tpu.tenancy import namespace as tenancy_ns

    cols = ("identity", "role", "state", "pid", "host", "fps",
            "param_version", "chunks_sent", "rejoins", "parked", "silent_s")
    peers = list(snapshot["peers"])
    tenants = sorted({p.get("tenant") or tenancy_ns.DEFAULT_TENANT
                      for p in peers},
                     key=lambda t: (not tenancy_ns.is_default(t), t))
    rows = [[str(p.get(c, "")) for c in cols] for p in peers]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for tenant in tenants:
        if len(tenants) > 1:
            lines.append(f"-- tenant {tenant} --")
        for p, r in zip(peers, rows):
            if (p.get("tenant") or tenancy_ns.DEFAULT_TENANT) != tenant:
                continue
            lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    m = snapshot.get("metrics", {})
    lines.append("")
    lines.append(
        f"alive={m.get('alive')} suspect={m.get('suspect')} "
        f"dead={m.get('dead')} parked={m.get('parked')} "
        f"rejoins={m.get('rejoins')} "
        f"hb_gap_p50={m.get('hb_gap_p50_s')}s "
        f"p99={m.get('hb_gap_p99_s')}s")
    # role-specific serving gauges (the inference plane's queue depth /
    # batch percentiles, remote-policy actors' fallback counts) — one
    # line per peer that reported any, so new roles are never a blind
    # spot on the operator surface
    for p in snapshot["peers"]:
        g = p.get("gauges")
        if g:
            lines.append(f"{p['identity']}: " + " ".join(
                f"{k}={g[k]}" for k in sorted(g)))
    # wire codec plane (runtime/codec.py): learner-side decode counts +
    # the param-delta publisher's byte counters — the operator table
    # answers "is compression on, and is anything being rejected"
    wire = m.get("wire")
    if wire:
        lines.append("wire: " + " ".join(
            f"{k}={wire[k]}" for k in sorted(wire)))
    # fleet SLO objectives (apex_tpu/obs/slo): one line per judged/
    # observed objective when the learner runs the engine — the operator
    # table answers "is the fleet in objective" without a scrape stack
    slo = snapshot.get("slo")
    if slo:
        from apex_tpu.obs.slo import format_slo_lines
        lines.extend(format_slo_lines(slo))
    # serving tier (apex_tpu/serving): the canary machine, per-shard
    # pins, and the tail of the deployment timeline — the operator
    # table answers "what model is each shard serving" directly
    serving = snapshot.get("serving")
    if serving:
        from apex_tpu.serving.deploy import format_serving_lines
        lines.extend(format_serving_lines(serving))
    # multi-tenant plane (apex_tpu/tenancy): admissions, per-tenant
    # bands/placement, and the tenancy timeline tail — the operator
    # table answers "who shares this fleet and who owns which band"
    tenancy = snapshot.get("tenancy")
    if tenancy:
        from apex_tpu.tenancy.scheduler import format_tenancy_lines
        lines.extend(format_tenancy_lines(tenancy))
    # population plane (apex_tpu/population): per-lineage score/
    # generation/survival and the exploit/explore timeline tail — the
    # operator table answers "who is winning the ladder and who copied
    # whom" directly
    population = snapshot.get("population")
    if population:
        from apex_tpu.population.controller import format_population_lines
        lines.extend(format_population_lines(population))
    return "\n".join(lines)


class FleetStatusServer:
    """REP socket serving registry snapshots on ``comms.status_port``.

    Its own socket and its own thread — the ChunkReceiver's ROUTER stays
    single-threaded, and a status query can never block the data plane.
    zmq imports lazily so in-host trainers work without the comms extra.

    Three request kinds on the one socket: any plain frame returns the
    pickled registry snapshot (``--role status``); the frame
    ``b"metrics"`` returns Prometheus text exposition from
    ``metrics_fn`` (the trainer's live scalars/rates/latency histograms
    — :mod:`apex_tpu.obs.metrics`), so the fleet is pollable by
    standard tooling; a pickled ``("ctl", {...})`` tuple (the PBT
    controller's exploit/explore commands, :mod:`apex_tpu.population`)
    is handed to ``ctl_fn`` and acked ``("ctl_ok", info)`` — the hook
    ENQUEUES only (the trainer thread applies at its next health tick;
    a command must never touch learner state from this thread).
    Without a ``metrics_fn`` the metrics request degrades to a
    fleet-only exposition rendered from the registry itself; without a
    ``ctl_fn`` ctl frames degrade to status replies (old servers keep
    answering new controllers harmlessly).
    """

    def __init__(self, comms: CommsConfig, registry: FleetRegistry,
                 bind_ip: str = "*", metrics_fn=None, snapshot_fn=None,
                 ctl_fn=None):
        import zmq

        self._zmq = zmq
        self.registry = registry
        self.metrics_fn = metrics_fn
        self.ctl_fn = ctl_fn
        # optional richer status payload (the trainer's fleet_summary —
        # registry snapshot PLUS reaction/replay-service/drain metrics);
        # scale supervisors key off those extras, so the trainer passes it
        self.snapshot_fn = snapshot_fn
        self.sock = zmq.Context.instance().socket(zmq.REP)
        self.sock.bind(f"tcp://{bind_ip}:{comms.status_port}")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _metrics_text(self) -> str:
        from apex_tpu.obs import metrics as obs_metrics
        if self.metrics_fn is not None:
            return self.metrics_fn()
        gauges, labeled = obs_metrics.render_fleet(self.registry.snapshot())
        return obs_metrics.render(gauges=gauges, labeled=labeled)

    def _run(self) -> None:
        from apex_tpu.runtime import wire
        while not self._stop.is_set():
            if not self.sock.poll(200, self._zmq.POLLIN):
                continue
            req = self.sock.recv()
            if req == b"metrics":
                try:
                    text = self._metrics_text()
                except Exception as e:      # a scrape must never wedge REP
                    text = f"# metrics unavailable: {type(e).__name__}\n"
                self.sock.send(text.encode("utf-8", errors="replace"))
            else:
                reply = None
                if self.ctl_fn is not None and req != b"status":
                    try:
                        msg = wire.restricted_loads(req)
                    except Exception:
                        msg = None          # not a ctl frame: status
                    if (isinstance(msg, tuple) and len(msg) == 2
                            and msg[0] == "ctl"
                            and isinstance(msg[1], dict)):
                        try:
                            info = self.ctl_fn(dict(msg[1]))
                        except Exception as e:  # never wedge the REP
                            info = {"accepted": False,
                                    "error": type(e).__name__}
                        reply = wire.dumps(("ctl_ok", info))
                if reply is None:       # any other frame means "status"
                    try:
                        snap = (self.snapshot_fn()
                                if self.snapshot_fn is not None
                                else self.registry.snapshot())
                    except Exception:   # a status query must never wedge
                        snap = self.registry.snapshot()
                    reply = wire.dumps(snap)
                self.sock.send(reply)

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=5)
        self.sock.close(linger=0)


def ctl_request(comms: CommsConfig, cmd: dict,
                learner_ip: str | None = None,
                timeout_s: float = 5.0) -> dict | None:
    """Client half of the learner ctl surface (the PBT controller's
    exploit/explore commands): one REQ round-trip carrying
    ``("ctl", cmd)``; the server's ack info dict, or None when nothing
    answers (or an old server replied with a status snapshot)."""
    import zmq

    from apex_tpu.runtime import wire

    sock = zmq.Context.instance().socket(zmq.REQ)
    ip = learner_ip or comms.learner_ip
    sock.connect(f"tcp://{ip}:{comms.status_port}")
    try:
        sock.send(wire.dumps(("ctl", dict(cmd))))
        if not sock.poll(int(timeout_s * 1000), zmq.POLLIN):
            return None
        try:
            got = wire.restricted_loads(sock.recv())
        except wire.WireRejected:
            return None
        if isinstance(got, tuple) and len(got) == 2 \
                and got[0] == "ctl_ok" and isinstance(got[1], dict):
            return got[1]
        return None
    finally:
        sock.close(linger=0)


def status_request(comms: CommsConfig, learner_ip: str | None = None,
                   timeout_s: float = 5.0) -> dict | None:
    """Client half of the status surface: one REQ round-trip to the
    learner's :class:`FleetStatusServer`; None when nothing answers."""
    import zmq

    from apex_tpu.runtime import wire

    sock = zmq.Context.instance().socket(zmq.REQ)
    ip = learner_ip or comms.learner_ip
    sock.connect(f"tcp://{ip}:{comms.status_port}")
    try:
        sock.send(b"status")
        if sock.poll(int(timeout_s * 1000), zmq.POLLIN):
            return wire.restricted_loads(sock.recv())
        return None
    finally:
        sock.close(linger=0)
