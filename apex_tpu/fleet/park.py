"""Park-and-rejoin for actor/evaluator roles.

A role whose param stream goes stale (no publish for
``CommsConfig.park_after_s`` — a live learner republishes every couple of
seconds, see ``training/apex.py``) must not spin, crash, or wedge: it
PARKS.  Parked means the worker loop is blocked inside its queue adapter —
env and :class:`~apex_tpu.replay.frame_chunks.FrameChunkBuilder` state
stay exactly where they were, no acks are drained, no chunks ship — while
this controller retries the learner with jittered exponential backoff.

Each retry races the startup barrier against the param stream
(:func:`apex_tpu.runtime.transport.barrier_wait` with ``rejoin_sub``): a
learner respawned from its newest checkpoint re-releases the barrier
before its first publish, so whichever signal lands first reattaches the
fleet in seconds with no operator action.  On rejoin the sender's
ack-credit window resets — the dead learner took the outstanding acks
with it, and a stale window would wedge the first post-rejoin send
forever.

The spurious-park guard matters: a send wedged on credit exhaustion can
mean EITHER a dead learner or a healthy-but-backpressuring one.  The
controller therefore probes the CONFLATE subscriber first and only parks
when the params themselves are stale; a probe that finds params stashes
them (``take_pending``) so the worker's next poll still sees the newest
weights.
"""

from __future__ import annotations

import random
import time
import zlib

from apex_tpu.config import CommsConfig


class ParkController:
    """One role's park/rejoin state.  Wired into the socket queue adapters
    (:mod:`apex_tpu.runtime.roles`); never constructed for in-host pools
    (the learner and its workers die together there)."""

    def __init__(self, comms: CommsConfig, identity: str, stop_event,
                 sub=None, sender=None, role: str = "actor",
                 clock=time.monotonic, sleep=time.sleep):
        self.comms = comms
        self.identity = identity
        self.role = role
        self.stop_event = stop_event
        self.sub = sub
        self.sender = sender
        self._clock = clock
        self._sleep = sleep
        self._last_params = clock()
        self._pending = None
        self.parked = False
        self.parks = 0
        self.rejoins = 0
        # learner-epoch fencing (PR 8): rejoins split by what the epoch
        # stamp on the first post-park publish proved — a RESTARTED
        # learner (epoch bumped: the outstanding ack window died with it,
        # reset credits) vs a merely STALLED one (same epoch: the acks
        # are still coming, a reset would over-credit the window)
        self.restarts_seen = 0
        self.stall_resumes = 0
        # deterministic jitter per identity: a fleet parked by one learner
        # death must not retry in lockstep (thundering-herd barrier hellos)
        self._rng = random.Random(zlib.crc32(identity.encode()))

    # -- freshness bookkeeping ---------------------------------------------

    def note_params(self) -> None:
        self._last_params = self._clock()

    def stale(self) -> bool:
        return (self._clock() - self._last_params
                > self.comms.park_after_s)

    def take_pending(self):
        got, self._pending = self._pending, None
        return got

    def park_state(self) -> tuple[bool, int]:
        """(parked, rejoins) — the HeartbeatEmitter's ``park_fn`` hook."""
        return self.parked, self.rejoins

    # -- the park loop ------------------------------------------------------

    def _beat_parked(self) -> None:
        """Best-effort parked heartbeat straight through the sender (the
        worker loop is blocked in an adapter, so its own emitter is not
        running) — visible when the learner is merely stalled, dropped on
        the floor when it is gone."""
        if self.sender is None:
            return
        from apex_tpu.fleet.heartbeat import Heartbeat
        try:
            self.sender.send_stat(Heartbeat(
                identity=self.identity, role=self.role, parked=True,
                rejoins=self.rejoins))
        except Exception:
            pass

    def park_and_rejoin(self, sub=None):
        """Block until the param stream is live again; returns the newest
        ``(version, params)`` (also stashed for :meth:`take_pending`
        callers) or None when not actually stale / stopped.

        Called from two places: the param adapter's poll (found nothing,
        staleness exceeded) and the chunk adapter's wedged send."""
        from apex_tpu.runtime import transport

        sub = sub if sub is not None else self.sub
        got = sub.poll(0)
        if got is not None:             # learner alive: never was a park
            self.note_params()
            self._pending = got
            return got
        if not self.stale() or self.stop_event.is_set():
            return None

        self.parked = True
        self.parks += 1
        # the epoch we last saw params under: the rejoin's restart-vs-
        # stall verdict compares the resumed stream's stamp against this
        self._epoch_at_park = getattr(sub, "learner_epoch", 0)
        backoff = self.comms.rejoin_backoff_s
        try:
            while not self.stop_event.is_set():
                self._beat_parked()
                if transport.barrier_wait(
                        self.comms, self.identity,
                        stop_event=self.stop_event,
                        timeout_s=self.comms.rejoin_attempt_s,
                        rejoin_sub=sub):
                    got = self._await_params(sub)
                    if got is not None:
                        return got
                    continue        # barrier said go but no publish: retry
                self._sleep(min(backoff * (0.5 + self._rng.random()),
                                self.comms.rejoin_backoff_max_s))
                backoff = min(2 * backoff, self.comms.rejoin_backoff_max_s)
        finally:
            self.parked = False
        return None

    def _await_params(self, sub):
        """Barrier released (or the stream twitched): wait out the
        learner's first publish, then account the rejoin.

        Epoch fencing decides the credit-window question: an epoch-
        stamped stream that resumed under the SAME epoch is a stalled
        learner whose outstanding acks are still in flight — resetting
        would over-credit the window — while a bumped (or unstamped)
        epoch means a restart took the acks with it, so the window
        resets exactly as before fencing existed."""
        deadline = self._clock() + 4 * self.comms.rejoin_attempt_s
        while not self.stop_event.is_set() and self._clock() < deadline:
            got = sub.poll(200)
            if got is not None:
                self.note_params()
                self._pending = got
                self.rejoins += 1
                epoch = getattr(sub, "learner_epoch", 0)
                pre = getattr(self, "_epoch_at_park", 0)
                stalled = bool(epoch) and epoch == pre
                if stalled:
                    self.stall_resumes += 1
                else:
                    if epoch and pre and epoch != pre:
                        self.restarts_seen += 1
                    if self.sender is not None:
                        # the dead learner never acked the in-flight
                        # window; a stale window wedges the first
                        # post-rejoin send
                        self.sender.reset_credits()
                return got
        return None
