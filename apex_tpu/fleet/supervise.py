"""Host supervisor: rate-limited, respawn-budgeted role relauncher.

Replaces the bare ``while true; do python -m apex_tpu.runtime ...; sleep 5``
loops the deploy bootstraps used to inline (``deploy/actor.sh``,
``deploy/evaluator.sh``) with the SAME semantics the in-host pool applies
to its workers (``apex_tpu.actors.pool.ActorPool``): respawns are a RATE,
not a lifetime cap — ``--max-respawns`` per ``--window`` seconds anchored
at the last respawn, so sporadic crashes over a long run never retire a
healthy role, while a crash loop (child dying under ``--min-uptime``)
backs off exponentially and eventually halts loudly.

The child's rejoin path is the role's own (:mod:`apex_tpu.fleet.park` +
the ``barrier_wait`` rejoin race), so a respawned process reattaches to a
running learner in seconds.  ``APEX_RESPAWN_COUNT`` is exported to each
life so the chaos harness (:mod:`apex_tpu.fleet.chaos`) can arm
deterministic kills on the first life only.

Pure stdlib — the supervisor must come up on a stock interpreter before
the baked env, JAX, or zmq are importable.

Usage::

    python -m apex_tpu.fleet.supervise [--max-respawns N] [--window S]
        [--min-uptime S] [--backoff S] [--backoff-max S] -- CMD [ARG...]
"""

from __future__ import annotations

import argparse
import random
import subprocess
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.fleet.supervise",
        description="rate-limited role supervisor (ActorPool respawn "
                    "semantics for whole processes)")
    p.add_argument("--max-respawns", type=int, default=10,
                   help="respawn budget per window (default 10)")
    p.add_argument("--window", type=float, default=600.0,
                   help="budget window seconds, anchored at the last "
                        "respawn (default 600)")
    p.add_argument("--min-uptime", type=float, default=60.0,
                   help="a life shorter than this counts against the "
                        "budget and doubles the backoff (default 60)")
    p.add_argument("--backoff", type=float, default=5.0,
                   help="initial respawn delay seconds (default 5)")
    p.add_argument("--backoff-max", type=float, default=60.0,
                   help="backoff ceiling seconds (default 60)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- then the role command to supervise")
    return p


def supervise(cmd: list[str], max_respawns: int = 10, window_s: float = 600.0,
              min_uptime_s: float = 60.0, backoff_s: float = 5.0,
              backoff_max_s: float = 60.0, sleep=time.sleep,
              clock=time.monotonic, run=None) -> int:
    """Run ``cmd`` until it exits 0 or the respawn budget is spent.
    Returns the supervisor's exit code (0 = child finished cleanly,
    1 = budget exhausted, last child rc otherwise on interrupt)."""
    import os

    run = run or (lambda c, env: subprocess.run(c, env=env).returncode)
    rng = random.Random()
    lives = 0
    window_respawns = 0
    last_respawn = 0.0
    backoff = backoff_s
    while True:
        env = dict(os.environ, APEX_RESPAWN_COUNT=str(lives))
        start = clock()
        rc = run(cmd, env)
        uptime = clock() - start
        lives += 1
        if rc == 0:
            print(f"supervise: {cmd[0]} exited cleanly after "
                  f"{uptime:.0f}s", flush=True)
            return 0
        # a full quiet window since the LAST respawn restores the budget
        # (rate limit, not lifetime cap — ActorPool._refresh_budget)
        if window_respawns and clock() - last_respawn > window_s:
            window_respawns = 0
        if uptime >= min_uptime_s:
            backoff = backoff_s          # long life: crash was sporadic
        else:
            backoff = min(2 * backoff, backoff_max_s)
        if window_respawns >= max_respawns:
            print(f"supervise: {window_respawns} respawns inside "
                  f"{window_s:.0f}s — crash loop, halting (rc={rc})",
                  flush=True)
            return 1
        window_respawns += 1
        last_respawn = clock()
        delay = backoff * (0.5 + rng.random())   # jitter: no fleet lockstep
        print(f"supervise: {cmd[0]} exited rc={rc} after {uptime:.0f}s; "
              f"respawn {window_respawns}/{max_respawns} in {delay:.1f}s",
              flush=True)
        sleep(delay)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("supervise: no command given (… -- CMD ARG...)",
              file=sys.stderr)
        return 2
    return supervise(cmd, max_respawns=args.max_respawns,
                     window_s=args.window, min_uptime_s=args.min_uptime,
                     backoff_s=args.backoff, backoff_max_s=args.backoff_max)


if __name__ == "__main__":
    raise SystemExit(main())
