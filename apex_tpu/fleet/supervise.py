"""Host supervisor: rate-limited, respawn-budgeted role relauncher.

Replaces the bare ``while true; do python -m apex_tpu.runtime ...; sleep 5``
loops the deploy bootstraps used to inline (``deploy/actor.sh``,
``deploy/evaluator.sh``) with the SAME semantics the in-host pool applies
to its workers (``apex_tpu.actors.pool.ActorPool``): respawns are a RATE,
not a lifetime cap — ``--max-respawns`` per ``--window`` seconds anchored
at the last respawn, so sporadic crashes over a long run never retire a
healthy role, while a crash loop (child dying under ``--min-uptime``)
backs off exponentially and eventually halts loudly.

The child's rejoin path is the role's own (:mod:`apex_tpu.fleet.park` +
the ``barrier_wait`` rejoin race), so a respawned process reattaches to a
running learner in seconds.  ``APEX_RESPAWN_COUNT`` is exported to each
life so the chaos harness (:mod:`apex_tpu.fleet.chaos`) can arm
deterministic kills on the first life only.

Pure stdlib — the supervisor must come up on a stock interpreter before
the baked env, JAX, or zmq are importable.  (The OPTIONAL elastic mode
below lazily imports the zmq status client only when ``--scale-max`` is
given.)

Elastic mode (PR 8 registry reactions): ``--scale-max N`` turns the
supervisor into a fleet-sized one — it keeps between ``--scale-min`` and
``--scale-max`` copies of the role command alive (the ``{slot}``
placeholder in the command becomes each child's slot index, i.e. its
actor id), and every ``--scale-interval`` seconds probes the learner's
status port for a scaling signal.  Two signals (``--scale-signal``):

* ``drain`` (default, PR 8): the aggregate actor drain-bound fraction
  (PR 4's ``ActorTimingStat``, surfaced in the trainer's fleet
  summary).  A drain-BOUND fleet is backpressured by the learner — more
  actors buy nothing, scale down; a fleet that barely drains means the
  learner is starving for data — scale up.
* ``slo``: the fleet SLO engine's alert snapshot
  (:mod:`apex_tpu.obs.slo`, the ROADMAP serving-tier item verbatim): a
  page-grade BREACH means the tier is out of objective — add capacity;
  a fleet whose every judged objective has burned ZERO error budget
  over the slow window ("idle") can retire a replica; everything
  between (BURNING, warn, RESOLVED cooldown) holds.  The round-trip
  p99 objective makes this exactly "autoscale the infer tier on its
  latency SLO".

One step per tick, clamped, either signal.

Usage::

    python -m apex_tpu.fleet.supervise [--max-respawns N] [--window S]
        [--min-uptime S] [--backoff S] [--backoff-max S] -- CMD [ARG...]
    python -m apex_tpu.fleet.supervise --scale-min 1 --scale-max 8 \
        [--scale-signal drain|slo] [--scale-interval S] \
        [--learner-ip IP] [--status-port P] \
        -- CMD --actor-id {slot} [ARG...]
"""

from __future__ import annotations

import argparse
import random
import subprocess
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.fleet.supervise",
        description="rate-limited role supervisor (ActorPool respawn "
                    "semantics for whole processes)")
    p.add_argument("--max-respawns", type=int, default=10,
                   help="respawn budget per window (default 10)")
    p.add_argument("--window", type=float, default=600.0,
                   help="budget window seconds, anchored at the last "
                        "respawn (default 600)")
    p.add_argument("--min-uptime", type=float, default=60.0,
                   help="a life shorter than this counts against the "
                        "budget and doubles the backoff (default 60)")
    p.add_argument("--backoff", type=float, default=5.0,
                   help="initial respawn delay seconds (default 5)")
    p.add_argument("--backoff-max", type=float, default=60.0,
                   help="backoff ceiling seconds (default 60)")
    p.add_argument("--scale-max", type=int, default=0,
                   help="elastic mode: keep up to this many copies of the "
                        "command alive, scaled by learner backpressure "
                        "(0 = classic single-child supervision)")
    p.add_argument("--scale-min", type=int, default=1,
                   help="elastic mode floor (default 1)")
    p.add_argument("--scale-interval", type=float, default=30.0,
                   help="seconds between backpressure probes (default 30)")
    p.add_argument("--scale-signal", choices=["drain", "slo"],
                   default="drain",
                   help="elastic mode sizing signal: 'drain' = actor "
                        "drain-bound fraction (PR 8 backpressure), "
                        "'slo' = the fleet SLO engine's alert severity "
                        "(apex_tpu/obs/slo — breach adds capacity, a "
                        "zero-burn fleet retires one)")
    p.add_argument("--learner-ip", default="127.0.0.1",
                   help="elastic mode: learner host for the status probe")
    p.add_argument("--status-port", type=int, default=52003,
                   help="elastic mode: learner fleet-status port")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- then the role command to supervise")
    return p


# -- elastic fleet supervision (PR 8) ---------------------------------------

def scale_decision(drain_frac: float | None, n_now: int, n_min: int,
                   n_max: int, high: float = 0.5, low: float = 0.15) -> int:
    """Target child count from the actor drain-bound fraction.

    ``drain_frac`` is the share of actor wall time spent blocked shipping
    chunks (the learner's aggregate of PR 4's ``ActorTimingStat``): at or
    above ``high`` the learner is the bottleneck and an actor can be
    retired; at or below ``low`` the learner is starving and one more
    actor helps.  One step per tick, clamped to [n_min, n_max]; an
    unreadable signal (None — learner unreachable or no worker reporting
    yet) holds steady."""
    if drain_frac is None:
        target = n_now
    elif drain_frac >= high:
        target = n_now - 1
    elif drain_frac <= low:
        target = n_now + 1
    else:
        target = n_now
    return max(n_min, min(n_max, target))


def scale_decision_slo(slo: dict | None, n_now: int, n_min: int,
                       n_max: int) -> int:
    """Target child count from the SLO engine's snapshot (the
    ``--scale-signal slo`` decision, fed by :func:`fleet_slo`).

    A page-grade breach (``severity >= 2``) means the tier is failing
    its objective — one more replica; an ``idle`` fleet (every judged
    objective at ZERO budget burn over the slow window) is provably
    over-provisioned — one fewer.  BURNING/warn/RESOLVED-cooldown and an
    unreadable snapshot (None — learner unreachable, engine not up yet)
    hold: scaling on a half-clear signal is how autoscalers flap.  One
    step per tick, clamped, like :func:`scale_decision`."""
    if not slo:
        target = n_now
    elif int(slo.get("severity", 0)) >= 2:
        target = n_now + 1
    elif slo.get("idle"):
        target = n_now - 1
    else:
        target = n_now
    return max(n_min, min(n_max, target))


def fleet_slo(learner_ip: str = "127.0.0.1", status_port: int = 52003,
              timeout_s: float = 5.0) -> dict | None:
    """One status round-trip for the trainer's SLO snapshot (the ``slo``
    section of the fleet summary), or None when nothing answers / no
    engine is running.  Lazy zmq, like :func:`fleet_drain_frac`."""
    import dataclasses

    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.registry import status_request

    comms = dataclasses.replace(CommsConfig(), status_port=status_port)
    try:
        snap = status_request(comms, learner_ip=learner_ip,
                              timeout_s=timeout_s)
    except Exception:
        return None
    if not snap:
        return None
    return snap.get("slo")


def fleet_drain_frac(learner_ip: str = "127.0.0.1",
                     status_port: int = 52003,
                     timeout_s: float = 5.0) -> float | None:
    """One status round-trip to the learner for the aggregate actor
    drain-bound fraction (``metrics.actor_drain_frac`` in the trainer's
    fleet summary), or None when nothing answers / nothing reported.
    zmq imports lazily — the classic supervision path stays stdlib."""
    import dataclasses

    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.registry import status_request

    comms = dataclasses.replace(CommsConfig(), status_port=status_port)
    try:
        snap = status_request(comms, learner_ip=learner_ip,
                              timeout_s=timeout_s)
    except Exception:
        return None
    if not snap:
        return None
    return snap.get("metrics", {}).get("actor_drain_frac")


class ScaleSupervisor:
    """Backpressure-scaled multi-child supervisor.

    Keeps ``target`` copies of ``cmd`` alive — slot ``i``'s command has
    every ``{slot}`` placeholder replaced by ``i``, so a fleet of
    ``--actor-id {slot}`` children lands on distinct epsilon-ladder
    slots.  Dead children respawn on their own slot (APEX_RESPAWN_COUNT
    exported per life, so chaos kills stay first-life-only); scale-down
    retires the HIGHEST slots first (the greediest end of the ladder).

    ``spawn(cmd, env) -> handle`` and ``probe() -> signal`` inject for
    tests; a handle needs ``poll()`` and ``terminate()``.  ``decide``
    maps ``(signal, n_now, n_min, n_max) -> target`` — default is the
    drain-frac :func:`scale_decision`; ``--scale-signal slo`` swaps in
    :func:`scale_decision_slo` with :func:`fleet_slo` as the probe.
    """

    def __init__(self, cmd: list[str], n_min: int, n_max: int,
                 interval_s: float = 30.0, probe=None, spawn=None,
                 clock=time.monotonic, sleep=time.sleep,
                 high: float = 0.5, low: float = 0.15, decide=None):
        import os

        self.cmd = list(cmd)
        self.n_min = max(1, int(n_min))
        self.n_max = max(self.n_min, int(n_max))
        self.interval_s = float(interval_s)
        self.probe = probe or (lambda: None)
        self._environ = os.environ
        self.spawn = spawn or (lambda c, env: subprocess.Popen(c, env=env))
        self._clock = clock
        self._sleep = sleep
        self.high, self.low = float(high), float(low)
        self.decide = decide or (
            lambda sig, n, lo, hi: scale_decision(sig, n, lo, hi,
                                                  high=self.high,
                                                  low=self.low))
        self.children: dict[int, object] = {}       # slot -> handle
        self._lives: dict[int, int] = {}            # slot -> spawn count
        self.target = self.n_min
        self.scale_ups = 0
        self.scale_downs = 0

    def _cmd_for(self, slot: int) -> list[str]:
        return [a.replace("{slot}", str(slot)) for a in self.cmd]

    def _spawn(self, slot: int) -> None:
        env = dict(self._environ,
                   APEX_RESPAWN_COUNT=str(self._lives.get(slot, 0)))
        self.children[slot] = self.spawn(self._cmd_for(slot), env)
        self._lives[slot] = self._lives.get(slot, 0) + 1

    def _apply_target(self) -> None:
        for slot in range(self.target):
            if slot not in self.children:
                self._spawn(slot)
        for slot in sorted(self.children, reverse=True):
            if slot >= self.target:
                self.children.pop(slot).terminate()

    def tick(self) -> None:
        """One supervision round: reap/respawn dead children inside the
        target, then re-decide the target from the backpressure probe."""
        for slot, h in list(self.children.items()):
            if h.poll() is not None:
                del self.children[slot]
                if slot < self.target:
                    self._spawn(slot)
        new = self.decide(self.probe(), self.target, self.n_min,
                          self.n_max)
        if new > self.target:
            self.scale_ups += 1
            print(f"supervise: scale up {self.target} -> {new}",
                  flush=True)
        elif new < self.target:
            self.scale_downs += 1
            print(f"supervise: scale down {self.target} -> {new}",
                  flush=True)
        self.target = new
        self._apply_target()

    def run(self, max_seconds: float | None = None) -> int:
        import signal

        def _term(signum, frame):   # teardown must reap the whole fleet
            raise SystemExit(128 + signum)

        try:
            signal.signal(signal.SIGTERM, _term)
        except ValueError:
            pass                    # not the main thread
        deadline = (None if max_seconds is None
                    else self._clock() + max_seconds)
        self._apply_target()
        next_probe = self._clock() + self.interval_s
        try:
            while deadline is None or self._clock() < deadline:
                # reap/respawn every beat; probe at the slower cadence
                for slot, h in list(self.children.items()):
                    if h.poll() is not None:
                        del self.children[slot]
                        if slot < self.target:
                            self._spawn(slot)
                if self._clock() >= next_probe:
                    self.tick()
                    next_probe = self._clock() + self.interval_s
                self._sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            for h in self.children.values():
                h.terminate()
            self.children.clear()
        return 0


def supervise(cmd: list[str], max_respawns: int = 10, window_s: float = 600.0,
              min_uptime_s: float = 60.0, backoff_s: float = 5.0,
              backoff_max_s: float = 60.0, sleep=time.sleep,
              clock=time.monotonic, run=None) -> int:
    """Run ``cmd`` until it exits 0 or the respawn budget is spent.
    Returns the supervisor's exit code (0 = child finished cleanly,
    1 = budget exhausted, last child rc otherwise on interrupt).

    A SIGTERM/SIGINT to the supervisor TERMINATES the current child
    before exiting — without the forwarding, killing a supervisor
    (topology teardown, `kill $pid` in run_local.sh's trap) leaked its
    child as an orphan still bound to the role's ports, which then
    shadowed the next fleet launched on the same host."""
    import os
    import signal

    if run is None:
        child: dict = {"p": None}

        def run(c, env):
            p = subprocess.Popen(c, env=env)
            child["p"] = p
            try:
                return p.wait()
            finally:
                child["p"] = None

        def _forward(signum, frame):
            p = child["p"]
            if p is not None:
                p.terminate()
            raise SystemExit(128 + signum)

        try:
            signal.signal(signal.SIGTERM, _forward)
            signal.signal(signal.SIGINT, _forward)
        except ValueError:
            pass                    # not the main thread: no forwarding
    rng = random.Random()
    lives = 0
    window_respawns = 0
    last_respawn = 0.0
    backoff = backoff_s
    while True:
        env = dict(os.environ, APEX_RESPAWN_COUNT=str(lives))
        start = clock()
        rc = run(cmd, env)
        uptime = clock() - start
        lives += 1
        if rc == 0:
            print(f"supervise: {cmd[0]} exited cleanly after "
                  f"{uptime:.0f}s", flush=True)
            return 0
        # a full quiet window since the LAST respawn restores the budget
        # (rate limit, not lifetime cap — ActorPool._refresh_budget)
        if window_respawns and clock() - last_respawn > window_s:
            window_respawns = 0
        if uptime >= min_uptime_s:
            backoff = backoff_s          # long life: crash was sporadic
        else:
            backoff = min(2 * backoff, backoff_max_s)
        if window_respawns >= max_respawns:
            print(f"supervise: {window_respawns} respawns inside "
                  f"{window_s:.0f}s — crash loop, halting (rc={rc})",
                  flush=True)
            return 1
        window_respawns += 1
        last_respawn = clock()
        delay = backoff * (0.5 + rng.random())   # jitter: no fleet lockstep
        print(f"supervise: {cmd[0]} exited rc={rc} after {uptime:.0f}s; "
              f"respawn {window_respawns}/{max_respawns} in {delay:.1f}s",
              flush=True)
        sleep(delay)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("supervise: no command given (… -- CMD ARG...)",
              file=sys.stderr)
        return 2
    if args.scale_max > 0:
        if args.scale_signal == "slo":
            probe = (lambda: fleet_slo(args.learner_ip,
                                       args.status_port))
            decide = scale_decision_slo
        else:
            probe = (lambda: fleet_drain_frac(args.learner_ip,
                                              args.status_port))
            decide = None           # the drain-frac default
        sup = ScaleSupervisor(
            cmd, n_min=args.scale_min, n_max=args.scale_max,
            interval_s=args.scale_interval, probe=probe, decide=decide)
        return sup.run()
    return supervise(cmd, max_respawns=args.max_respawns,
                     window_s=args.window, min_uptime_s=args.min_uptime,
                     backoff_s=args.backoff, backoff_max_s=args.backoff_max)


if __name__ == "__main__":
    raise SystemExit(main())
