"""Deterministic fault injection for the multi-host runtime.

A control plane that has never watched its fleet die is decoration.  This
module turns a seed + a compact spec into a REPLAYABLE fault schedule and
injects it through thin wrappers around the two transport hot spots —
chunk sends (actor side) and param publishes (learner side) — so the same
``CHAOS_SEED`` produces the same kills, drops, delays, and stalls, message
for message, run after run.

Spec (``CHAOS_SPEC``, JSON; every key optional)::

    {"kill": {"actor-0": 30, "learner": 60},   # exit 137 at send/publish N
     "drop_frac": 0.1,                          # fraction of chunks dropped
     "delay_frac": 0.1, "delay_s": 0.05,        # fraction of chunks delayed
     "stall_at": 20, "stall_s": 3.0,            # one publish stall window
     # partition-grade faults (PR 8):
     "ack_withhold": {"at": 10, "n": 5, "hold_s": 3.0},  # learner ingress:
     #   park the acks of chunks [at, at+n) for hold_s — credit windows
     #   exhaust, senders retry, acks eventually flow: DELAY, never loss
     "mute": ["replay-0"],                      # directional link drop:
     #   the named role's OUTGOING replies vanish (its ingress stays up —
     #   actor->shard up while shard->learner down)
     "epoch_skew": {"learner": -1},             # learner-epoch fencing:
     #   skew this identity's outgoing replay write-back epochs (negative
     #   = stale: shards must reject, count, and stay uncorrupted)
     "score_bias": {"evaluator": {"after_s": 60, "delta": -100.0}}}
     #   model-quality regression injection (the serving tier's canary
     #   drills): after after_s of the evaluator's run, every reported
     #   episode score shifts by delta — the eval-ladder gauges and the
     #   eval_score SLO see a degraded model, deterministically.  Keys
     #   match by PREFIX (evaluator identities carry a uuid suffix).

Determinism: one RNG draw per message, streamed from
``seed ^ crc32(identity)``, so a message's fate depends only on (seed,
identity, message index) — never on wall clock or interleaving.  Kills use
``os._exit(137)``: no finally blocks, no atexit, no socket lingering —
the closest a process gets to SIGKILLing itself.

Respawn awareness: a supervisor-restarted process inherits the same env,
and a deterministic kill-at-N would execute again every life — a kill
loop, not a chaos test.  ``APEX_RESPAWN_COUNT`` (exported by
``apex_tpu.fleet.supervise`` and by test harnesses doing their own
restarts) therefore disarms the ``kill`` entries on every life after the
first; drop/delay/stall schedules stay live.

Activation is env-driven (``chaos_from_env``) so the localhost topology
(``scripts/run_local.sh``), the deploy scripts, and pytest subprocesses
all inject the same way: export and go.
"""

from __future__ import annotations

import json
import os
import random
import time
import zlib
from dataclasses import dataclass


@dataclass(frozen=True)
class ChaosPlan:
    """The schedule resolved for ONE wire identity."""

    seed: int
    identity: str
    kill_at: int | None = None      # message index to die at (armed lives)
    drop_frac: float = 0.0
    delay_frac: float = 0.0
    delay_s: float = 0.05
    stall_at: int | None = None     # publish index to stall at
    stall_s: float = 0.0
    # learner-ingress ack withholding (ChunkReceiver injects)
    ack_withhold_at: int | None = None
    ack_withhold_n: int = 1
    ack_withhold_s: float = 3.0
    # directional link drop: this identity's outgoing replies vanish
    mute_replies: bool = False
    # learner-epoch skew applied to outgoing replay write-backs
    epoch_skew: int = 0
    # evaluator score bias (canary drills): reported episode scores
    # shift by delta once after_s of the role's run has elapsed
    score_bias_after_s: float | None = None
    score_bias_delta: float = 0.0

    def rng(self) -> random.Random:
        return random.Random(self.seed ^ zlib.crc32(self.identity.encode()))


class ChaosConfig:
    """Parsed seed + spec; :meth:`plan_for` resolves one role's plan."""

    def __init__(self, seed: int, spec: dict, respawn_count: int = 0):
        self.seed = seed
        self.spec = spec
        self.respawn_count = respawn_count

    def plan_for(self, identity: str) -> ChaosPlan:
        from apex_tpu.tenancy import namespace as tenancy_ns

        # tenant-scoped targeting (PR 13): a spec with a "tenant" field
        # applies ONLY to that tenant's peers (parsed off the namespaced
        # identity) — and its kill/mute/skew/score_bias keys may then
        # name the BARE role id ("actor-0" hits "rally/actor-0"), so a
        # drill can blast one tenant with zero radius into its
        # neighbors.  Without the field, behavior is exactly pre-tenancy
        # (full-identity matching, every tenant exposed alike).
        spec_tenant = self.spec.get("tenant")
        tenant, base = tenancy_ns.split(identity)
        if spec_tenant and tenant != spec_tenant:
            return ChaosPlan(seed=self.seed, identity=identity)  # no-op

        def lookup(table: dict):
            if identity in table:
                return table[identity]
            if spec_tenant and base in table:
                return table[base]
            return None

        kill = lookup(self.spec.get("kill", {}))
        if self.respawn_count > 0:
            kill = None             # kills are first-life only (see above)
        aw = self.spec.get("ack_withhold") or {}
        # score_bias keys match by PREFIX: evaluator identities carry a
        # random uuid suffix ("evaluator-0-ab12cd"), so the spec names
        # the stable stem ("evaluator" / "evaluator-0")
        sb = None
        for key, entry in sorted((self.spec.get("score_bias")
                                  or {}).items()):
            if identity.startswith(key) \
                    or (spec_tenant and base.startswith(key)):
                sb = entry
                break
        mute = self.spec.get("mute", ())
        skew = lookup(self.spec.get("epoch_skew", {}))
        return ChaosPlan(
            seed=self.seed, identity=identity,
            kill_at=kill,
            drop_frac=float(self.spec.get("drop_frac", 0.0)),
            delay_frac=float(self.spec.get("delay_frac", 0.0)),
            delay_s=float(self.spec.get("delay_s", 0.05)),
            stall_at=self.spec.get("stall_at"),
            stall_s=float(self.spec.get("stall_s", 0.0)),
            ack_withhold_at=aw.get("at"),
            ack_withhold_n=int(aw.get("n", 1)),
            ack_withhold_s=float(aw.get("hold_s", 3.0)),
            mute_replies=(identity in mute
                          or bool(spec_tenant and base in mute)),
            epoch_skew=int(skew or 0),
            score_bias_after_s=(None if sb is None
                                else float(sb.get("after_s", 0.0))),
            score_bias_delta=(0.0 if sb is None
                              else float(sb.get("delta", 0.0))))


def chaos_from_env(environ=None) -> ChaosConfig | None:
    """None unless ``CHAOS_SEED`` is set (empty string counts as unset, so
    shell scripts can export it unconditionally)."""
    e = os.environ if environ is None else environ
    seed = e.get("CHAOS_SEED", "")
    if not str(seed).strip():
        return None
    spec = json.loads(e.get("CHAOS_SPEC") or "{}")
    return ChaosConfig(int(seed), spec,
                       respawn_count=int(e.get("APEX_RESPAWN_COUNT", "0")
                                         or 0))


def _die(identity: str, index: int) -> None:
    print(f"chaos: killing {identity} at message {index} (exit 137)",
          flush=True)
    os._exit(137)


class ChaosChunkSender:
    """Wraps :class:`apex_tpu.runtime.transport.ChunkSender`; one RNG draw
    per chunk decides drop/delay, and ``kill_at`` fires on the send index.
    A dropped chunk consumes no credit (the loss is actor-side, before the
    socket) — the learner simply never sees it, exactly like a process
    dying mid-buffer."""

    def __init__(self, inner, plan: ChaosPlan, sleep=time.sleep):
        self.inner = inner
        self.plan = plan
        self._sleep = sleep
        self._rng = plan.rng()
        self._n = 0
        self.dropped = 0
        self.delayed = 0

    def send_chunk(self, msg, stop_event=None, max_wait_s=None) -> bool:
        i = self._n
        self._n += 1
        if self.plan.kill_at is not None and i >= self.plan.kill_at:
            _die(self.plan.identity, i)
        r = self._rng.random()
        if r < self.plan.drop_frac:
            self.dropped += 1
            return True
        if r < self.plan.drop_frac + self.plan.delay_frac:
            self.delayed += 1
            self._sleep(self.plan.delay_s)
        return self.inner.send_chunk(msg, stop_event, max_wait_s=max_wait_s)

    # pass-throughs the adapters/emitters rely on
    def send_stat(self, stat) -> None:
        self.inner.send_stat(stat)

    def reset_credits(self) -> None:
        self.inner.reset_credits()

    def note_resend(self) -> None:
        note = getattr(self.inner, "note_resend", None)
        if note is not None:
            note()

    @property
    def chunks_sent(self) -> int:
        return self.inner.chunks_sent

    @property
    def acks_received(self) -> int:
        return self.inner.acks_received

    @property
    def resends(self) -> int:
        return getattr(self.inner, "resends", 0)

    @property
    def rerouted(self) -> int:
        return getattr(self.inner, "rerouted", 0)

    def wire_gauges(self) -> dict:
        fn = getattr(self.inner, "wire_gauges", None)
        return fn() if callable(fn) else {}

    def close(self, *a, **kw) -> None:
        self.inner.close(*a, **kw)


class ChaosParamPublisher:
    """Wraps :class:`apex_tpu.runtime.transport.ParamPublisher`; the
    publish index drives the learner-side schedule (kill / stall)."""

    def __init__(self, inner, plan: ChaosPlan, sleep=time.sleep):
        self.inner = inner
        self.plan = plan
        self._sleep = sleep
        self._n = 0
        self.stalls = 0

    def publish(self, version: int, params) -> None:
        i = self._n
        self._n += 1
        if self.plan.kill_at is not None and i >= self.plan.kill_at:
            _die(self.plan.identity, i)
        if self.plan.stall_at is not None and i == self.plan.stall_at \
                and self.plan.stall_s > 0:
            self.stalls += 1
            self._sleep(self.plan.stall_s)
        self.inner.publish(version, params)

    def close(self) -> None:
        self.inner.close()


def maybe_wrap_sender(sender, identity: str):
    """Env-gated wrap for actor/evaluator chunk senders."""
    chaos = chaos_from_env()
    if chaos is None:
        return sender
    return ChaosChunkSender(sender, chaos.plan_for(identity))


def maybe_wrap_publisher(publisher, identity: str = "learner"):
    """Env-gated wrap for the learner's param publisher."""
    chaos = chaos_from_env()
    if chaos is None:
        return publisher
    return ChaosParamPublisher(publisher, chaos.plan_for(identity))
