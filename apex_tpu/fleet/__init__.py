"""Fleet control plane: heartbeats, membership, park-and-rejoin, chaos.

Ape-X's premise is a fleet of hundreds of actor processes feeding one
learner; at that scale role death is routine, not exceptional.  This
package is the supervision layer the socket runtime
(:mod:`apex_tpu.runtime`) was missing:

* :mod:`~apex_tpu.fleet.heartbeat` — the periodic liveness message every
  role ships on the stat channel it already has.
* :mod:`~apex_tpu.fleet.registry` — the learner-side membership machine
  (JOINING → ALIVE → SUSPECT → DEAD), the ``fleet_*`` scalars, and the
  ``--role status`` snapshot surface.
* :mod:`~apex_tpu.fleet.park` — actor/evaluator staleness detection and
  the jittered-backoff rejoin race against a respawned learner's barrier.
* :mod:`~apex_tpu.fleet.chaos` — seeded deterministic fault schedules
  (kills, drops, delays, stalls) injected through transport wrappers.
* :mod:`~apex_tpu.fleet.supervise` — the rate-limited host supervisor the
  deploy bootstraps launch roles under.
"""

from apex_tpu.fleet.heartbeat import Heartbeat, HeartbeatEmitter
from apex_tpu.fleet.registry import (FleetRegistry, FleetStatusServer,
                                     format_fleet_table, status_request)

__all__ = ["Heartbeat", "HeartbeatEmitter", "FleetRegistry",
           "FleetStatusServer", "format_fleet_table", "status_request"]
