"""Heartbeat message + emitter for the fleet control plane.

Every role ships a periodic :class:`Heartbeat` on the stat channel it
already has — workers via the pool stat queue, socket roles via
``ChunkSender.send_stat`` (the adapters present both as one queue) — so
membership costs zero new sockets.  The learner-side
:class:`~apex_tpu.fleet.registry.FleetRegistry` turns the beat stream into
the JOINING → ALIVE → SUSPECT → DEAD machine and the ``fleet_*`` scalars.

Pure stdlib: the message crosses process boundaries (mp.Queue pickling and
the restricted ZMQ wire — this class is on the
:data:`apex_tpu.runtime.wire.ALLOWED_GLOBALS` allowlist), and worker
children import it before JAX initializes.
"""

from __future__ import annotations

import os
import socket
import time
from dataclasses import dataclass


@dataclass
class Heartbeat:
    """One liveness report.  ``rejoins``/``parked`` are self-reported park
    state (:mod:`apex_tpu.fleet.park`); counters are cumulative so the
    registry can difference them across beats."""

    identity: str                   # wire identity ("actor-3", "evaluator-…")
    role: str = "actor"
    pid: int = 0
    host: str = ""
    fps: float = 0.0                # env transitions/s over the beat window
    param_version: int = 0
    chunks_sent: int = 0
    acks_received: int = 0
    rejoins: int = 0                # park -> resume cycles this process
    parked: bool = False
    dropped_stats: int = 0          # same carry semantics as EpisodeStat
    # sender-window recovery accounting (PR 8): bounded sends retried on
    # credit exhaustion, and chunks rerouted to the learner-direct
    # fallback when the owning replay shard wedged.  Cumulative, like
    # chunks_sent/acks_received.
    resends: int = 0
    rerouted: int = 0
    # sender wall clock at beat creation (0.0 = unstamped): the learner's
    # registry differences it against its own wall clock into a per-peer
    # clock offset (skew + transit) — the alignment input for
    # ``python -m apex_tpu.obs.merge`` cross-host trace merging
    wall_ts: float = 0.0
    # role-specific serving gauges (plain str -> number dict, so the
    # restricted wire carries it): the infer server ships queue depth /
    # batch-size percentiles, remote-policy actors ship fallback counts
    # and round-trip percentiles — surfaced on the `--role status` table
    # and the Prometheus exposition.  None = role has nothing extra.
    gauges: dict | None = None


class HeartbeatEmitter:
    """Rate-limited beat factory for a worker/role loop.

    The loop calls :meth:`tick` per transition batch and
    :meth:`maybe_beat` once per iteration; a beat materializes at most
    every ``interval_s``.  ``counters_fn``/``park_fn`` are optional hooks
    into the transport layer (socket roles: the ChunkSender's wire
    counters, the ParkController's state) — in-host pools run without
    them and the emitter counts its own chunk puts.
    """

    def __init__(self, identity: str, role: str = "actor",
                 interval_s: float = 2.0, counters_fn=None, park_fn=None,
                 gauges_fn=None, clock=time.monotonic):
        self.identity = identity
        self.role = role
        self.interval_s = interval_s
        self.counters_fn = counters_fn
        self.park_fn = park_fn
        self.gauges_fn = gauges_fn
        self._clock = clock
        self._pid = os.getpid()
        self._host = socket.gethostname()
        self._last = clock()
        self._window_trans = 0
        self.chunks_sent = 0        # local count when counters_fn is None

    def tick(self, n: int = 1) -> None:
        self._window_trans += n

    def note_chunk(self) -> None:
        self.chunks_sent += 1

    def maybe_beat(self, param_version: int = 0) -> Heartbeat | None:
        now = self._clock()
        span = now - self._last
        if span < self.interval_s:
            return None
        self._last = now
        fps = self._window_trans / span if span > 0 else 0.0
        self._window_trans = 0
        counters = (self.counters_fn() if self.counters_fn is not None
                    else {"chunks_sent": self.chunks_sent,
                          "acks_received": 0})
        parked, rejoins = (self.park_fn() if self.park_fn is not None
                           else (False, 0))
        return Heartbeat(
            identity=self.identity, role=self.role, pid=self._pid,
            host=self._host, fps=round(fps, 1),
            param_version=int(param_version),
            chunks_sent=int(counters.get("chunks_sent", 0)),
            acks_received=int(counters.get("acks_received", 0)),
            rejoins=int(rejoins), parked=bool(parked),
            resends=int(counters.get("resends", 0)),
            rerouted=int(counters.get("rerouted", 0)),
            wall_ts=time.time(),
            gauges=(dict(self.gauges_fn())
                    if self.gauges_fn is not None else None))
