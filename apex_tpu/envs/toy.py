"""Numpy-native environments with the gymnasium API.

The reference assumes gym[atari]'s ALE emulator (``create_env.sh:5``,
``wrapper.py:257``).  This image has no ALE, and CI must never depend on it,
so the framework ships two self-contained numpy envs:

* :class:`CartPoleEnv` — the classic control task (Barto et al. dynamics),
  1-D observations, exercises the MLP trunk; learning curves are fast enough
  for CI learning tests.
* :class:`CatchEnv` — a pixel env (falling ball, movable paddle) rendered to
  84x84x1 uint8, exercising the full conv/WarpFrame/FrameStack path without
  an emulator.

Both are cheap enough that hundreds of actor processes can run per host.
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np


class CartPoleEnv(gym.Env):
    """Pole balancing; physics constants from the classic task definition."""

    metadata: dict = {}

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4

    def __init__(self, max_episode_steps: int = 500):
        self.observation_space = gym.spaces.Box(-np.inf, np.inf, (4,),
                                                np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._max_steps = max_episode_steps
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._state = self.np_random.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN

        cos, sin = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin) / total_mass
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos ** 2 /
                                  total_mass))
        x_acc = temp - pole_ml * theta_acc * cos / total_mass

        self._state = np.array([
            x + self.TAU * x_dot,
            x_dot + self.TAU * x_acc,
            theta + self.TAU * theta_dot,
            theta_dot + self.TAU * theta_acc,
        ])
        self._steps += 1

        terminated = bool(abs(self._state[0]) > self.X_LIMIT
                          or abs(self._state[2]) > self.THETA_LIMIT)
        truncated = self._steps >= self._max_steps
        return (self._state.astype(np.float32), 1.0, terminated, truncated, {})


class VelocityMask(gym.ObservationWrapper):
    """Hide CartPole's velocity components — the classic DRQN/partially-
    observable variant (Hausknecht & Stone 2015): the agent sees only
    ``(x, theta)`` and must infer velocities from history, which a
    feedforward Q-network cannot do and a recurrent one can.  This is the
    learning certificate env for the R2D2 family."""

    _KEEP = np.array([0, 2])

    def __init__(self, env: gym.Env):
        super().__init__(env)
        self.observation_space = gym.spaces.Box(-np.inf, np.inf, (2,),
                                                np.float32)

    def observation(self, obs):
        return np.asarray(obs, np.float32)[self._KEEP]


class ContinuousNavEnv(gym.Env):
    """Continuous-action navigation: drive a point to the origin.

    The CI-scale continuous-control task for AQL (the reference exercises
    AQL on gym Box-action tasks, ``model.py:174-176``).  Observation is the
    agent's position in ``[-2, 2]^dim``; the action is a velocity in
    ``[-1, 1]^dim`` scaled by 0.2; reward is ``-|position|_2`` per step, so
    an optimal policy proposes actions pointing at the origin and episode
    return climbs toward 0.  Episodes truncate at ``max_episode_steps``.
    """

    metadata: dict = {}

    def __init__(self, dim: int = 2, max_episode_steps: int = 30,
                 step_scale: float = 0.2):
        self.dim, self._max_steps, self._scale = dim, max_episode_steps, \
            step_scale
        self.observation_space = gym.spaces.Box(-2.0, 2.0, (dim,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (dim,), np.float32)
        self._pos = np.zeros(dim, np.float64)
        self._steps = 0

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._pos = self.np_random.uniform(-2.0, 2.0, size=self.dim)
        self._steps = 0
        return self._pos.astype(np.float32), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float64), -1.0, 1.0)
        self._pos = np.clip(self._pos + self._scale * a, -2.0, 2.0)
        self._steps += 1
        reward = -float(np.linalg.norm(self._pos))
        truncated = self._steps >= self._max_steps
        return self._pos.astype(np.float32), reward, False, truncated, {}


class RallyEnv(gym.Env):
    """Two-paddle rally against a scripted opponent — the Pong-shaped
    pixel task (ALE is absent from this image; ``origin_repo/create_env.sh:5``
    / ``wrapper.py:257`` assume it).  Unlike :class:`CatchEnv`'s drop-and-
    catch loop, this has OPPONENT DYNAMICS and long multi-rally credit
    horizons: points are scored tens of steps after the stroke that won
    them, and beating the opponent requires discovering the edge-shot
    mechanic rather than just tracking the ball.

    Court: ``grid x grid`` cells, rendered to ``pixels x pixels x 1``
    uint8.  The opponent guards column 0, the agent column ``grid-1``;
    actions 0=stay, 1=up, 2=down.  The ball advances one column per step;
    vertical speed is set by WHERE it strikes a paddle (center -> shallow,
    edge -> steep, the classic Pong deflection) and reflects off the
    walls.  The opponent tracks the incoming ball at speed 1 — it returns
    every shallow ball, but an edge hit sends the ball at |vy| = 1.75,
    which outruns it across the court: the agent must learn to RECEIVE
    anywhere and STRIKE with its paddle edge.  Reward +1 when the
    opponent misses, -1 when the agent does; an episode is ``points``
    points (eval metric = score differential, the reference's unclipped
    eval convention, ``origin_repo/eval.py:49-87``).
    """

    metadata: dict = {}

    MAX_VY = 1.75          # edge-hit deflection; outruns the speed-1 opponent
    MIN_VY = 0.5           # center hits stay live (no horizontal stalemates)

    def __init__(self, grid: int = 21, pixels: int = 84, points: int = 3,
                 paddle_half: int = 1, agent_half: int | None = None,
                 opp_speed: float = 1.0, dtype=np.float64):
        # ``agent_half`` widens ONLY the agent's paddle (easier receiving
        # without making the opponent harder to score past) and
        # ``opp_speed`` caps the opponent's per-step tracking — the two
        # difficulty knobs the Small certificate variant uses; the full
        # variant keeps the symmetric speed-1 game
        self.grid, self.pixels, self.points = grid, pixels, points
        self.half = paddle_half
        self.agent_half = self.half if agent_half is None else agent_half
        self.opp_speed = opp_speed
        # Continuous-state compute dtype.  float64 (the python-float
        # default) is bit-identical to the pre-knob behavior; float32
        # makes every op the same correctly-rounded IEEE-f32 op the
        # jittable port (envs/jax_envs.py) runs, so the exact-trajectory
        # parity pin can compare like with like — the deflection lattice
        # is non-dyadic (7/12ths), so f64 and f32 trajectories disagree
        # at round()-to-pixel boundaries after a few paddle contacts.
        self._scalar = np.dtype(dtype).type
        self.observation_space = gym.spaces.Box(0, 255, (pixels, pixels, 1),
                                                np.uint8)
        self.action_space = gym.spaces.Discrete(3)
        self._scale = pixels // grid

    # -- mechanics ---------------------------------------------------------

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._agent_y = self._opp_y = self._scalar((self.grid - 1) / 2)
        self._played = 0
        self._serve(toward_agent=bool(self.np_random.random() < 0.5))
        return self._render(), {}

    def _serve(self, toward_agent: bool) -> None:
        self._bx = self._scalar((self.grid - 1) / 2)
        self._by = self._scalar(self.np_random.integers(2, self.grid - 2))
        self._vx = 1 if toward_agent else -1
        self._vy = self._scalar(self.np_random.choice([-1.0, -0.5, 0.5, 1.0]))

    def _deflect(self, offset: float) -> float:
        """Paddle-contact vertical speed from the normalized hit offset
        (center 0 -> shallow, edge +-1 -> MAX_VY steep)."""
        vy = self.MAX_VY * offset
        if abs(vy) < self.MIN_VY:
            sign = 1.0 if self.np_random.random() < 0.5 else -1.0
            vy = self.MIN_VY * sign
        return self._scalar(np.clip(vy, -self.MAX_VY, self.MAX_VY))

    def step(self, action):
        g, half, ahalf = self.grid, self.half, self.agent_half
        # agent paddle
        self._agent_y = self._scalar(np.clip(
            self._agent_y + (0, -1, 1)[int(action)], ahalf, g - 1 - ahalf))
        # scripted opponent: track the ball at ALL times (a re-centering
        # opponent loses to plain tracking — measured; this one only
        # loses to deliberately generated steep angles, or — at reduced
        # opp_speed — to sustained accurate returns)
        self._opp_y = self._scalar(np.clip(
            self._opp_y + np.clip(self._by - self._opp_y,
                                  -self.opp_speed, self.opp_speed),
            half, g - 1 - half))
        # ball advance + wall reflection
        self._bx += self._vx
        self._by += self._vy
        while self._by < 0 or self._by > g - 1:
            if self._by < 0:
                self._by = -self._by
            else:
                self._by = 2 * (g - 1) - self._by
            self._vy = -self._vy

        reward = 0.0
        if self._bx <= 0:                       # opponent's goal column
            if abs(self._by - self._opp_y) <= half + 0.5:
                self._bx, self._vx = self._scalar(0.0), 1
                self._vy = self._deflect(
                    (self._by - self._opp_y) / (half + 0.5))
            else:
                reward = 1.0
                self._played += 1
                self._serve(toward_agent=False)
        elif self._bx >= g - 1:                 # agent's goal column
            if abs(self._by - self._agent_y) <= ahalf + 0.5:
                self._bx, self._vx = self._scalar(g - 1), -1
                self._vy = self._deflect(
                    (self._by - self._agent_y) / (ahalf + 0.5))
            else:
                reward = -1.0
                self._played += 1
                self._serve(toward_agent=True)
        terminated = self._played >= self.points
        return self._render(), reward, terminated, False, {}

    # -- rendering ---------------------------------------------------------

    def _block(self, img, row: float, col: int, h: int, value: int) -> None:
        s = self._scale
        r0 = int(np.clip(round(row) - h, 0, self.grid - 1)) * s
        r1 = (int(np.clip(round(row) + h, 0, self.grid - 1)) + 1) * s
        img[r0:r1, col * s:(col + 1) * s] = value

    def _render(self) -> np.ndarray:
        img = np.zeros((self.pixels, self.pixels, 1), np.uint8)
        self._block(img, self._opp_y, 0, self.half, 128)
        self._block(img, self._agent_y, self.grid - 1, self.agent_half, 128)
        bx = int(np.clip(round(self._bx), 0, self.grid - 1))
        self._block(img, self._by, bx, 0, 255)
        return img


class CatchEnv(gym.Env):
    """Catch a falling ball with a paddle; pixel observations.

    Internal grid is ``grid x grid``; observations are rendered to
    ``pixels x pixels x 1`` uint8 (default 84, matching WarpFrame geometry).
    Reward +1 for a catch, -1 for a miss; an episode is ``balls`` drops.
    Actions: 0=stay, 1=left, 2=right.
    """

    metadata: dict = {}

    def __init__(self, grid: int = 21, pixels: int = 84, balls: int = 5):
        self.grid, self.pixels, self.balls = grid, pixels, balls
        self.observation_space = gym.spaces.Box(0, 255, (pixels, pixels, 1),
                                                np.uint8)
        self.action_space = gym.spaces.Discrete(3)
        self._scale = pixels // grid

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self._paddle = self.grid // 2
        self._drop()
        self._remaining = self.balls
        return self._render(), {}

    def _drop(self):
        self._ball_x = int(self.np_random.integers(0, self.grid))
        self._ball_y = 0

    def step(self, action):
        self._paddle = int(np.clip(self._paddle + (0, -1, 1)[int(action)],
                                   0, self.grid - 1))
        self._ball_y += 1
        reward, terminated = 0.0, False
        if self._ball_y == self.grid - 1:
            reward = 1.0 if abs(self._ball_x - self._paddle) <= 1 else -1.0
            self._remaining -= 1
            if self._remaining == 0:
                terminated = True
            else:
                self._drop()
        return self._render(), reward, terminated, False, {}

    def _render(self) -> np.ndarray:
        s = self._scale
        img = np.zeros((self.pixels, self.pixels, 1), np.uint8)
        by, bx = self._ball_y * s, self._ball_x * s
        img[by:by + s, bx:bx + s] = 255
        py = (self.grid - 1) * s
        p0 = max(self._paddle - 1, 0) * s
        p1 = (min(self._paddle + 1, self.grid - 1) + 1) * s
        img[py:py + s, p0:p1] = 128
        return img
