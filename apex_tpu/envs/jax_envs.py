"""Jittable functional ports of the toy pixel envs (Anakin substrate).

``envs/toy.py`` holds the numpy gymnasium envs the host actor fleet steps
one python call at a time.  Catch and Rally are integer/float32 grid worlds
with no emulator dependency, so they can run INSIDE the accelerator: this
module re-expresses them as pure functions

    reset(key)               -> (state, obs)
    step(state, action, key) -> (state, obs, reward, done, final_frame)

over small array states, vmappable across env batches and scannable with
``lax.scan`` (the co-located batched-simulation economics of Accelerated
Methods, arxiv 1803.02811, and the Anakin/commodity-hardware setups of
arxiv 2111.01264).  ``apex_tpu/training/anakin.py`` fuses them with the
epsilon-greedy policy and on-device chunk assembly into one scanned
rollout program.

Parity contract (pinned in tests/test_jax_envs.py): stepped under the SAME
seeds and actions, a port's trajectory — rendered uint8 observations,
rewards, terminations — is IDENTICAL to the numpy env's.  Randomness is
the one seam: the numpy envs draw from gymnasium's PCG64 stream while the
ports draw with ``jax.random`` — so every draw site here has a FIXED
fold-in tag (the ``_T_*`` constants), and the parity tests drive the numpy
env through a keyed ``np_random`` shim that replays the same
``fold_in(key, tag)`` draws.  Because keyed draws are stateless, unused
draws cost nothing and can never desync the two sides.

Auto-reset lives INSIDE ``step`` (a scanned rollout cannot stop to call
``reset``): on ``done`` the returned ``obs`` is the NEXT episode's reset
observation while ``final_frame`` is the terminal render — exactly the two
frames the host loop hands ``FrameChunkBuilder.add_step`` /
``begin_episode``.  On ordinary steps ``final_frame is obs``.

Catch dynamics are pure integers => bitwise parity over full trajectories.
Rally computes in float32 where the numpy env uses float64; every op is
the same correctly-rounded IEEE elementary op, and the parity test pins a
fixed-seed trajectory exactly (the dynamics lattice keeps f32 and f64
agreeing on every discrete observable over the pinned horizon).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

# -- draw-site tags ----------------------------------------------------------
# Step-scope draws fold these onto the per-step env key; reset-scope draws
# (initial reset AND in-step auto-reset) use the _T_RESET_* tags, so a
# terminal step's dead serve draws and its auto-reset draws can never
# collide.  tests/test_jax_envs.py's KeyedNpRandom shim replays the same
# (key, tag) -> value mapping into the numpy envs.
_T_COIN = 0          # in-step coin (Rally deflect sign)
_T_INT = 1           # in-step integer draw (Catch drop column, Rally serve row)
_T_CHOICE = 2        # in-step choice draw (Rally serve vy)
_T_RESET_COIN = 3    # reset-scope coin (Rally serve direction)
_T_RESET_INT = 4     # reset-scope integer draw
_T_RESET_CHOICE = 5  # reset-scope choice draw


def _coin(key, tag: int):
    """random() < 0.5, keyed."""
    return jax.random.uniform(jax.random.fold_in(key, tag)) < 0.5


def _randint(key, tag: int, low: int, high: int):
    return jax.random.randint(jax.random.fold_in(key, tag), (), low, high)


class JaxEnv(NamedTuple):
    """One jittable env: pure reset/step plus the spec the chunk plane
    needs.  ``step`` returns ``(state, obs, reward, done, final_frame)``
    with auto-reset folded in (module docstring)."""

    reset: Callable[..., Any]
    step: Callable[..., Any]
    frame_shape: tuple[int, ...]
    num_actions: int
    env_id: str


# -- Catch -------------------------------------------------------------------


class CatchState(NamedTuple):
    paddle: jax.Array       # i32
    ball_x: jax.Array       # i32
    ball_y: jax.Array       # i32
    remaining: jax.Array    # i32


@dataclass(frozen=True)
class CatchParams:
    """Twin of :class:`apex_tpu.envs.toy.CatchEnv`'s constructor knobs."""

    grid: int = 21
    pixels: int = 84
    balls: int = 5

    @property
    def scale(self) -> int:
        return self.pixels // self.grid


def _catch_render(p: CatchParams, state: CatchState) -> jax.Array:
    """Bitwise port of ``CatchEnv._render``: ball block at (ball_y,
    ball_x), 3-cell paddle row at the bottom drawn AFTER the ball (the
    paddle overwrites where they overlap)."""
    s = p.scale
    rows = jnp.arange(p.pixels, dtype=jnp.int32)[:, None]
    cols = jnp.arange(p.pixels, dtype=jnp.int32)[None, :]
    by, bx = state.ball_y * s, state.ball_x * s
    ball = ((rows >= by) & (rows < by + s)
            & (cols >= bx) & (cols < bx + s))
    py = (p.grid - 1) * s
    p0 = jnp.maximum(state.paddle - 1, 0) * s
    p1 = (jnp.minimum(state.paddle + 1, p.grid - 1) + 1) * s
    pad = (rows >= py) & (rows < py + s) & (cols >= p0) & (cols < p1)
    img = jnp.where(ball, jnp.uint8(255), jnp.uint8(0))
    img = jnp.where(pad, jnp.uint8(128), img)
    return img[:, :, None]


def make_catch(grid: int = 21, pixels: int = 84, balls: int = 5,
               env_id: str = "ApexCatch-v0") -> JaxEnv:
    p = CatchParams(grid=grid, pixels=pixels, balls=balls)

    def reset(key) -> tuple[CatchState, jax.Array]:
        state = CatchState(
            paddle=jnp.int32(p.grid // 2),
            ball_x=_randint(key, _T_RESET_INT, 0, p.grid),
            ball_y=jnp.int32(0),
            remaining=jnp.int32(p.balls))
        return state, _catch_render(p, state)

    def step(state: CatchState, action, key):
        move = jnp.asarray([0, -1, 1], jnp.int32)[action]
        paddle = jnp.clip(state.paddle + move, 0, p.grid - 1)
        ball_y = state.ball_y + 1
        landed = ball_y == p.grid - 1
        caught = jnp.abs(state.ball_x - paddle) <= 1
        reward = jnp.where(
            landed, jnp.where(caught, jnp.float32(1.0), jnp.float32(-1.0)),
            jnp.float32(0.0))
        remaining = state.remaining - landed.astype(jnp.int32)
        done = landed & (remaining == 0)
        # drop within the episode (landed, balls left): new column from the
        # in-step tag — the terminal render keeps the OLD ball position
        drop = landed & ~done
        mid = CatchState(
            paddle=paddle,
            ball_x=jnp.where(drop, _randint(key, _T_INT, 0, p.grid),
                             state.ball_x),
            ball_y=jnp.where(drop, jnp.int32(0), ball_y),
            remaining=remaining)
        final_frame = _catch_render(p, mid)
        # auto-reset (reset-scope tags, same key — mirrors the host driver
        # calling env.reset() right after the terminal step)
        fresh = CatchState(
            paddle=jnp.int32(p.grid // 2),
            # apexlint: disable=J004 -- every draw site folds a DISTINCT _T_* tag onto the step key (module docstring): tagged fold_in IS the fresh-subkey discipline here
            ball_x=_randint(key, _T_RESET_INT, 0, p.grid),
            ball_y=jnp.int32(0),
            remaining=jnp.int32(p.balls))
        out = jax.tree.map(lambda a, b: jnp.where(done, a, b), fresh, mid)
        obs = jnp.where(done, _catch_render(p, fresh), final_frame)
        return out, obs, reward, done, final_frame

    return JaxEnv(reset=reset, step=step,
                  frame_shape=(pixels, pixels, 1), num_actions=3,
                  env_id=env_id)


# -- Rally -------------------------------------------------------------------


class RallyState(NamedTuple):
    agent_y: jax.Array      # f32
    opp_y: jax.Array        # f32
    bx: jax.Array           # f32 (half-integer courts exist: grid=14)
    by: jax.Array           # f32
    vx: jax.Array           # i32 (+1 toward agent)
    vy: jax.Array           # f32
    played: jax.Array       # i32


@dataclass(frozen=True)
class RallyParams:
    grid: int = 21
    pixels: int = 84
    points: int = 3
    paddle_half: int = 1
    agent_half: int | None = None
    opp_speed: float = 1.0

    # derived, matching toy.RallyEnv.__init__
    a_half: int = field(init=False)

    def __post_init__(self) -> None:
        object.__setattr__(self, "a_half",
                           self.paddle_half if self.agent_half is None
                           else self.agent_half)

    @property
    def scale(self) -> int:
        return self.pixels // self.grid


_MAX_VY = 1.75
_MIN_VY = 0.5


def _rally_serve(p: RallyParams, key, toward_agent, reset_scope: bool):
    """(bx, by, vx, vy) of a fresh serve — ``toy.RallyEnv._serve``."""
    t_int = _T_RESET_INT if reset_scope else _T_INT
    t_choice = _T_RESET_CHOICE if reset_scope else _T_CHOICE
    # apexlint: disable=J004 -- distinct fold-in tags per draw site (module docstring), not key reuse
    by = _randint(key, t_int, 2, p.grid - 2).astype(jnp.float32)
    vy = jnp.asarray([-1.0, -0.5, 0.5, 1.0], jnp.float32)[
        # apexlint: disable=J004 -- distinct fold-in tags per draw site (module docstring), not key reuse
        _randint(key, t_choice, 0, 4)]
    return (jnp.float32((p.grid - 1) / 2), by,
            jnp.where(toward_agent, jnp.int32(1), jnp.int32(-1)), vy)


def _rally_reset_state(p: RallyParams, key) -> RallyState:
    mid = jnp.float32((p.grid - 1) / 2)
    # apexlint: disable=J004 -- distinct fold-in tags per draw site (module docstring), not key reuse
    bx, by, vx, vy = _rally_serve(p, key, _coin(key, _T_RESET_COIN),
                                  reset_scope=True)
    return RallyState(agent_y=mid, opp_y=mid, bx=bx, by=by, vx=vx, vy=vy,
                      played=jnp.int32(0))


def _rally_deflect(key, offset):
    """``toy.RallyEnv._deflect``: center -> shallow, edge -> steep, with
    the coin-flipped minimum-speed floor."""
    vy = jnp.float32(_MAX_VY) * offset
    sign = jnp.where(_coin(key, _T_COIN), jnp.float32(1.0),
                     jnp.float32(-1.0))
    vy = jnp.where(jnp.abs(vy) < _MIN_VY, jnp.float32(_MIN_VY) * sign, vy)
    return jnp.clip(vy, -_MAX_VY, _MAX_VY)


def _rally_render(p: RallyParams, state: RallyState) -> jax.Array:
    """Bitwise port of ``toy.RallyEnv._render`` (opponent, agent, then the
    ball — later draws overwrite)."""
    s = p.scale
    rows = jnp.arange(p.pixels, dtype=jnp.int32)[:, None]
    cols = jnp.arange(p.pixels, dtype=jnp.int32)[None, :]

    def block(row, col, h):
        r = jnp.round(row).astype(jnp.int32)
        r0 = jnp.clip(r - h, 0, p.grid - 1) * s
        r1 = (jnp.clip(r + h, 0, p.grid - 1) + 1) * s
        return ((rows >= r0) & (rows < r1)
                & (cols >= col * s) & (cols < (col + 1) * s))

    bx = jnp.clip(jnp.round(state.bx).astype(jnp.int32), 0, p.grid - 1)
    img = jnp.where(block(state.opp_y, jnp.int32(0), p.paddle_half),
                    jnp.uint8(128), jnp.uint8(0))
    img = jnp.where(block(state.agent_y, jnp.int32(p.grid - 1), p.a_half),
                    jnp.uint8(128), img)
    img = jnp.where(block(state.by, bx, 0), jnp.uint8(255), img)
    return img[:, :, None]


def make_rally(grid: int = 21, pixels: int = 84, points: int = 3,
               paddle_half: int = 1, agent_half: int | None = None,
               opp_speed: float = 1.0,
               env_id: str = "ApexRally-v0") -> JaxEnv:
    p = RallyParams(grid=grid, pixels=pixels, points=points,
                    paddle_half=paddle_half, agent_half=agent_half,
                    opp_speed=opp_speed)
    g, half, ahalf = p.grid, p.paddle_half, p.a_half
    speed = jnp.float32(p.opp_speed)

    def reset(key) -> tuple[RallyState, jax.Array]:
        state = _rally_reset_state(p, key)
        return state, _rally_render(p, state)

    def step(state: RallyState, action, key):
        move = jnp.asarray([0.0, -1.0, 1.0], jnp.float32)[action]
        agent_y = jnp.clip(state.agent_y + move, jnp.float32(ahalf),
                           jnp.float32(g - 1 - ahalf))
        opp_y = jnp.clip(
            state.opp_y + jnp.clip(state.by - state.opp_y, -speed, speed),
            jnp.float32(half), jnp.float32(g - 1 - half))
        bx = state.bx + state.vx.astype(jnp.float32)
        by = state.by + state.vy
        # wall reflection (|vy| <= 1.75 < g-1 => at most one bounce)
        hit_low, hit_high = by < 0, by > g - 1
        by = jnp.where(hit_low, -by, jnp.where(hit_high, 2 * (g - 1) - by,
                                               by))
        vy = jnp.where(hit_low | hit_high, -state.vy, state.vy)

        at_opp = bx <= 0
        at_agent = bx >= g - 1
        opp_saves = jnp.abs(by - opp_y) <= half + 0.5
        agent_saves = jnp.abs(by - agent_y) <= ahalf + 0.5
        opp_deflect = at_opp & opp_saves
        agent_deflect = at_agent & agent_saves
        agent_scores = at_opp & ~opp_saves
        opp_scores = at_agent & ~agent_saves
        scored = agent_scores | opp_scores

        reward = jnp.where(agent_scores, jnp.float32(1.0),
                           jnp.where(opp_scores, jnp.float32(-1.0),
                                     jnp.float32(0.0)))
        # deflections: position snaps to the goal column, vy from the
        # normalized hit offset (the one per-step paddle contact)
        off = jnp.where(opp_deflect, (by - opp_y) / jnp.float32(half + 0.5),
                        (by - agent_y) / jnp.float32(ahalf + 0.5))
        dvy = _rally_deflect(key, off)
        any_deflect = opp_deflect | agent_deflect
        bx = jnp.where(opp_deflect, jnp.float32(0.0),
                       jnp.where(agent_deflect, jnp.float32(g - 1), bx))
        vx = jnp.where(opp_deflect, jnp.int32(1),
                       jnp.where(agent_deflect, jnp.int32(-1), state.vx))
        vy = jnp.where(any_deflect, dvy, vy)
        # serve after a point (toward the side that conceded)
        # apexlint: disable=J004 -- distinct fold-in tags per draw site (module docstring), not key reuse
        sbx, sby, svx, svy = _rally_serve(p, key, opp_scores,
                                          reset_scope=False)
        bx = jnp.where(scored, sbx, bx)
        by = jnp.where(scored, sby, by)
        vx = jnp.where(scored, svx, vx)
        vy = jnp.where(scored, svy, vy)
        played = state.played + scored.astype(jnp.int32)
        done = played >= p.points

        mid = RallyState(agent_y=agent_y, opp_y=opp_y, bx=bx, by=by,
                         vx=vx, vy=vy, played=played)
        final_frame = _rally_render(p, mid)
        # apexlint: disable=J004 -- auto-reset draws use the _T_RESET_* tag family, disjoint from the in-step tags above
        fresh = _rally_reset_state(p, key)
        out = jax.tree.map(lambda a, b: jnp.where(done, a, b), fresh, mid)
        obs = jnp.where(done, _rally_render(p, fresh), final_frame)
        return out, obs, reward, done, final_frame

    return JaxEnv(reset=reset, step=step,
                  frame_shape=(pixels, pixels, 1), num_actions=3,
                  env_id=env_id)
