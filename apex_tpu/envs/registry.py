"""Env construction: the reference's two composers re-expressed.

``make_atari(env_id)`` (``origin_repo/wrapper.py:255-262``) and
``wrap_atari_dqn(env, args)`` (``wrapper.py:316-329``) become one
``make_env(env_id, cfg)`` that dispatches on the id:

* ``Apex*`` ids -> numpy-native envs (no emulator needed; see
  :mod:`apex_tpu.envs.toy`).  Pixel envs still get FrameStack so the
  observation contract matches Atari exactly.
* ``*NoFrameskip*`` ids -> the full DeepMind wrapper stack; requires
  ``ale_py``, which this image does not ship — gated with a clear error.
"""

from __future__ import annotations

from typing import Any

import gymnasium as gym

from apex_tpu.config import EnvConfig
from apex_tpu.envs import toy, wrappers


def _ale_available() -> bool:
    try:
        import ale_py  # noqa: F401
        return True
    except ImportError:
        return False


def make_atari(env_id: str, skip: int = 4,
               max_episode_steps: int | None = None) -> gym.Env:
    """Base Atari env + Noop + MaxAndSkip (reference: wrapper.py:255-262)."""
    if not _ale_available():
        raise ImportError(
            "ale_py is not installed; Atari envs are unavailable in this "
            "image. Use 'ApexCartPole-v0' or 'ApexCatch-v0' instead.")
    import ale_py
    gym.register_envs(ale_py)
    env = gym.make(env_id)
    env = wrappers.NoopResetEnv(env, noop_max=30)
    env = wrappers.MaxAndSkipEnv(env, skip=skip)
    if max_episode_steps is not None:
        env = wrappers.TimeLimit(env, max_episode_steps)
    return env


def wrap_atari_dqn(env: gym.Env, cfg: EnvConfig,
                   stack_frames: bool = True) -> gym.Env:
    """DeepMind preprocessing stack (reference: wrapper.py:316-329)."""
    if cfg.episodic_life:
        env = wrappers.EpisodicLifeEnv(env)
    if "FIRE" in env.unwrapped.get_action_meanings():
        env = wrappers.FireResetEnv(env)
    env = wrappers.WarpFrame(env)
    if cfg.clip_rewards:
        env = wrappers.ClipRewardEnv(env)
    if stack_frames and cfg.frame_stack > 1:
        env = wrappers.FrameStack(env, cfg.frame_stack)
    return env


def make_env(env_id: str | None = None, cfg: EnvConfig | None = None,
             seed: int | None = None,
             max_episode_steps: int | None = None,
             stack_frames: bool = True) -> gym.Env:
    """One-stop constructor used by every role (actor/evaluator/driver).

    ``stack_frames=False`` omits the FrameStack wrapper: actors feeding the
    frame-pool replay consume SINGLE frames (stacking happens on device at
    sample time; the acting stack lives in FrameChunkBuilder).
    """
    cfg = cfg or EnvConfig()
    env_id = env_id or cfg.env_id

    if env_id.startswith("ApexCartPole"):
        env = (toy.CartPoleEnv(max_episode_steps=max_episode_steps)
               if max_episode_steps is not None else toy.CartPoleEnv())
        if "PO" in env_id:      # ApexCartPolePO-v0: velocities hidden
            env = toy.VelocityMask(env)
    elif env_id.startswith("ApexContinuousNav"):
        env = (toy.ContinuousNavEnv(max_episode_steps=max_episode_steps)
               if max_episode_steps is not None else toy.ContinuousNavEnv())
    elif env_id.startswith(("ApexCatch", "ApexRally")):
        # Pixel toy envs.  Catch — Small: 7x7 grid rendered to 42x42
        # (smallest input the Nature conv geometry accepts), 3 balls (a
        # 6-step credit horizon); Medium: 11x11 at 44x44, 4 balls (a
        # 10-step horizon, the harder pixel certificate standing in for
        # ALE, absent from this image; ROUND4_NOTES.md).  Rally — the
        # Pong-shaped ADVERSARIAL task (scripted opponent, edge-shot
        # mechanic — toy.RallyEnv); Small: 14-cell court at 42x42, 2
        # points (the CI-scale certificate); full: 21 at 84x84, 3 points
        # (the flagship-geometry stand-in for ALE Pong).
        if env_id.startswith("ApexCatch"):
            if "Small" in env_id:
                env = toy.CatchEnv(grid=7, pixels=42, balls=3)
            elif "Medium" in env_id:
                env = toy.CatchEnv(grid=11, pixels=44, balls=4)
            else:
                env = toy.CatchEnv()
        else:
            # Small: wide agent paddle + 0.45-speed opponent — the two
            # levers calibration showed matter for a CI-budget DQN
            # (reward density from reliable catches; a grid-10 big-ball
            # variant measured WORSE).  Ladder: random -0.68 / tracking
            # +1.65 / edge +2.0.  The full variant keeps the symmetric
            # speed-1 duel (ladder measured on the same 14-cell 2-point
            # court WITHOUT the Small handicaps: random -1.45 / tracking
            # +0.57 / edge +2.0 — the 21-cell 3-point full env scales
            # these, it has not been separately calibrated).
            env = (toy.RallyEnv(grid=14, pixels=42, points=2,
                                agent_half=2, opp_speed=0.45)
                   if "Small" in env_id else toy.RallyEnv())
        # ONE copy of the pixel wrapper tail for every toy pixel env
        if max_episode_steps is not None:
            env = wrappers.TimeLimit(env, max_episode_steps)
        if stack_frames and cfg.frame_stack > 1:
            env = wrappers.FrameStack(env, cfg.frame_stack)
    else:
        env = make_atari(env_id, skip=cfg.frame_skip,
                         max_episode_steps=max_episode_steps)
        env = wrap_atari_dqn(env, cfg, stack_frames=stack_frames)

    if seed is not None:
        env.reset(seed=seed)
        env.action_space.seed(seed)
    return env


def jittable_env(env_id: str) -> bool:
    """Capability flag: True when :func:`make_jax_env` can build a pure-JAX
    port of ``env_id`` for on-device Anakin rollouts
    (:mod:`apex_tpu.training.anakin`).  Catch/Rally are integer/float32
    grid worlds that run inside the accelerator; everything else (ALE,
    CartPole-family float dynamics, continuous nav) stays on the host
    pipeline."""
    return env_id.startswith(("ApexCatch", "ApexRally"))


def make_jax_env(env_id: str | None = None, cfg: EnvConfig | None = None):
    """Jittable functional twin of :func:`make_env` for the on-device
    rollout engine — same env-id -> variant-geometry table as the numpy
    dispatch above, returning an :class:`apex_tpu.envs.jax_envs.JaxEnv`
    (pure reset/step over array states, auto-reset inside step).  Raises
    ``ValueError`` naming the env id for non-jittable envs — the
    ``--rollout ondevice`` / ``--role loadgen`` guard."""
    from apex_tpu.envs import jax_envs

    cfg = cfg or EnvConfig()
    env_id = env_id or cfg.env_id
    if not jittable_env(env_id):
        raise ValueError(
            f"env {env_id!r} has no jittable port — on-device rollouts "
            f"(--rollout ondevice / --role loadgen) serve the "
            f"ApexCatch*/ApexRally* families only; use the host actor "
            f"pipeline for this env")
    if env_id.startswith("ApexCatch"):
        if "Small" in env_id:
            return jax_envs.make_catch(grid=7, pixels=42, balls=3,
                                       env_id=env_id)
        if "Medium" in env_id:
            return jax_envs.make_catch(grid=11, pixels=44, balls=4,
                                       env_id=env_id)
        return jax_envs.make_catch(env_id=env_id)
    if "Small" in env_id:
        return jax_envs.make_rally(grid=14, pixels=42, points=2,
                                   agent_half=2, opp_speed=0.45,
                                   env_id=env_id)
    return jax_envs.make_rally(env_id=env_id)


def unstacked_env_spec(env: gym.Env,
                       cfg: EnvConfig) -> tuple[tuple[int, ...], Any, int]:
    """(frame_shape, frame_dtype, frame_stack) for an env built with
    ``stack_frames=False`` — the FrameChunkBuilder/FramePoolReplay spec.
    Vector (1-D) observations use frame_stack=1."""
    space = env.observation_space
    shape = tuple(space.shape)
    stack = cfg.frame_stack if len(shape) == 3 else 1
    return shape, space.dtype, stack


def make_eval_env(env_id: str | None = None, cfg: EnvConfig | None = None,
                  seed: int | None = None) -> gym.Env:
    """Evaluation env: UNCLIPPED rewards, full episodes (no EpisodicLife) —
    the reference evaluator measures true game score this way
    (``origin_repo/eval.py:52``)."""
    import dataclasses
    cfg = cfg or EnvConfig()
    eval_cfg = dataclasses.replace(cfg, clip_rewards=False,
                                   episodic_life=False)
    return make_env(env_id, eval_cfg, seed=seed)


def obs_spec(env: gym.Env) -> tuple[tuple[int, ...], Any]:
    space = env.observation_space
    return tuple(space.shape), space.dtype


def num_actions(env: gym.Env) -> int:
    return int(env.action_space.n)
