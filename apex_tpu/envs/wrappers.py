"""DeepMind-style Atari preprocessing wrappers on the gymnasium API.

Capability parity with the reference's wrapper stack
(``origin_repo/wrapper.py``): NoopReset(<=30) (``wrapper.py:11-38``),
FireReset (``:41-59``), EpisodicLife (``:62-96``), MaxAndSkip(4) with 2-frame
max-pool (``:99-127``), sign reward clipping (``:130-136``), WarpFrame 84x84
grayscale (``:139-157``), FrameStack with memory-deduping LazyFrames
(``:160-252``), TimeLimit (``:282-298``).

Deliberate TPU-first deltas:

* **gymnasium (terminated/truncated) API** rather than legacy gym.
* **NHWC channel-LAST stacking** — the reference permutes to channel-first for
  torch (``wrapper.py:301-313``); XLA:TPU convs are NHWC-native so there is no
  permute wrapper at all.
* **uint8 end-to-end** — no ScaledFloatFrame (``wrapper.py:207-215``); scaling
  happens inside the compiled model graph, keeping wire/replay traffic 4x
  smaller.
"""

from __future__ import annotations

from collections import deque

import gymnasium as gym
import numpy as np

try:
    import cv2
    cv2.ocl.setUseOpenCL(False)
except Exception:  # pragma: no cover - cv2 is present in the target image
    cv2 = None


class NoopResetEnv(gym.Wrapper):
    """Random number of no-ops at reset (reference: wrapper.py:11-38)."""

    def __init__(self, env, noop_max: int = 30):
        super().__init__(env)
        self.noop_max = noop_max
        assert env.unwrapped.get_action_meanings()[0] == "NOOP"

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        noops = self.np_random.integers(1, self.noop_max + 1)
        for _ in range(noops):
            obs, _, terminated, truncated, info = self.env.step(0)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        return obs, info


class FireResetEnv(gym.Wrapper):
    """Press FIRE after reset for envs that need it (reference: wrapper.py:41-59)."""

    def __init__(self, env):
        super().__init__(env)
        meanings = env.unwrapped.get_action_meanings()
        assert meanings[1] == "FIRE" and len(meanings) >= 3

    def reset(self, **kwargs):
        self.env.reset(**kwargs)
        obs, _, terminated, truncated, _ = self.env.step(1)
        if terminated or truncated:
            self.env.reset(**kwargs)
        obs, _, terminated, truncated, info = self.env.step(2)
        if terminated or truncated:
            obs, info = self.env.reset(**kwargs)
        return obs, info


class EpisodicLifeEnv(gym.Wrapper):
    """End episodes on life loss, only truly reset on game over
    (reference: wrapper.py:62-96)."""

    def __init__(self, env):
        super().__init__(env)
        self.lives = 0
        self.was_real_done = True

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self.was_real_done = terminated or truncated
        lives = self.env.unwrapped.ale.lives()
        if 0 < lives < self.lives:
            terminated = True
        self.lives = lives
        return obs, reward, terminated, truncated, info

    def reset(self, **kwargs):
        if self.was_real_done:
            obs, info = self.env.reset(**kwargs)
        else:
            obs, _, _, _, info = self.env.step(0)
        self.lives = self.env.unwrapped.ale.lives()
        return obs, info


class MaxAndSkipEnv(gym.Wrapper):
    """Repeat action ``skip`` times, max-pool the last two raw frames
    (reference: wrapper.py:99-127)."""

    def __init__(self, env, skip: int = 4):
        super().__init__(env)
        self._obs_buffer = np.zeros((2,) + env.observation_space.shape,
                                    dtype=np.uint8)
        self._skip = skip

    def step(self, action):
        total_reward, terminated, truncated, info = 0.0, False, False, {}
        for i in range(self._skip):
            obs, reward, terminated, truncated, info = self.env.step(action)
            if i == self._skip - 2:
                self._obs_buffer[0] = obs
            if i == self._skip - 1:
                self._obs_buffer[1] = obs
            total_reward += float(reward)
            if terminated or truncated:
                break
        return (self._obs_buffer.max(axis=0), total_reward, terminated,
                truncated, info)

    def reset(self, **kwargs):
        return self.env.reset(**kwargs)


class ClipRewardEnv(gym.RewardWrapper):
    """Sign-clip rewards (reference: wrapper.py:130-136)."""

    def reward(self, reward):
        return float(np.sign(reward))


class WarpFrame(gym.ObservationWrapper):
    """Grayscale + resize to 84x84 (reference: wrapper.py:139-157).
    Emits (84, 84, 1) uint8 — channel-last, see module docstring."""

    def __init__(self, env, width: int = 84, height: int = 84):
        super().__init__(env)
        if cv2 is None:
            raise ImportError(
                "WarpFrame requires opencv-python (cv2) for grayscale/resize")
        self.width, self.height = width, height
        self.observation_space = gym.spaces.Box(
            0, 255, (height, width, 1), np.uint8)

    def observation(self, frame):
        if frame.ndim == 3 and frame.shape[-1] == 3:
            frame = cv2.cvtColor(frame, cv2.COLOR_RGB2GRAY)
        frame = cv2.resize(frame, (self.width, self.height),
                           interpolation=cv2.INTER_AREA)
        return frame[:, :, None].astype(np.uint8)


class LazyFrames:
    """Stacked-observation view sharing the underlying frame buffers.

    Same memory-dedup trick as the reference (``wrapper.py:218-252``): n-step
    neighbors share ``stack-1`` frames, so materializing the stack only at
    batch-encode time cuts replay RAM by ~stack x.  Concatenates along the
    LAST axis (NHWC) where the reference used the first.
    """

    __slots__ = ("_frames", "_out")

    def __init__(self, frames: list[np.ndarray]):
        self._frames = frames
        self._out = None

    def _force(self) -> np.ndarray:
        if self._out is None:
            self._out = np.concatenate(self._frames, axis=-1)
            self._frames = None
        return self._out

    def __array__(self, dtype=None, copy=None):
        out = self._force()
        return out.astype(dtype) if dtype is not None else out

    def __len__(self):
        return len(self._force())

    @property
    def shape(self):
        f = self._frames
        if f is None:
            return self._out.shape
        return f[0].shape[:-1] + (f[0].shape[-1] * len(f),)


class FrameStack(gym.Wrapper):
    """Stack the last k observations as a LazyFrames (reference: wrapper.py:160-205)."""

    def __init__(self, env, k: int = 4):
        super().__init__(env)
        self.k = k
        self.frames: deque = deque(maxlen=k)
        shp = env.observation_space.shape
        self.observation_space = gym.spaces.Box(
            0, 255, shp[:-1] + (shp[-1] * k,), np.uint8)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        for _ in range(self.k):
            self.frames.append(obs)
        return self._get_ob(), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self.frames.append(obs)
        return self._get_ob(), reward, terminated, truncated, info

    def _get_ob(self):
        assert len(self.frames) == self.k
        return LazyFrames(list(self.frames))


class TimeLimit(gym.Wrapper):
    """Truncate after ``max_episode_steps`` (reference: wrapper.py:282-298)."""

    def __init__(self, env, max_episode_steps: int):
        super().__init__(env)
        self._max = max_episode_steps
        self._elapsed = 0

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self._elapsed += 1
        if self._elapsed >= self._max:
            truncated = True
        return obs, reward, terminated, truncated, info

    def reset(self, **kwargs):
        self._elapsed = 0
        return self.env.reset(**kwargs)
