"""Pallas TPU kernel for the replay frame-stack gather.

The hottest data movement in the fused learner step is sample-time stack
reconstruction (:meth:`apex_tpu.replay.frame_pool.FramePoolReplay.sample`):
``2 * B * S`` random rows of the HBM frame ring — for the reference config
(B=512, S=4, 84x84 frames) ~29MB of data-dependent gather per step.  XLA
lowers ``frames[ids]`` to a generic dynamic-gather; this kernel instead
streams each row with an explicit double-buffered DMA driven by
scalar-prefetched indices (the embedding-lookup pattern from the pallas
guide): the row ids land in SMEM before the kernel body runs, so every
grid step issues its next row fetch while the previous one is in flight,
and the row bytes move HBM -> VMEM exactly once.

The kernel is TPU-only; :func:`gather_rows` dispatches on the platform of
the ``frames`` buffer — ``jnp.take`` everywhere else (CPU CI, the virtual
mesh) — and parity is pinned by ``tests/test_gather.py`` in interpret mode.

Mosaic constrains DMA slices of 2-D buffers to (8, 128)-tile boundaries, so
single-row slices of ``[F, D]`` only lower when each row is itself a whole
number of tiles: rows must span a multiple of ``ROW_UNIT = 8 * 128``
elements.  :class:`~apex_tpu.replay.frame_pool.FramePoolReplay` pads its
ring rows to this unit for pixel frames (84x84 -> 7168, +1.6%); the kernel
then views the ring as ``[F, 8, D/8]`` and slices dim 0, which carries no
tiling constraint.  Ineligible layouts (tiny vector obs, odd dtypes) fall
back to ``jnp.take`` in auto mode.

Reference analogue: the torch side pays this cost in
``_encode_sample``'s host-side ``np.stack`` of LazyFrames
(``memory.py:348-362``) — per-sample Python decompression on the replay
host.  Here it is one compiled device op either way; the kernel removes
XLA's gather overhead on top.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# one (8, 128) tile, in elements: the row-size quantum the kernel needs
ROW_UNIT = 8 * 128

# rows DMA'd per grid step (row count padded up to a multiple): enough
# in-flight transfers to amortize per-row DMA latency; the VMEM out block
# stays small (32 * 7168B = 229KB for Atari rows)
_GROUP = 32


def _gather_kernel(ids_ref, frames_ref, out_ref, sems):
    """One grid step DMAs _GROUP rows HBM->VMEM: start all, then drain, so
    the row-fetch latencies overlap each other, and Mosaic's grid pipeline
    overlaps this step's fetches with the previous block's writeback.
    Refs are 3-D ``[rows, 8, D/8]`` — the sliced dim sits outside the
    (8, 128)-tiled trailing pair, so single-row slices lower cleanly
    (slicing a 2-D ``[F, D]`` ref one row at a time does not: Mosaic
    requires tile-aligned slices in the trailing two dims)."""
    i = pl.program_id(0)
    copies = []
    for j in range(_GROUP):
        row = ids_ref[i * _GROUP + j]
        cp = pltpu.make_async_copy(
            frames_ref.at[pl.ds(row, 1)],
            out_ref.at[pl.ds(j, 1)],
            sems.at[j])
        cp.start()
        copies.append(cp)
    for cp in copies:
        cp.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_gather(frames3: jax.Array, ids: jax.Array,
                   interpret: bool = False) -> jax.Array:
    """``frames3`` MUST already be the tiled 3-D view ``[F, 8, D/8]`` —
    reshaping a 2-D ring inside the same jit makes XLA materialize a copy
    of the whole ring as the custom-call operand, which costs more than the
    gather itself.  FramePoolReplay therefore STORES its ring 3-D."""
    n, (f, _, c) = ids.shape[0], frames3.shape
    pad = (-n) % _GROUP
    ids_padded = jnp.pad(ids, (0, pad))         # extra rows cut off below
    grid = (ids_padded.shape[0] // _GROUP,)
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],  # ring in HBM
            out_specs=pl.BlockSpec((_GROUP, 8, c),
                                   lambda i, ids: (i, 0, 0)),
            scratch_shapes=[pltpu.SemaphoreType.DMA((_GROUP,))],
        ),
        out_shape=jax.ShapeDtypeStruct((ids_padded.shape[0], 8, c),
                                       frames3.dtype),
        interpret=interpret,
    )(ids_padded, frames3)
    return out.reshape(-1, 8 * c)[:n]


def _on_tpu(x: jax.Array) -> bool:
    try:
        return list(x.devices())[0].platform == "tpu"
    except Exception:        # tracers under jit: ask the default backend
        return jax.default_backend() == "tpu"


def pallas_eligible(d: int, dtype) -> bool:
    """Row layouts the TPU kernel can slice: whole (8, 128) tiles.
    FramePoolReplay pads pixel rows to satisfy this.  (bf16's (16, 128)
    native tile doesn't fit the 8-sublane row view — frames are u8/f32.)"""
    return d % ROW_UNIT == 0 and jnp.dtype(dtype).itemsize in (1, 4)


def resolved_mode(frames: jax.Array, mode: str = "auto") -> str:
    """The concrete path :func:`gather_rows` will take for this operand —
    ``pallas`` | ``interpret`` | ``xla`` — with the ``APEX_GATHER_MODE``
    operational override applied.  Benches report this so a silent
    fallback is visible in the recorded JSON."""
    if mode != "auto":
        return mode
    forced = os.environ.get("APEX_GATHER_MODE")
    if forced not in (None, "", "auto"):
        if forced not in ("pallas", "interpret", "xla"):
            raise ValueError(
                f"APEX_GATHER_MODE={forced!r}: expected pallas | "
                f"interpret | xla | auto")
        return forced
    d = math.prod(frames.shape[1:])
    return ("pallas" if frames.ndim == 3 and _on_tpu(frames)
            and pallas_eligible(d, frames.dtype) else "xla")


def gather_rows(frames: jax.Array, ids: jax.Array,
                mode: str = "auto") -> jax.Array:
    """Row gather from a frame ring; returns flat rows ``[N, D]``.

    ``frames`` is either the flat ring ``[F, D]`` or the tiled 3-D view
    ``[F, 8, D/8]`` the pallas kernel needs (what FramePoolReplay stores
    for pixel frames).  mode: ``auto`` = pallas kernel on TPU for tiled
    eligible rings, ``jnp.take`` elsewhere; ``pallas`` / ``interpret`` /
    ``xla`` force a path (tests, benches).
    """
    d = math.prod(frames.shape[1:])
    mode = resolved_mode(frames, mode)
    if mode in ("pallas", "interpret"):
        if d % 8:
            raise ValueError(
                f"pallas gather needs row dim % 8 == 0, got {d}")
        f3 = (frames if frames.ndim == 3
              else frames.reshape(frames.shape[0], 8, d // 8))
        return _pallas_gather(f3, ids, interpret=(mode == "interpret"))
    return jnp.take(frames, ids, axis=0).reshape(ids.shape[0], d)
