"""Pallas TPU kernel for the replay frame-stack gather.

The hottest data movement in the fused learner step is sample-time stack
reconstruction (:meth:`apex_tpu.replay.frame_pool.FramePoolReplay.sample`):
``2 * B * S`` random rows of the HBM frame ring — for the reference config
(B=512, S=4, 84x84 frames) ~29MB of data-dependent gather per step.  XLA
lowers ``frames[ids]`` to a generic dynamic-gather; this kernel instead
streams rows through Mosaic's own grid pipeline driven by scalar-prefetched
indices (the embedding-lookup pattern from the pallas guide): the row ids
land in SMEM before the kernel body runs, the input BlockSpec's
``index_map`` reads ``ids[i]`` to pick each grid step's source row, and
Mosaic double-buffers the row DMAs — fetching step ``i+1``'s row while
step ``i`` writes back.

History: the first version of this kernel hand-rolled the DMAs
(``make_async_copy`` with a per-row semaphore array).  It passed interpret
parity and a round-3 standalone on-chip run, then on the round-4 live chip
it HUNG — and an orphaned on-device DMA wait wedges the device for every
subsequent client, which is the worst failure mode a replay-path op can
have.  This rewrite delegates all DMA scheduling/semaphores to Mosaic's
pipeline machinery precisely to remove that class of deadlock; the
hand-rolled grouping is gone until the simple form is proven on hardware.

The kernel is TPU-only and strictly OPT-IN until it has a clean on-chip
record (see :func:`resolved_mode`): ``gather_mode="pallas"`` on the replay
spec, or the process-global ``APEX_GATHER_MODE=pallas`` — which still
gates per-operand on layout eligibility.  Everything else (CPU CI, the
virtual mesh, un-opted TPU runs) takes ``jnp.take``; parity is pinned by
``tests/test_gather.py`` in interpret mode.

Mosaic constrains DMA slices of 2-D buffers to (8, 128)-tile boundaries, so
single-row slices of ``[F, D]`` only lower when each row is itself a whole
number of tiles: rows must span a multiple of ``ROW_UNIT = 8 * 128``
elements.  :class:`~apex_tpu.replay.frame_pool.FramePoolReplay` pads its
ring rows to this unit for pixel frames (84x84 -> 7168, +1.6%); the kernel
then views the ring as ``[F, 8, D/8]`` and blocks dim 0, which carries no
tiling constraint.

Reference analogue: the torch side pays this cost in
``_encode_sample``'s host-side ``np.stack`` of LazyFrames
(``memory.py:348-362``) — per-sample Python decompression on the replay
host.  Here it is one compiled device op either way; the kernel removes
XLA's gather overhead on top.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# one (8, 128) tile, in elements: the row-size quantum the kernel needs
ROW_UNIT = 8 * 128


def _gather_kernel(ids_ref, in_ref, out_ref):
    """Per grid step: one gathered row, already staged into VMEM by the
    pipeline (the in_spec's index_map chose the source row from the
    prefetched ids).  The body is a plain VMEM copy; all DMA issue/wait
    is Mosaic's."""
    del ids_ref
    out_ref[...] = in_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_gather(frames3: jax.Array, ids: jax.Array,
                   interpret: bool = False) -> jax.Array:
    """``frames3`` MUST already be the tiled 3-D view ``[F, 8, D/8]`` —
    reshaping a 2-D ring inside the same jit makes XLA materialize a copy
    of the whole ring as the custom-call operand, which costs more than the
    gather itself.  FramePoolReplay therefore STORES its ring 3-D."""
    n, c = ids.shape[0], frames3.shape[2]
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[pl.BlockSpec((1, 8, c),
                                   lambda i, ids: (ids[i], 0, 0))],
            out_specs=pl.BlockSpec((1, 8, c), lambda i, ids: (i, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((n, 8, c), frames3.dtype),
        interpret=interpret,
    )(ids, frames3)
    return out.reshape(n, 8 * c)


def pallas_eligible(d: int, dtype) -> bool:
    """Row layouts the TPU kernel can slice: whole (8, 128) tiles.
    FramePoolReplay pads pixel rows to satisfy this.  (bf16's (16, 128)
    native tile doesn't fit the 8-sublane row view — frames are u8/f32.)"""
    return d % ROW_UNIT == 0 and jnp.dtype(dtype).itemsize in (1, 4)


def resolved_mode(frames: jax.Array, mode: str = "auto") -> str:
    """The concrete path :func:`gather_rows` will take for this operand —
    ``pallas`` | ``interpret`` | ``xla`` — with the ``APEX_GATHER_MODE``
    operational override applied.  Benches report this so a silent
    fallback is visible in the recorded JSON.

    ``auto`` currently resolves to ``xla`` EVERYWHERE, including eligible
    TPU layouts: the round-4 live run proved a misbehaving gather kernel
    doesn't just fail, it can wedge the whole device for every later
    client (module docstring).  Until the rewritten kernel has a clean
    on-chip record, the kernel path is strictly opt-in —
    ``APEX_GATHER_MODE=pallas`` or an explicit ``gather_mode="pallas"`` —
    and ``bench.py`` attempts that opt-in LAST, after the safe numbers
    are recorded."""
    if mode != "auto":
        if mode == "pallas":
            # explicit API opt-in gets the same per-operand eligibility
            # gate as the env override — but loudly: the caller named the
            # kernel path, so an unsliceable layout is a usage error
            # worth a clear message, not a Mosaic lowering traceback (and
            # not a silent xla swap that would misreport what's being
            # measured).  ``interpret`` stays permissive down to the
            # d % 8 row-view check in :func:`gather_rows` — it is the CPU
            # emulation lane and deliberately parity-tests layouts the
            # chip would reject.
            d = math.prod(frames.shape[1:])
            if not (frames.ndim == 3 and pallas_eligible(d, frames.dtype)):
                raise ValueError(
                    f"gather_mode='pallas' needs the tiled 3-D ring view "
                    f"[F, 8, D/8] with rows of whole (8, 128) tiles "
                    f"(D % {ROW_UNIT} == 0) and a 1- or 4-byte dtype; "
                    f"got shape {frames.shape} dtype {frames.dtype}. "
                    f"Use 'xla' (or 'auto') for this layout.")
        return mode
    forced = os.environ.get("APEX_GATHER_MODE")
    if forced not in (None, "", "auto"):
        if forced not in ("pallas", "interpret", "xla"):
            raise ValueError(
                f"APEX_GATHER_MODE={forced!r}: expected pallas | "
                f"interpret | xla | auto")
        if forced in ("pallas", "interpret"):
            # the env opt-in is process-GLOBAL but eligibility is
            # per-OPERAND: a process can hold both an eligible pixel ring
            # (stored 3-D) and a small vector pool (2-D, rows not whole
            # tiles) — the latter must quietly keep the XLA path rather
            # than hand Mosaic an unsliceable layout (interpret gets the
            # same gate so a CPU parity lane behaves like the chip would)
            d = math.prod(frames.shape[1:])
            if not (frames.ndim == 3 and pallas_eligible(d, frames.dtype)):
                return "xla"
        return forced
    return "xla"


def gather_rows(frames: jax.Array, ids: jax.Array,
                mode: str = "auto") -> jax.Array:
    """Row gather from a frame ring; returns flat rows ``[N, D]``.

    ``frames`` is either the flat ring ``[F, D]`` or the tiled 3-D view
    ``[F, 8, D/8]`` the pallas kernel needs (what FramePoolReplay stores
    for pixel frames).  mode: ``auto`` currently resolves to ``jnp.take``
    everywhere unless ``APEX_GATHER_MODE`` overrides (see
    :func:`resolved_mode` for why); ``pallas`` / ``interpret`` / ``xla``
    force a path (tests, benches, opted-in production).
    """
    d = math.prod(frames.shape[1:])
    mode = resolved_mode(frames, mode)
    if mode in ("pallas", "interpret"):
        if d % 8:
            raise ValueError(
                f"pallas gather needs row dim % 8 == 0, got {d}")
        f3 = (frames if frames.ndim == 3
              else frames.reshape(frames.shape[0], 8, d // 8))
        return _pallas_gather(f3, ids, interpret=(mode == "interpret"))
    return jnp.take(frames, ids, axis=0).reshape(ids.shape[0], d)
