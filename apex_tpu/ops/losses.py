"""Loss and update rules as pure functions.

Parity with the reference's ``utils.compute_loss`` (``utils.py:64-81``) and
``update_parameters`` (``utils.py:84-97``):

* n-step double-DQN TD target: online net argmax picks the action, target net
  evaluates it (``utils.py:71-74``).
* Huber (delta=1) elementwise, weighted by IS weights, mean-reduced
  (``utils.py:79-80``).
* Replay priorities via the mixed-max heuristic
  ``0.9*max(|td|) + 0.1*|td| + 1e-6`` (``utils.py:77``).
* Gradient clipping by global norm (max_norm=40, ``arguments.py:65-66``) and
  centered RMSprop (``ApeX.py:37``) — composed as one optax chain so the whole
  update fuses into the learner's XLA step.

Unlike the reference, which runs THREE forward passes (online(s), online(s'),
target(s') — ``utils.py:67-69``), we fold online(s) and online(s') into one
batched pass over concatenated states: fewer, larger MXU matmuls.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class TDOutput(NamedTuple):
    loss: jax.Array          # scalar
    td_abs: jax.Array        # (B,) |TD error|
    priorities: jax.Array    # (B,) mixed-max heuristic priorities
    q_taken: jax.Array       # (B,) Q(s0, a0) — mean logged as learner/q


class AQLOutput(NamedTuple):
    loss: jax.Array
    td_abs: jax.Array
    priorities: jax.Array
    q_taken: jax.Array
    best_idx: jax.Array      # (B,) argmax candidate of the CURRENT state —
                             # the proposal loss target, returned here so the
                             # update never re-scores the candidate set


def huber(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Elementwise Huber written exactly as the reference's branchless form
    (``utils.py:79``)."""
    absx = jnp.abs(x)
    return jnp.where(absx < delta, 0.5 * x * x, delta * (absx - 0.5 * delta))


# max/mean (or max/per-item) priority mix weight — ONE constant shared by
# the batch-level heuristic below, the sequence loss (r2d2_loss), and the
# acting-time sequence priorities (training/r2d2.py:SequenceBuilder) so
# learner write-back and actor inserts can't drift onto different mixes
PRIORITY_ETA = 0.9


def mixed_max_priorities(td_abs: jax.Array, eps: float = 1e-6) -> jax.Array:
    return (PRIORITY_ETA * td_abs.max()
            + (1.0 - PRIORITY_ETA) * td_abs + eps)


def double_dqn_loss(
    apply_fn: Callable[..., jax.Array],
    params: Any,
    target_params: Any,
    batch: dict[str, jax.Array],
    weights: jax.Array,
) -> tuple[jax.Array, TDOutput]:
    """IS-weighted n-step double-DQN Huber loss.

    ``batch['reward']`` is the pre-accumulated n-step return,
    ``batch['next_obs']`` the bootstrap state, and ``batch['discount']`` the
    per-transition bootstrap coefficient (the actor-side accumulator builds
    all three, mirroring ``memory.py:415-440``): ``gamma ** n`` for full
    windows (``utils.py:74``), ``gamma ** k`` for truncated tails, and ``0``
    at true terminals — replacing the reference's ``gamma ** n * (1 - done)``
    with truncation-correct bootstrapping.
    """
    obs, next_obs = batch["obs"], batch["next_obs"]
    both = jnp.concatenate([obs, next_obs], axis=0)
    q_both = apply_fn(params, both)
    q_values, next_q_values = jnp.split(q_both, 2, axis=0)
    tgt_next_q_values = apply_fn(target_params, next_obs)

    actions = batch["action"].astype(jnp.int32)
    q_taken = jnp.take_along_axis(q_values, actions[:, None], axis=1)[:, 0]
    next_actions = next_q_values.argmax(axis=1)
    next_q_taken = jnp.take_along_axis(
        tgt_next_q_values, next_actions[:, None], axis=1)[:, 0]

    target = batch["reward"] + batch["discount"] * next_q_taken
    td = jax.lax.stop_gradient(target) - q_taken
    td_abs = jnp.abs(td)

    loss = (huber(td) * weights).mean()
    return loss, TDOutput(loss=loss, td_abs=td_abs,
                          priorities=mixed_max_priorities(td_abs),
                          q_taken=q_taken)


def r2d2_loss(
    apply_fn: Callable[..., tuple],
    params: Any,
    target_params: Any,
    batch: dict[str, jax.Array],
    weights: jax.Array,
    *,
    burn_in: int,
    n_steps: int,
    eta: float = PRIORITY_ETA,
    eps: float = 1e-6,
) -> tuple[jax.Array, TDOutput]:
    """Sequence double-DQN loss for the recurrent family (R2D2 recipe on
    the reference's TD conventions).

    ``apply_fn(params, obs_seq [B, L, *obs], carry) -> (q [B, L, A],
    carry)`` is the recurrent network.  ``batch``: ``obs [B, T, *obs]``,
    ``action``/``reward`` ``[B, T]``, ``discount [B, T]`` =
    ``gamma * (1 - done)`` per STEP (0 at terminals — padded steps also
    carry 0, so n-step products truncate naturally), ``mask [B, T]`` = 1
    on real steps, ``state_c``/``state_h`` ``[B, H]`` — the actor's
    recurrent state at sequence start (R2D2 stored-state).  Sequence
    geometry: ``T = burn_in + unroll + n_steps``; the loss covers the
    ``unroll`` positions after burn-in.

    Burn-in: both nets unroll the prefix from the stored state and the
    resulting carries are ``stop_gradient``-ed — the prefix only warms
    the state, contributing no gradient and no loss terms.

    Per-sequence priorities use R2D2's mix ``eta * max_t |td| +
    (1 - eta) * mean_t |td|`` — the sequence analogue of the reference's
    mixed-max heuristic (``utils.py:77``).
    """
    obs = batch["obs"]
    t_total = obs.shape[1]
    unroll = t_total - burn_in - n_steps
    if unroll < 1:
        raise ValueError(
            f"sequence length {t_total} too short for burn_in={burn_in} "
            f"+ n_steps={n_steps} + at least one unroll step")

    carry0 = (batch["state_c"], batch["state_h"])
    if burn_in:
        _, carry_on = apply_fn(params, obs[:, :burn_in], carry0)
        _, carry_tg = apply_fn(target_params, obs[:, :burn_in], carry0)
        carry_on = jax.lax.stop_gradient(carry_on)
        carry_tg = jax.lax.stop_gradient(carry_tg)
    else:
        carry_on = carry_tg = carry0

    body = obs[:, burn_in:]                       # [B, unroll + n, *obs]
    q_seq, _ = apply_fn(params, body, carry_on)   # [B, unroll + n, A]
    qt_seq, _ = apply_fn(target_params, body, carry_tg)

    r = batch["reward"][:, burn_in:]
    d = batch["discount"][:, burn_in:]
    m = batch["mask"][:, burn_in:]

    # n-step returns per unroll position; discount 0 at terminals/padding
    # truncates every product past end-of-episode
    returns = jnp.zeros(r.shape[:1] + (unroll,), jnp.float32)
    disc_prod = jnp.ones_like(returns)
    for i in range(n_steps):
        returns = returns + disc_prod * r[:, i:i + unroll]
        disc_prod = disc_prod * d[:, i:i + unroll]

    next_online = q_seq[:, n_steps:n_steps + unroll]
    next_target = qt_seq[:, n_steps:n_steps + unroll]
    a_star = next_online.argmax(axis=-1)
    bootstrap = jnp.take_along_axis(next_target, a_star[..., None],
                                    axis=-1)[..., 0]
    target = returns + disc_prod * bootstrap

    actions = batch["action"][:, burn_in:burn_in + unroll].astype(jnp.int32)
    q_taken = jnp.take_along_axis(q_seq[:, :unroll], actions[..., None],
                                  axis=-1)[..., 0]
    td = jax.lax.stop_gradient(target) - q_taken
    lmask = m[:, :unroll]
    n_valid = jnp.maximum(lmask.sum(axis=1), 1.0)

    loss = ((huber(td) * lmask).sum(axis=1) / n_valid * weights).mean()

    td_abs = jnp.abs(td) * lmask
    seq_max = td_abs.max(axis=1)
    seq_mean = td_abs.sum(axis=1) / n_valid
    priorities = eta * seq_max + (1.0 - eta) * seq_mean + eps
    q_mean = (q_taken * lmask).sum(axis=1) / n_valid
    return loss, TDOutput(loss=loss, td_abs=seq_mean,
                          priorities=priorities, q_taken=q_mean)


def make_optimizer(lr: float = 6.25e-5, decay: float = 0.95,
                   eps: float = 1.5e-7, centered: bool = True,
                   max_grad_norm: float = 40.0,
                   lr_decay_steps: int | None = 1000,
                   lr_decay_rate: float = 0.99) -> optax.GradientTransformation:
    """Clip-then-RMSprop chain matching ``ApeX.py:37`` + ``utils.py:95``,
    with the single-host drivers' ``StepLR(step_size=1000, gamma=0.99)``
    reproduced as a staircase exponential decay (``DQN.py:39,71``,
    ``ApeX.py:38,60``): lr(step) = lr * rate^(step // steps), stepped once
    per optimizer update exactly like ``scheduler.step()`` per learner
    iteration.  ``lr_decay_steps=0``/``None`` = constant lr (the
    reference's distributed learner, ``origin_repo/learner.py:145``)."""
    schedule = (optax.exponential_decay(lr, lr_decay_steps, lr_decay_rate,
                                        staircase=True)
                if lr_decay_steps else lr)
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.rmsprop(schedule, decay=decay, eps=eps, centered=centered),
    )


# -- AQL (proposal-action Q-learning) --------------------------------------

def aql_q_loss(
    score_fn: Callable[..., jax.Array],
    params: Any,
    target_params: Any,
    batch: dict[str, jax.Array],
    weights: jax.Array,
    online_noise: jax.Array,
    target_noise: jax.Array,
) -> tuple[jax.Array, AQLOutput]:
    """Double-DQN TD loss over the stored candidate set (reference
    ``compute_loss_AQL``, ``utils.py:44-61``).

    ``batch['action']`` is the INDEX into ``batch['a_mu'] [B, T, A]``; both
    current and next state are scored against the SAME stored candidate set
    (the reference reuses the transition's ``a_mu`` for ``next_states`` too,
    ``utils.py:47-49`` — by design: the set that produced the acted action
    stays the comparison basis).  ``online_noise``/``target_noise`` pin one
    NoisyNet draw per network per update, matching the
    reset-once-per-step buffer semantics (``AQL_dis.py:104-105``).
    """
    obs, next_obs, a_mu = batch["obs"], batch["next_obs"], batch["a_mu"]
    both = jnp.concatenate([obs, next_obs], axis=0)
    a_both = jnp.concatenate([a_mu, a_mu], axis=0)
    q_both = score_fn(params, both, a_both, online_noise)
    q_values, next_q_values = jnp.split(q_both, 2, axis=0)
    tgt_next_q_values = score_fn(target_params, next_obs, a_mu, target_noise)

    idx = batch["action"].astype(jnp.int32)
    q_taken = jnp.take_along_axis(q_values, idx[:, None], axis=1)[:, 0]
    next_idx = next_q_values.argmax(axis=1)
    next_q_taken = jnp.take_along_axis(
        tgt_next_q_values, next_idx[:, None], axis=1)[:, 0]

    target = batch["reward"] + batch["discount"] * next_q_taken
    td = jax.lax.stop_gradient(target) - q_taken
    td_abs = jnp.abs(td)
    loss = (huber(td) * weights).mean()
    return loss, AQLOutput(loss=loss, td_abs=td_abs,
                           priorities=mixed_max_priorities(td_abs),
                           q_taken=q_taken,
                           best_idx=jax.lax.stop_gradient(
                               q_values.argmax(axis=1)))


def aql_proposal_loss(
    log_prob_fn: Callable[..., tuple[jax.Array, jax.Array]],
    params: Any,
    batch: dict[str, jax.Array],
    best_idx: jax.Array,
    entropy_coef: float,
) -> jax.Array:
    """Entropy-regularized NLL of the argmax-Q candidate (reference
    ``AQL_dis.py:79-86``): pull the proposal mean toward the action the Q
    head currently ranks best.  ``best_idx`` comes from the Q pass and is
    treated as data (no gradient through the argmax)."""
    best_action = jnp.take_along_axis(
        batch["a_mu"], best_idx[:, None, None], axis=1)[:, 0, :]
    log_prob, entropy = log_prob_fn(params, batch["obs"],
                                    jax.lax.stop_gradient(best_action))
    return jnp.mean(-log_prob - entropy_coef * entropy)


def aql_param_labels(params: Any) -> Any:
    """'proposal' / 'q' label tree for the two-optimizer split
    (``AQL.py:41-42``).

    The state-embedding trunk belongs to the PROPOSAL group: it feeds only
    the proposal mean (the Q score path reads raw observations through
    ``q_feature``, reference ``model.py:294-320``).  The reference
    accidentally freezes this trunk forever — its Q optimizer owns but never
    gradients it, and its proposal optimizer gradients but never owns it
    (``AQL_dis.py:87-101``).  Training it under the proposal optimizer is a
    deliberate fix, not drift."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: "proposal"
        if any(str(getattr(k, "key", k)).startswith(("proposal", "embed"))
               for k in path) else "q",
        params)


def make_aql_optimizer(q_lr: float = 1e-4, proposal_lr: float = 1e-4,
                       max_grad_norm: float = 40.0,
                       cosine_steps: int | None = None
                       ) -> optax.GradientTransformation:
    """Per-group clip + Adam, split by :func:`aql_param_labels` (reference
    clips and steps the two parameter sets independently,
    ``AQL_dis.py:87-101``, Adam opts ``AQL.py:41-42``).

    ``cosine_steps`` reproduces the reference's
    ``CosineAnnealingLR(T_max=max_step, eta_min=lr/1000)`` on both groups
    (``AQL.py:48-49``; ``max_step`` defaults to 1e6, ``AQL.py:18``);
    ``None``/0 = constant lr (the distributed ``AQL_dis`` path, which
    never constructs schedulers)."""
    def group(lr):
        if cosine_steps:
            lr = cosine_annealing(lr, cosine_steps, lr / 1000.0)
        return optax.chain(optax.clip_by_global_norm(max_grad_norm),
                           optax.adam(lr))
    return optax.multi_transform(
        {"q": group(q_lr), "proposal": group(proposal_lr)},
        aql_param_labels)


def cosine_annealing(lr: float, t_max: int, eta_min: float):
    """torch ``CosineAnnealingLR`` value curve: eta_min + (lr - eta_min) *
    (1 + cos(pi * t / T_max)) / 2, held at eta_min past ``T_max`` (the
    closed form; the reference never steps past max_step)."""
    def schedule(count):
        t = jnp.minimum(count, t_max)
        return eta_min + (lr - eta_min) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * t / t_max))
    return schedule
