"""Loss and update rules as pure functions.

Parity with the reference's ``utils.compute_loss`` (``utils.py:64-81``) and
``update_parameters`` (``utils.py:84-97``):

* n-step double-DQN TD target: online net argmax picks the action, target net
  evaluates it (``utils.py:71-74``).
* Huber (delta=1) elementwise, weighted by IS weights, mean-reduced
  (``utils.py:79-80``).
* Replay priorities via the mixed-max heuristic
  ``0.9*max(|td|) + 0.1*|td| + 1e-6`` (``utils.py:77``).
* Gradient clipping by global norm (max_norm=40, ``arguments.py:65-66``) and
  centered RMSprop (``ApeX.py:37``) — composed as one optax chain so the whole
  update fuses into the learner's XLA step.

Unlike the reference, which runs THREE forward passes (online(s), online(s'),
target(s') — ``utils.py:67-69``), we fold online(s) and online(s') into one
batched pass over concatenated states: fewer, larger MXU matmuls.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class TDOutput(NamedTuple):
    loss: jax.Array          # scalar
    td_abs: jax.Array        # (B,) |TD error|
    priorities: jax.Array    # (B,) mixed-max heuristic priorities
    q_taken: jax.Array       # (B,) Q(s0, a0) — mean logged as learner/q


def huber(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Elementwise Huber written exactly as the reference's branchless form
    (``utils.py:79``)."""
    absx = jnp.abs(x)
    return jnp.where(absx < delta, 0.5 * x * x, delta * (absx - 0.5 * delta))


def mixed_max_priorities(td_abs: jax.Array, eps: float = 1e-6) -> jax.Array:
    return 0.9 * td_abs.max() + 0.1 * td_abs + eps


def double_dqn_loss(
    apply_fn: Callable[..., jax.Array],
    params: Any,
    target_params: Any,
    batch: dict[str, jax.Array],
    weights: jax.Array,
) -> tuple[jax.Array, TDOutput]:
    """IS-weighted n-step double-DQN Huber loss.

    ``batch['reward']`` is the pre-accumulated n-step return,
    ``batch['next_obs']`` the bootstrap state, and ``batch['discount']`` the
    per-transition bootstrap coefficient (the actor-side accumulator builds
    all three, mirroring ``memory.py:415-440``): ``gamma ** n`` for full
    windows (``utils.py:74``), ``gamma ** k`` for truncated tails, and ``0``
    at true terminals — replacing the reference's ``gamma ** n * (1 - done)``
    with truncation-correct bootstrapping.
    """
    obs, next_obs = batch["obs"], batch["next_obs"]
    both = jnp.concatenate([obs, next_obs], axis=0)
    q_both = apply_fn(params, both)
    q_values, next_q_values = jnp.split(q_both, 2, axis=0)
    tgt_next_q_values = apply_fn(target_params, next_obs)

    actions = batch["action"].astype(jnp.int32)
    q_taken = jnp.take_along_axis(q_values, actions[:, None], axis=1)[:, 0]
    next_actions = next_q_values.argmax(axis=1)
    next_q_taken = jnp.take_along_axis(
        tgt_next_q_values, next_actions[:, None], axis=1)[:, 0]

    target = batch["reward"] + batch["discount"] * next_q_taken
    td = jax.lax.stop_gradient(target) - q_taken
    td_abs = jnp.abs(td)

    loss = (huber(td) * weights).mean()
    return loss, TDOutput(loss=loss, td_abs=td_abs,
                          priorities=mixed_max_priorities(td_abs),
                          q_taken=q_taken)


def make_optimizer(lr: float = 6.25e-5, decay: float = 0.95,
                   eps: float = 1.5e-7, centered: bool = True,
                   max_grad_norm: float = 40.0) -> optax.GradientTransformation:
    """Clip-then-RMSprop chain matching ``ApeX.py:37`` + ``utils.py:95``."""
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.rmsprop(lr, decay=decay, eps=eps, centered=centered),
    )
