"""Device-resident segment trees for prioritized replay.

TPU re-design of the reference's pointer-chasing Python trees
(``memory.py:10-143``): the tree is ONE flat ``jnp`` array of length
``2 * capacity`` living in HBM.  Node 1 is the root; node ``i`` has children
``2i`` and ``2i+1``; leaves occupy ``[capacity, 2*capacity)``.  Every operation
is vectorized over a batch of indices and expressed as fixed-depth gather/
scatter loops, so the whole thing traces into a single XLA program — there is
no per-element Python, no locks, and updates for a K-sized batch cost
``O(K log C)`` fully-parallel work.

Semantics match the reference exactly:

* ``update_*`` — leaf write + root-ward recomputation (``memory.py:76-87``).
* ``find_prefixsum_idx`` — iterative descent, descending LEFT when
  ``left_subtree_sum > u`` else RIGHT with ``u -= left_subtree_sum``
  (``memory.py:106-129``).
* ``stratified_sample`` — batch-size strata, one uniform draw per stratum:
  ``u_i = (i + U_i) * total / B`` (``memory.py:242-250``).

Capacity must be a power of 2 (asserted by the reference at ``memory.py:34``;
here it is implied by the array length).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_sum_tree(capacity: int) -> jax.Array:
    _check_capacity(capacity)
    return jnp.zeros(2 * capacity, dtype=jnp.float32)


def init_min_tree(capacity: int) -> jax.Array:
    _check_capacity(capacity)
    return jnp.full(2 * capacity, jnp.inf, dtype=jnp.float32)


def _check_capacity(capacity: int) -> None:
    if capacity <= 0 or capacity & (capacity - 1):
        raise ValueError(f"capacity must be a positive power of 2, got {capacity}")


def capacity_of(tree: jax.Array) -> int:
    return tree.shape[0] // 2


def depth_of(tree: jax.Array) -> int:
    return (tree.shape[0] // 2).bit_length() - 1


def _propagate(tree: jax.Array, leaf_nodes: jax.Array, reduce_op) -> jax.Array:
    """Recompute ancestors of ``leaf_nodes`` level by level.

    Duplicate parents in a level all write the same recomputed value, so
    scatter-set with duplicates is well-defined.  The loop is unrolled at
    trace time (depth = log2(capacity), e.g. 21 for a 2M buffer).
    """
    nodes = leaf_nodes // 2
    for _ in range(depth_of(tree)):
        tree = tree.at[nodes].set(reduce_op(tree[2 * nodes], tree[2 * nodes + 1]))
        nodes = nodes // 2
    return tree


def update_sum(tree: jax.Array, idx: jax.Array, values: jax.Array) -> jax.Array:
    """Set leaves ``idx`` (buffer coordinates, 0-based) to ``values``."""
    leaf = idx + capacity_of(tree)
    tree = tree.at[leaf].set(values.astype(tree.dtype))
    return _propagate(tree, leaf, jnp.add)


def update_min(tree: jax.Array, idx: jax.Array, values: jax.Array) -> jax.Array:
    leaf = idx + capacity_of(tree)
    tree = tree.at[leaf].set(values.astype(tree.dtype))
    return _propagate(tree, leaf, jnp.minimum)


def update_both(sum_tree: jax.Array, min_tree: jax.Array,
                idx: jax.Array, values: jax.Array):
    """Fused sum+min leaf update — one call per priority write
    (reference merges add+update for the same reason, ``memory.py:334-346``)."""
    return update_sum(sum_tree, idx, values), update_min(min_tree, idx, values)


def tree_total(sum_tree: jax.Array) -> jax.Array:
    return sum_tree[1]


def tree_min(min_tree: jax.Array) -> jax.Array:
    return min_tree[1]


def get_leaves(tree: jax.Array, idx: jax.Array) -> jax.Array:
    return tree[idx + capacity_of(tree)]


def find_prefixsum_idx(sum_tree: jax.Array, u: jax.Array) -> jax.Array:
    """Vectorized root-to-leaf descent (reference: ``memory.py:106-129``).

    ``u`` may have any batch shape; returns leaf indices in buffer
    coordinates.  Each level is one gather over the batch; the level loop is
    unrolled at trace time.

    Note: duplicate indices within one batched ``update_*`` call must carry
    equal values (the sampled-batch case: one transition sampled twice gets
    one TD error); distinct values for the same index are scatter-order
    dependent.
    """
    node = jnp.ones(u.shape, dtype=jnp.int32)
    u = u.astype(sum_tree.dtype)
    for _ in range(depth_of(sum_tree)):
        left = sum_tree[2 * node]
        go_right = u >= left
        u = jnp.where(go_right, u - left, u)
        node = 2 * node + go_right.astype(jnp.int32)
    return node - capacity_of(sum_tree)


def stratified_sample(sum_tree: jax.Array, key: jax.Array, batch_size: int,
                      size: jax.Array) -> jax.Array:
    """Proportional stratified sampling (reference: ``memory.py:242-250``).

    Draws one index per stratum ``[i, i+1) * total / B``.  ``size`` (current
    element count) clamps the result so float round-off at stratum boundaries
    can never select an empty leaf.
    """
    total = tree_total(sum_tree)
    offsets = jax.random.uniform(key, (batch_size,), dtype=sum_tree.dtype)
    u = (jnp.arange(batch_size, dtype=sum_tree.dtype) + offsets) * (
        total / batch_size)
    idx = find_prefixsum_idx(sum_tree, u)
    return jnp.clip(idx, 0, jnp.maximum(size - 1, 0))
