"""Sharded serving tier with epoch-fenced canary deployments.

PR 9's inference plane is one process serving one fleet; this package
turns it into a version-controlled serving TIER — the Ape-X
separation-of-concerns argument (arxiv 1803.00933) applied to the
inference side, with arxiv 2111.01264's useful-work-per-box economics
deciding how shards pack onto hosts:

* :mod:`~apex_tpu.serving.shard` — the shard fabric: N infer servers on
  ``infer_port + s``, workers routed by a stable identity hash, each
  shard inheriting PR 9's down-marker/local-fallback/re-probe semantics
  (a dead shard degrades its worker band to bit-identical local acting,
  never to a stall).
* :mod:`~apex_tpu.serving.fence` — the model-version order:
  ``(learner_epoch, param_version)`` lexicographic, the ONE place
  epoch/version comparisons live (apexlint J016 keeps it that way).
* :mod:`~apex_tpu.serving.deploy` — the deployment controller
  (``--role serve-ctl``): new model versions canary onto a shard
  fraction behind the servers' epoch-fenced param gate, promote when
  the eval-ladder score and round-trip SLO hold for a soak window
  (:class:`~apex_tpu.obs.slo.SloEngine` verdicts — PR 11's machinery,
  not a second judge), and roll back BY EPOCH on breach, with the
  bounded deployment timeline surfaced in ``fleet_summary.json``, the
  ``--role status`` table, and ``apex_serving_*`` Prometheus rows.
"""

from apex_tpu.serving.deploy import (DeployController, ServeCtl,
                                     ServingStat, run_serve_ctl)
from apex_tpu.serving.shard import (infer_shard, make_infer_client,
                                    shard_port)

__all__ = ["DeployController", "ServeCtl", "ServingStat", "infer_shard",
           "make_infer_client", "run_serve_ctl", "shard_port"]
